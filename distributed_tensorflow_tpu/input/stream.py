"""Append-only event-log stream source for online training.

The streaming counterpart of the file-based ``Dataset`` sources: a
producer (the *ingestor*) appends length-prefixed records to a single
append-only log file with **monotonic offsets** (record index 0, 1,
2, ...), and a resumable :class:`StreamDataset` consumer tails the log
from any offset — the ``tf.data``-of-a-Kafka-topic shape the online
recommender scenario needs (ROADMAP item 2), built on plain files so
the whole topology runs under the existing chaos harness.

Record format (little-endian)::

    MAGIC(u16) | length(u32) | crc32(u32) | payload bytes

Crash semantics are the same contract the telemetry event logs keep
(telemetry/events.py): a **torn tail** — the unfinished last record of
a SIGKILL'd writer — is expected and invisible to readers (a record is
only yielded once its header, payload, and crc are all intact), while
mid-file damage raises :class:`StreamCorruptError` because the log can
no longer be trusted. A restarted producer opens the log with
:meth:`StreamWriter.open` which **truncates** any torn tail before
appending, so offsets stay contiguous across producer generations.

Exactly-once consumption is the CONSUMER's contract, by construction:
the trainer records its cursor (the next unapplied offset) *inside*
the same atomic checkpoint commit as the model state it fed
(models/online_dlrm.OnlineTrainer), so a killed-and-reformed trainer
replays exactly the records after the last commit — no lost events, no
double-applied events, regardless of where the kill landed between
apply and commit (tests/test_stream.py proves it by killing there).
"""

from __future__ import annotations

import os
import pickle
import struct
import time
import zlib

import numpy as np

#: Record header: magic, payload length, payload crc32.
_MAGIC = 0x5EDA
_HEADER = struct.Struct("<HII")
HEADER_BYTES = _HEADER.size

#: Default log file name inside a stream directory.
LOG_NAME = "stream.log"


class StreamCorruptError(ValueError):
    """The log is damaged BEFORE its final record (torn tails are
    expected from crashed producers; mid-file damage is not)."""


def scan_log(path: str) -> tuple[int, int]:
    """Walk the log once: returns ``(record_count, clean_end_byte)``.

    ``clean_end_byte`` is the byte offset just past the last COMPLETE
    record — a torn tail (truncated header/payload or a crc mismatch on
    the final record) is excluded; damage before the final record
    raises :class:`StreamCorruptError`. ``(0, 0)`` for a missing file.
    """
    try:
        size = os.path.getsize(path)
    except OSError:
        return 0, 0
    count = 0
    pos = 0
    with open(path, "rb") as f:
        while pos + HEADER_BYTES <= size:
            f.seek(pos)
            magic, length, crc = _HEADER.unpack(f.read(HEADER_BYTES))
            if magic != _MAGIC:
                raise StreamCorruptError(
                    f"{path}: bad record magic {magic:#x} at byte {pos} "
                    f"(mid-file corruption)")
            end = pos + HEADER_BYTES + length
            if end > size:
                break                     # torn tail: payload truncated
            payload = f.read(length)
            if zlib.crc32(payload) != crc:
                if end >= size:
                    break                 # torn tail: crc of last record
                raise StreamCorruptError(
                    f"{path}: record {count} at byte {pos} fails its "
                    f"crc32 (mid-file corruption)")
            count += 1
            pos = end
    return count, pos


def count_records(path: str) -> int:
    """Number of complete records in the log (cheap header walk)."""
    return scan_log(path)[0]


class StreamWriter:
    """Append-only producer handle for one log file.

    :meth:`open` is how every producer incarnation starts: it scans the
    existing log, TRUNCATES any torn tail left by a killed predecessor,
    and resumes appending at the next offset — so the log's offsets are
    contiguous and immutable across producer generations (a complete
    record is never rewritten; only a torn, never-readable tail is).
    """

    def __init__(self, path: str, *, _resume: tuple[int, int] = (0, 0)):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self._next_offset, end = _resume
        self._f = open(path, "r+b" if os.path.exists(path) else "w+b")
        self._f.seek(end)
        self._f.truncate(end)

    @classmethod
    def open(cls, path: str) -> "StreamWriter":
        count, end = scan_log(path) if os.path.exists(path) else (0, 0)
        return cls(path, _resume=(count, end))

    @property
    def next_offset(self) -> int:
        return self._next_offset

    def append(self, payload: bytes) -> int:
        """Append one record; returns its offset. The write is a single
        buffered write of header+payload — call :meth:`flush` to make a
        batch of records visible to tailing consumers."""
        rec = _HEADER.pack(_MAGIC, len(payload), zlib.crc32(payload)) \
            + payload
        self._f.write(rec)
        off = self._next_offset
        self._next_offset += 1
        return off

    def append_event(self, event: dict) -> int:
        return self.append(pickle.dumps(event, protocol=4))

    def flush(self):
        self._f.flush()
        # no fsync: torn tails are tolerated by design; durability of
        # the MODEL rides the checkpoint commit protocol, not the log

    def close(self):
        self.flush()
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class StreamReader:
    """Sequential record reader with a resumable cursor.

    ``seek(offset)`` positions before record ``offset`` (a header walk
    from the start — paid once per consumer incarnation);
    ``read_available()`` then yields every COMPLETE record currently in
    the file, advancing the cursor. An incomplete tail simply ends the
    iteration (the producer may still be writing it) — call again after
    the producer flushes more.
    """

    def __init__(self, path: str):
        self.path = path
        self._offset = 0
        self._pos = 0

    @property
    def offset(self) -> int:
        """Next offset this reader will yield."""
        return self._offset

    def seek(self, offset: int):
        """Position before record ``offset``; raises if the log holds
        fewer complete records (the caller asked to resume past the
        end of history)."""
        count, pos = 0, 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            size = 0
        with open(self.path, "rb") if size else _nullfile() as f:
            while count < offset:
                if pos + HEADER_BYTES > size:
                    raise ValueError(
                        f"{self.path}: cannot seek to offset {offset}; "
                        f"log holds only {count} complete record(s)")
                f.seek(pos)
                magic, length, _crc = _HEADER.unpack(f.read(HEADER_BYTES))
                if magic != _MAGIC:
                    raise StreamCorruptError(
                        f"{self.path}: bad magic at byte {pos}")
                end = pos + HEADER_BYTES + length
                if end > size:
                    raise ValueError(
                        f"{self.path}: cannot seek to offset {offset}; "
                        f"log holds only {count} complete record(s)")
                count += 1
                pos = end
        self._offset, self._pos = offset, pos

    def read_available(self):
        """Yield ``(offset, payload_bytes)`` for every complete record
        from the cursor to the current end of file."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return
        if size <= self._pos:
            return
        with open(self.path, "rb") as f:
            while self._pos + HEADER_BYTES <= size:
                f.seek(self._pos)
                magic, length, crc = _HEADER.unpack(f.read(HEADER_BYTES))
                if magic != _MAGIC:
                    raise StreamCorruptError(
                        f"{self.path}: bad record magic at byte "
                        f"{self._pos}")
                end = self._pos + HEADER_BYTES + length
                if end > size:
                    return                # tail still being written
                payload = f.read(length)
                if zlib.crc32(payload) != crc:
                    if end >= size:
                        return            # torn final record
                    raise StreamCorruptError(
                        f"{self.path}: record {self._offset} fails "
                        f"crc32")
                off = self._offset
                self._offset += 1
                self._pos = end
                yield off, payload


class _nullfile:
    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class StreamDataset:
    """Resumable tailing consumer over one event log.

    Yields ``(offset, event_dict)`` in offset order starting at
    ``start_offset``, polling the file for new records (the producer
    may still be appending). Iteration ends when ``end_offset`` records
    have been yielded, or after ``idle_timeout_s`` with no new data
    (producer gone) — whichever is configured.
    """

    def __init__(self, path: str, *, start_offset: int = 0,
                 poll_s: float = 0.05):
        self.path = path
        self.start_offset = start_offset
        self.poll_s = poll_s

    def events(self, *, end_offset: int | None = None,
               idle_timeout_s: float | None = None):
        if end_offset is not None and self.start_offset >= end_offset:
            return                      # already consumed to the end
        reader = StreamReader(self.path)
        if self.start_offset:
            # resume cursor: the log may not hold our offset yet (a
            # reformed trainer can come back before the reformed
            # producer re-appends) — wait for it
            deadline = (time.monotonic() + idle_timeout_s
                        if idle_timeout_s else None)
            while True:
                try:
                    reader.seek(self.start_offset)
                    break
                except ValueError:
                    if deadline and time.monotonic() > deadline:
                        return
                    time.sleep(self.poll_s)
        idle_since = time.monotonic()
        while True:
            got = False
            for off, payload in reader.read_available():
                got = True
                idle_since = time.monotonic()
                yield off, pickle.loads(payload)
                if end_offset is not None and off + 1 >= end_offset:
                    return
            if not got:
                if (idle_timeout_s is not None
                        and time.monotonic() - idle_since
                        > idle_timeout_s):
                    return
                time.sleep(self.poll_s)

    def __iter__(self):
        return self.events()


# ---------------------------------------------------------------------------
# Seeded synthetic recommendation events (the millions-of-users shape:
# Zipf-distributed user/item ids over a universe far larger than any
# embedding table, so admission/eviction actually have work to do).
# ---------------------------------------------------------------------------

def seeded_events(seed: int, start: int, n: int, *,
                  n_users: int = 50_000, n_items: int = 10_000,
                  n_dense: int = 4, zipf_a: float = 1.2) -> dict:
    """One deterministic chunk of ``n`` events for offsets
    ``start..start+n-1``: a dict of arrays (``user``, ``item``,
    ``dense``, ``label``). Determinism is per (seed, start): the chunk
    is a pure function of its boundaries, and the LOG is the source of
    truth once written (a restarted producer resumes at the log's end,
    so already-written records are never regenerated)."""
    rng = np.random.default_rng([seed, start])
    user = (rng.zipf(zipf_a, size=n) - 1) % n_users
    item = (rng.zipf(zipf_a, size=n) - 1) % n_items
    dense = rng.normal(size=(n, n_dense)).astype(np.float32)
    score = dense.mean(1) + 0.3 * np.cos((user + item).astype(np.float64))
    label = (score > 0).astype(np.int32)
    return {"user": user.astype(np.int64), "item": item.astype(np.int64),
            "dense": dense, "label": label}


def append_chunk(writer: StreamWriter, chunk: dict) -> int:
    """Append one :func:`seeded_events` chunk as individual records;
    returns the next offset after the chunk. Flushes once at the end so
    consumers observe whole chunks."""
    n = len(chunk["label"])
    for i in range(n):
        writer.append_event({
            "user": int(chunk["user"][i]),
            "item": int(chunk["item"][i]),
            "dense": chunk["dense"][i],
            "label": int(chunk["label"][i]),
        })
    writer.flush()
    return writer.next_offset
