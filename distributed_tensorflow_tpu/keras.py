"""tf.keras-shaped namespace — the source-compat façade.

≙ the tf_keras package surface the reference's training scripts import
(TFK/src/engine/training.py Model, TFK/src/layers/, TFK/src/optimizers/,
TFK/src/losses.py, TFK/src/callbacks.py). A reference script migrates by
swapping its import line::

    # reference:                      # this framework:
    import tf_keras as keras          from distributed_tensorflow_tpu \
                                          import keras

and keeping everything else — Sequential/layers construction inside
``strategy.scope()``, ``model.compile(optimizer=..., loss=...,
metrics=[...])``, ``model.fit/evaluate/predict`` — verbatim
(examples/train_mnist_keras_script.py is the proof script).

Weight layouts equal tf_keras's (tests/test_reference_parity pins the
conv/dense layouts), so ``get_weights``/``set_weights`` round-trip with
real tf_keras models.
"""

from __future__ import annotations

import optax as _optax

from distributed_tensorflow_tpu.training import callbacks
from distributed_tensorflow_tpu.training import layers
from distributed_tensorflow_tpu.training import losses
from distributed_tensorflow_tpu.training import metrics
from distributed_tensorflow_tpu.training import regularizers
from distributed_tensorflow_tpu.training.functional import Input, Model
from distributed_tensorflow_tpu.training.layers import Sequential


class _Optimizers:
    """≙ tf_keras.optimizers — constructors returning optax transforms
    (wrapped in inject_hyperparams so LearningRateScheduler works).
    ``learning_rate`` may be a float OR a ``schedules.*`` object —
    inject_hyperparams re-evaluates callables per optimizer step, the
    keras per-step schedule semantics."""

    from distributed_tensorflow_tpu.training import schedules

    @staticmethod
    def SGD(learning_rate=0.01, momentum: float = 0.0):
        return _optax.inject_hyperparams(_optax.sgd)(
            learning_rate=learning_rate, momentum=momentum)

    @staticmethod
    def Adam(learning_rate=1e-3, b1: float = 0.9, b2: float = 0.999):
        return _optax.inject_hyperparams(_optax.adam)(
            learning_rate=learning_rate, b1=b1, b2=b2)

    @staticmethod
    def AdamW(learning_rate=1e-3, weight_decay: float = 1e-4):
        return _optax.inject_hyperparams(_optax.adamw)(
            learning_rate=learning_rate, weight_decay=weight_decay)

    @staticmethod
    def RMSprop(learning_rate=1e-3):
        return _optax.inject_hyperparams(_optax.rmsprop)(
            learning_rate=learning_rate)


optimizers = _Optimizers()


class _Models:
    """≙ tf_keras.models — whole-model persistence + aliases."""

    Model = Model
    Sequential = Sequential

    @staticmethod
    def load_model(filepath: str):
        from distributed_tensorflow_tpu.training.saving import load_model
        return load_model(filepath)

    @staticmethod
    def save_model(model, filepath: str):
        from distributed_tensorflow_tpu.training.saving import save_model
        save_model(model, filepath)


models = _Models()


class _Utils:
    """≙ tf_keras.utils — the helpers reference scripts actually call."""

    @staticmethod
    def to_categorical(y, num_classes=None, dtype="float32"):
        import numpy as np
        y = np.asarray(y, dtype="int64")
        shape = y.shape
        flat = y.reshape(-1)
        n = int(num_classes) if num_classes else int(flat.max()) + 1
        out = np.zeros((flat.shape[0], n), dtype=dtype)
        out[np.arange(flat.shape[0]), flat] = 1
        return out.reshape(*shape, n)       # keras: input shape + (C,)

    @staticmethod
    def set_random_seed(seed: int):
        import random

        import numpy as np
        random.seed(seed)
        np.random.seed(seed)

    @staticmethod
    def plot_model(model, *a, **kw):
        raise NotImplementedError(
            "plot_model needs graphviz; use model.summary() instead")


utils = _Utils()

__all__ = ["layers", "losses", "metrics", "callbacks", "optimizers",
           "models", "utils", "regularizers", "Model", "Sequential",
           "Input"]
