"""Model.compile / fit / evaluate / predict on a Strategy.

≙ the reference's Keras training-loop layer (tf_keras/src/engine/
training.py: fit :1453, make_train_function :1338, train_step :1118) —
the L7 layer SURVEY.md §1 maps above tf.distribute. The reference builds
a tf.function per replica and aggregates via the ~15 strategy hooks
(distribute_lib.py:2394); the TPU-native redesign compiles ONE global
SPMD train step over the strategy's mesh (the model of SURVEY §3.4):

- loss is a sample-weighted GLOBAL mean inside the program, so the
  reference's per-replica loss scaling by num_replicas_in_sync
  (distribute_lib.py:1675) holds by construction;
- metric state is an explicit pytree updated inside the program on the
  globally-sharded batch (≙ SyncOnRead SUM variables, values.py:1294);
- partial final batches are zero-padded with a sample-weight mask, so
  one static batch shape compiles once and evaluate() is exact
  (≙ get_next_as_optional partial-batch handling, input_lib.py:574).

Works under any Strategy (OneDevice, Mirrored, MultiWorkerMirrored, TPU):
build/compile inside ``strategy.scope()``.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.input.dataset import Dataset
from distributed_tensorflow_tpu.training import callbacks as callbacks_lib
from distributed_tensorflow_tpu.training import losses as losses_lib
from distributed_tensorflow_tpu.training import metrics as metrics_lib

_OPTIMIZERS = {
    "sgd": lambda lr: optax.sgd(lr),
    "adam": lambda lr: optax.adam(lr),
    "adamw": lambda lr: optax.adamw(lr),
    "rmsprop": lambda lr: optax.rmsprop(lr),
}


def _default_strategy():
    from distributed_tensorflow_tpu.parallel.one_device import (
        OneDeviceStrategy)
    return OneDeviceStrategy()


def _unflatten_like(template, flat: dict, prefix: str = ""):
    """Inverse of checkpoint._flatten for plain pytrees."""
    from collections.abc import Mapping
    if isinstance(template, Mapping):
        return type(template)(
            {k: _unflatten_like(template[k],
                                flat, f"{prefix}/{k}" if prefix else str(k))
             for k in template})
    if isinstance(template, (list, tuple)):
        vals = [_unflatten_like(v, flat, f"{prefix}/{i}" if prefix else str(i))
                for i, v in enumerate(template)]
        if hasattr(template, "_fields"):      # NamedTuple (optax states)
            return type(template)(*vals)
        return type(template)(vals)
    return flat[prefix or "value"]


class Model:
    """A trainable model: a flax module + optimizer + loss + metrics.

    Usage (≙ tf_keras Model under a strategy scope)::

        strategy = dtx.MirroredStrategy()
        with strategy.scope():
            model = dtx.training.Model(MNISTCNN())
            model.compile(optimizer="adam", learning_rate=1e-3,
                          loss="sparse_categorical_crossentropy",
                          metrics=["accuracy"])
        model.fit(x_train, y_train, epochs=3, batch_size=256,
                  validation_data=(x_test, y_test))
    """

    def __init__(self, module, *, eval_module=None, seed: int = 0):
        """``eval_module``: variant used by evaluate/predict for modules
        whose eval behavior is a constructor flag (e.g.
        ``ResNet(cfg, train=False)`` for running BN averages)."""
        self.module = module
        self.eval_module = eval_module or module
        self.seed = seed
        self.strategy = None
        self.stop_training = False
        # {"params", "opt_state", "step", "model_state"} — model_state
        # holds non-param flax collections (batch_stats etc.,
        # ≙ Keras non-trainable weights updated by the forward pass)
        self._state = None
        self._built = False
        self._compiled = False
        self._train_fn = None
        self._eval_fn = None
        self._predict_fn = None
        self._restored_initial_epoch = None

    # -- build / compile ---------------------------------------------------
    def build(self, sample_input):
        """Initialize parameters on the strategy's mesh (replicated)."""
        self._ensure_strategy()
        sample = jax.tree_util.tree_map(
            lambda a: jnp.zeros(np.shape(a), np.asarray(a).dtype),
            sample_input)
        rng = jax.random.PRNGKey(self.seed)

        def init_vars():
            return dict(self.module.init(rng, sample))

        variables = self.strategy.init_state(init_vars)
        params = variables.pop("params", {})   # parameter-less models OK
        variables.pop("reg_losses", None)      # recomputed per step
        self._build_sample = sample        # Sequential.add rebuilds with it
        self._state = {"params": params, "step": jnp.zeros((), jnp.int32),
                       "model_state": variables}
        if self._compiled:
            self._state["opt_state"] = self.strategy.init_state(
                lambda: self._tx.init(params))
        self._built = True

    def _ensure_strategy(self):
        if self.strategy is None:
            from distributed_tensorflow_tpu.parallel.strategy import (
                has_strategy, get_strategy)
            self.strategy = (get_strategy() if has_strategy()
                             else _default_strategy())

    def compile(self, optimizer="adam", loss=None, metrics=(),
                learning_rate: float | None = None):
        """≙ Model.compile. ``optimizer``: optax GradientTransformation or
        one of {"sgd", "adam", "adamw", "rmsprop"}; string optimizers (and
        any optimizer when ``learning_rate`` is given) are wrapped in
        ``optax.inject_hyperparams`` so LearningRateScheduler works."""
        self._ensure_strategy()
        # Schedule-driven optimizers (callable learning_rate): optax
        # re-evaluates the schedule every update, so host-side writes to
        # model.learning_rate (ReduceLROnPlateau, LearningRateScheduler)
        # would be silently clobbered — the setter raises in that
        # combination (tf_keras fails loudly there too).
        self._lr_schedule_driven = callable(learning_rate)
        if isinstance(optimizer, str):
            key = optimizer.lower()
            if key not in _OPTIMIZERS:
                raise ValueError(f"Unknown optimizer {optimizer!r}; "
                                 f"known: {sorted(_OPTIMIZERS)}")
            lr = learning_rate if learning_rate is not None else 1e-3
            maker = {"sgd": optax.sgd, "adam": optax.adam,
                     "adamw": optax.adamw, "rmsprop": optax.rmsprop}[key]
            self._tx = optax.inject_hyperparams(maker)(learning_rate=lr)
        else:
            self._tx = optimizer
        if loss is None:
            raise ValueError("compile() requires a loss")
        self._loss = losses_lib.get(loss)
        self._metrics = [metrics_lib.get(m, loss=self._loss)
                         for m in (metrics or ())]
        names = [m.name for m in self._metrics]
        if len(set(names)) != len(names):
            raise ValueError(f"Duplicate metric names: {names}")
        self._loss_metric = metrics_lib.Mean("loss")
        self._compiled = True
        if self._built:
            self._state["opt_state"] = self.strategy.init_state(
                lambda: self._tx.init(self._state["params"]))
        # new compile invalidates compiled functions
        self._train_fn = self._eval_fn = self._predict_fn = None

    # -- learning rate (LearningRateScheduler support) ---------------------
    @property
    def learning_rate(self) -> float:
        hp = getattr(self._state["opt_state"], "hyperparams", None)
        if hp is None or "learning_rate" not in hp:
            raise AttributeError(
                "optimizer has no mutable learning_rate; compile with a "
                "string optimizer or optax.inject_hyperparams")
        return float(hp["learning_rate"])

    @learning_rate.setter
    def learning_rate(self, value: float):
        if getattr(self, "_lr_schedule_driven", False):
            raise AttributeError(
                "learning_rate was compiled as a schedule; "
                "inject_hyperparams re-evaluates it every update, so "
                "writes (ReduceLROnPlateau, LearningRateScheduler) "
                "would be silently clobbered — compile with a float "
                "learning rate to drive it from callbacks")
        opt = self._state["opt_state"]
        hp = getattr(opt, "hyperparams", None)
        if hp is None or "learning_rate" not in hp:
            raise AttributeError(
                "optimizer has no mutable learning_rate; compile with a "
                "string optimizer or optax.inject_hyperparams")
        hp["learning_rate"] = jnp.asarray(value, jnp.float32)

    # -- compiled step functions ------------------------------------------
    def _metric_init(self):
        ms = {"loss": self._loss_metric.init()}
        for m in self._metrics:
            ms[m.name] = m.init()
        return self.strategy.replicate(ms)

    def _metric_results(self, mstate) -> dict:
        out = {"loss": float(self._loss_metric.result(mstate["loss"]))}
        for m in self._metrics:
            out[m.name] = float(m.result(mstate[m.name]))
        return out

    def _make_train_function(self):
        if self._train_fn is not None:
            return self._train_fn
        # Bucketed-overlap gradient sync (ISSUE 6): on >1 replica the
        # strategy supplies a GradientBucketer and the step computes
        # per-replica gradients under shard_map, reducing them as
        # reverse-layer-order buckets so late-layer collectives overlap
        # the remaining backward pass. Models with mutable collections
        # (BN batch_stats) keep the GSPMD path: its global-batch
        # statistics semantics must not change under the default.
        if not self._state.get("model_state"):
            get_bucketer = getattr(self.strategy, "gradient_bucketer", None)
            bucketer = get_bucketer() if callable(get_bucketer) else None
            if bucketer is not None:
                self._train_fn = self._make_bucketed_train_function(bucketer)
                return self._train_fn
        module, loss_obj = self.module, self._loss
        metrics, loss_metric = self._metrics, self._loss_metric
        tx = self._tx

        base_rng = jax.random.PRNGKey(self.seed ^ 0x5eed)

        def step(state, mstate, batch, full):
            x, y, sw = batch
            model_state = state.get("model_state", {})
            collections = list(model_state)
            # per-step stochastic-layer rng (≙ Keras Dropout seeds);
            # harmless for modules that never request the "dropout"
            # stream
            rngs = {"dropout": jax.random.fold_in(base_rng,
                                                  state["step"])}

            def compute_loss(params):
                preds, mutated = module.apply(
                    {"params": params, **model_state}, x,
                    mutable=collections + ["reg_losses"], rngs=rngs)
                mutated = dict(mutated)
                # weight-regularizer penalties (keras model.losses):
                # part of the objective AND the reported loss
                reg = sum(jax.tree_util.tree_leaves(
                    mutated.pop("reg_losses", {})), jnp.zeros((), jnp.float32))
                per = loss_obj.call(y, preds).astype(jnp.float32) + reg
                w = sw.astype(jnp.float32)
                loss = jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1e-9)
                return loss, (preds, per, mutated)

            (loss, (preds, per, mutated)), grads = jax.value_and_grad(
                compute_loss, has_aux=True)(state["params"])
            updates, opt_state = tx.update(grads, state["opt_state"],
                                           state["params"])
            params = optax.apply_updates(state["params"], updates)
            # forward-pass state (BN batch statistics) computed over a
            # zero-PADDED final batch would corrupt the running averages
            # — keep the previous state for partial batches (`full`=0)
            new_model_state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(full > 0, new, old),
                model_state, dict(mutated)) if collections else {}
            new_state = {"params": params, "opt_state": opt_state,
                         "step": state["step"] + 1,
                         "model_state": new_model_state}
            m2 = dict(mstate)
            m2["loss"] = loss_metric.update_values(mstate["loss"], per, sw)
            for m in metrics:
                m2[m.name] = m.update(mstate[m.name], y, preds, sw)
            return new_state, m2

        self._train_fn = self.strategy.compile_step(step)
        return self._train_fn

    def _make_bucketed_train_function(self, bucketer):
        """Explicit-SPMD train step: per-replica grads + reverse-order
        bucketed allreduce (collectives.GradientBucketer) + replicated
        optimizer apply, all inside one shard_map region. Numerically the
        same objective as the GSPMD path (global sample-weighted mean);
        only the reduction schedule changes — each bucket's collective
        launches as soon as backprop has produced its (late-layer)
        gradients instead of one compiler-chosen sync point."""
        module, loss_obj = self.module, self._loss
        metrics, loss_metric = self._metrics, self._loss_metric
        tx = self._tx
        strategy = self.strategy
        mesh = strategy.mesh
        axes = strategy.data_axis_names
        base_rng = jax.random.PRNGKey(self.seed ^ 0x5eed)
        from jax.sharding import PartitionSpec as P
        from distributed_tensorflow_tpu.parallel import collectives as coll

        def local_apply(params, opt_state, step_idx, x, y, sw):
            # per-(step, replica) stochastic-layer rng: replicas draw
            # DIFFERENT dropout masks for their distinct data shards
            rngs = {"dropout": jax.random.fold_in(
                jax.random.fold_in(base_rng, step_idx),
                coll.combined_axis_index(axes))}

            def local_objective(p):
                preds, mutated = module.apply(
                    {"params": p}, x, mutable=["reg_losses"], rngs=rngs)
                reg = sum(jax.tree_util.tree_leaves(
                    dict(mutated).get("reg_losses", {})),
                    jnp.zeros((), jnp.float32))
                per = loss_obj.call(y, preds).astype(jnp.float32) + reg
                w = sw.astype(jnp.float32)
                return jnp.sum(per * w), (preds, per)

            (num, (preds, per)), grads = jax.value_and_grad(
                local_objective, has_aux=True)(params)
            den = jnp.maximum(
                coll.all_reduce(jnp.sum(sw.astype(jnp.float32)), axes),
                1e-9)
            # global loss = psum(local weighted sums) / psum(weights);
            # its gradient is psum(local grads) / psum(weights) — the
            # psum is the bucketed, reverse-scheduled reduction.
            grads = bucketer.all_reduce(grads)
            grads = jax.tree_util.tree_map(
                lambda g: (g / den).astype(g.dtype), grads)
            loss = coll.all_reduce(num, axes) / den
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, preds, per

        spmd = jax.shard_map(
            local_apply, mesh=mesh,
            in_specs=(P(), P(), P(), P(axes), P(axes), P(axes)),
            out_specs=(P(), P(), P(), P(axes), P(axes)),
            check_vma=False)

        def step(state, mstate, batch, full):
            x, y, sw = batch
            params, opt_state, loss, preds, per = spmd(
                state["params"], state["opt_state"], state["step"],
                x, y, sw)
            new_state = {"params": params, "opt_state": opt_state,
                         "step": state["step"] + 1, "model_state": {}}
            m2 = dict(mstate)
            m2["loss"] = loss_metric.update_values(mstate["loss"], per, sw)
            for m in metrics:
                m2[m.name] = m.update(mstate[m.name], y, preds, sw)
            return new_state, m2

        return strategy.compile_step(step)

    def _make_eval_function(self):
        if self._eval_fn is not None:
            return self._eval_fn
        module, loss_obj = self.eval_module, self._loss
        metrics, loss_metric = self._metrics, self._loss_metric

        def eval_step(params, model_state, mstate, batch):
            x, y, sw = batch
            preds, mutated = module.apply(
                {"params": params, **model_state}, x,
                mutable=["reg_losses"])
            reg = sum(jax.tree_util.tree_leaves(
                dict(mutated).get("reg_losses", {})),
                jnp.zeros((), jnp.float32))
            per = loss_obj.call(y, preds).astype(jnp.float32) + reg
            m2 = dict(mstate)
            m2["loss"] = loss_metric.update_values(mstate["loss"], per, sw)
            for m in metrics:
                m2[m.name] = m.update(mstate[m.name], y, preds, sw)
            return m2

        self._eval_fn = jax.jit(eval_step)
        return self._eval_fn

    def _make_predict_function(self):
        if self._predict_fn is not None:
            return self._predict_fn
        module = self.eval_module
        self._predict_fn = jax.jit(
            lambda params, model_state, x: module.apply(
                {"params": params, **model_state}, x))
        return self._predict_fn

    # -- data plumbing -----------------------------------------------------
    def _batches(self, x, y=None, sample_weight=None, *, batch_size,
                 shuffle=False, seed=0):
        """Yield ((x, y, sw), full) global batches with a static batch
        size: the final partial batch is zero-padded and masked via sw,
        with ``full`` = 0.0 flagging it (so forward-pass state updates
        can be suppressed for padded rows)."""
        if isinstance(x, Dataset) or (y is None and not isinstance(
                x, (np.ndarray, jnp.ndarray))):
            # pre-batched dataset / iterable of (x, y[, sw]) tuples
            ds = Dataset.from_iterable(x)
            static = [None]

            def gen():
                for el in ds:
                    if not isinstance(el, (tuple, list)) or len(el) < 2:
                        raise ValueError(
                            "dataset elements must be (x, y) or (x, y, sw)")
                    bx, by = el[0], el[1]
                    bw = el[2] if len(el) > 2 else None
                    n = np.shape(jax.tree_util.tree_leaves(bx)[0])[0]
                    if static[0] is None:
                        static[0] = n
                    yield self._pad(bx, by, bw, n, static[0])
            return gen()

        x = np.asarray(x)
        y = np.asarray(y)
        n = len(x)

        def gen():
            idx = np.arange(n)
            if shuffle:
                np.random.default_rng(seed).shuffle(idx)
            for start in range(0, n, batch_size):
                sel = idx[start:start + batch_size]
                bw = (np.asarray(sample_weight)[sel]
                      if sample_weight is not None else None)
                yield self._pad(x[sel], y[sel], bw, len(sel), batch_size)
        return gen()

    @staticmethod
    def _pad(bx, by, bw, n, full):
        sw = np.ones(n, np.float32) if bw is None else \
            np.asarray(bw, np.float32)
        if n == full:
            return (bx, by, sw), np.float32(1.0)

        def pad(a):
            a = np.asarray(a)
            width = [(0, full - n)] + [(0, 0)] * (a.ndim - 1)
            return np.pad(a, width)
        return ((jax.tree_util.tree_map(pad, bx),
                 jax.tree_util.tree_map(pad, by), pad(sw)),
                np.float32(0.0))

    def _place(self, batch):
        return self.strategy.shard_batch(batch)

    # -- fit / evaluate / predict -----------------------------------------
    def fit(self, x, y=None, *, batch_size: int = 32, epochs: int = 1,
            verbose: int = 1, callbacks: Sequence | None = None,
            validation_data=None, validation_split: float = 0.0,
            shuffle: bool = True,
            initial_epoch: int = 0, steps_per_epoch: int | None = None,
            sample_weight=None, class_weight=None):
        """≙ Model.fit (tf_keras training.py:1453). ``validation_split``
        holds out the LAST fraction of (x, y) before shuffling, like
        keras (training.py train_validation_split); ``class_weight``
        maps class index -> weight, multiplied into sample_weight
        (keras class_weight semantics, sparse integer labels)."""
        if not self._compiled:
            raise RuntimeError("compile() the model before fit()")
        if validation_split:
            if not 0.0 < validation_split < 1.0:
                raise ValueError(
                    f"validation_split must be in (0, 1), got "
                    f"{validation_split}")
            if validation_data is not None:
                raise ValueError(
                    "pass either validation_data or validation_split, "
                    "not both")
            if y is None:
                raise ValueError(
                    "validation_split requires array inputs (x, y)")
            x, y = np.asarray(x), np.asarray(y)
            split = int(len(x) * (1.0 - validation_split))
            if split == 0 or split == len(x):
                raise ValueError(
                    f"validation_split={validation_split} on "
                    f"{len(x)} samples leaves an empty training or "
                    f"validation set")
            if sample_weight is not None:
                sw = np.asarray(sample_weight)
                validation_data = (x[split:], y[split:], sw[split:])
                sample_weight = sw[:split]
            else:
                validation_data = (x[split:], y[split:])
            x, y = x[:split], y[:split]
        if class_weight:
            # AFTER the validation split: keras applies class_weight to
            # TRAINING batches only (val_loss stays unweighted).
            if y is None:
                raise ValueError(
                    "class_weight requires array labels (x, y)")
            y_arr = np.asarray(y)
            if y_arr.ndim > 1:        # one-hot -> sparse for lookup
                y_arr = np.argmax(y_arr, axis=-1)
            cw = np.ones(int(y_arr.max()) + 1, np.float32)
            for cls, w in class_weight.items():
                if int(cls) >= len(cw):
                    cw = np.concatenate(
                        [cw, np.ones(int(cls) + 1 - len(cw), np.float32)])
                cw[int(cls)] = w
            per_sample = cw[y_arr.astype(np.int64)]
            sample_weight = (per_sample if sample_weight is None
                             else np.asarray(sample_weight, np.float32)
                             * per_sample)
        if not self._built:
            (first_x, _, _), _ = next(iter(self._batches(
                x, y, batch_size=batch_size, shuffle=False)))
            self.build(first_x)
            self._state["opt_state"] = self.strategy.init_state(
                lambda: self._tx.init(self._state["params"]))

        self.stop_training = False
        history = callbacks_lib.History()
        cbs = list(callbacks or [])
        if verbose:
            cbs.append(callbacks_lib.ProgbarLogger())
        cbs.append(history)
        cb_list = callbacks_lib.CallbackList(
            cbs, self, {"epochs": epochs, "batch_size": batch_size})

        train_fn = self._make_train_function()
        # Batch logs materialize every metric on the host (a device
        # sync per step); only build them on steps some overriding
        # callback wants, at its declared interval.
        log_intervals = [
            cb.batch_log_interval for cb in cb_list.callbacks
            if type(cb).on_train_batch_end
            is not callbacks_lib.Callback.on_train_batch_end]
        batch_log_every = min(log_intervals) if log_intervals else 0

        cb_list.on_train_begin()
        start_epoch = initial_epoch
        if self._restored_initial_epoch is not None:
            start_epoch = max(start_epoch, self._restored_initial_epoch)
            self._restored_initial_epoch = None

        # Unified telemetry: per-step train.step events + step-time
        # histogram + steps_completed counter (loss stays device-side
        # per step — the gauge/event carries it at epoch granularity to
        # avoid forcing a host sync every batch).
        from distributed_tensorflow_tpu.training.loops import StepTelemetry
        step_telemetry = StepTelemetry()
        global_step = 0
        for epoch in range(start_epoch, epochs):
            cb_list.on_epoch_begin(epoch)
            mstate = self._metric_init()
            steps = 0
            for batch, full in self._batches(x, y, sample_weight,
                                             batch_size=batch_size,
                                             shuffle=shuffle,
                                             seed=self.seed + epoch):
                # host-callback time is a named step phase: what the
                # step loop spends OUTSIDE the compiled step (callback
                # list + optional host metric readback) is the "host"
                # share obs_report's phase table attributes.
                cb_t0 = time.perf_counter()
                cb_list.on_train_batch_begin(steps)
                cb_s = time.perf_counter() - cb_t0
                self._state, mstate = train_fn(
                    self._state, mstate, self._place(batch), full)
                cb_t0 = time.perf_counter()
                if batch_log_every and steps % batch_log_every == 0:
                    cb_list.on_train_batch_end(
                        steps, self._metric_results(mstate))
                else:
                    cb_list.on_train_batch_end(steps, None)
                cb_s += time.perf_counter() - cb_t0
                step_telemetry.step_completed(
                    global_step, phases={"host": cb_s})
                global_step += 1
                steps += 1
                if steps_per_epoch and steps >= steps_per_epoch:
                    break
                if self.stop_training:      # e.g. TerminateOnNaN
                    break
            logs = self._metric_results(mstate)
            telemetry.event("train.epoch", epoch=epoch,
                            **{k: float(v) for k, v in logs.items()
                               if isinstance(v, (int, float))})
            if validation_data is not None:
                # 2-tuple (x, y) or keras's 3-tuple (x, y, sample_weight)
                vx, vy = validation_data[0], validation_data[1]
                vsw = (validation_data[2]
                       if len(validation_data) > 2 else None)
                val = self.evaluate(vx, vy, sample_weight=vsw,
                                    batch_size=batch_size, verbose=0,
                                    return_dict=True)
                logs.update({f"val_{k}": v for k, v in val.items()})
            cb_list.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        cb_list.on_train_end()
        self.history = history
        return history

    def evaluate(self, x, y=None, *, batch_size: int = 32,
                 verbose: int = 0, steps: int | None = None,
                 sample_weight=None, return_dict: bool = False):
        """≙ Model.evaluate. Keras return convention: scalar loss with
        no compiled metrics, ``[loss, metric...]`` otherwise,
        ``{"loss": ..., metric: ...}`` with ``return_dict=True``. Exact
        on partial final batches (mask-padded)."""
        if not self._compiled or not self._built:
            raise RuntimeError("build+compile the model before evaluate()")
        eval_fn = self._make_eval_function()
        mstate = self._metric_init()
        count = 0
        for batch, _full in self._batches(x, y, sample_weight,
                                          batch_size=batch_size):
            mstate = eval_fn(self._state["params"],
                             self._state.get("model_state", {}), mstate,
                             self._place(batch))
            count += 1
            if steps and count >= steps:
                break
        results = self._metric_results(mstate)
        if verbose:
            print("  ".join(f"{k}={v:.4f}" for k, v in results.items()),
                  flush=True)
        if return_dict:
            return results
        if len(results) == 1:
            return results["loss"]
        return [results["loss"]] + [results[m.name]
                                    for m in self._metrics]

    def predict(self, x, *, batch_size: int = 32) -> Any:
        """≙ Model.predict. Accepts an array OR a pre-batched Dataset /
        iterable of input batches (keras predict(dataset) semantics —
        elements may be bare inputs or (x, ...) tuples whose first
        entry is the input).

        Telemetry: each batch emits a ``predict.step`` event and lands
        in the ``inference/step_time`` batch-latency histogram — the
        same ``inference/`` namespace the serving engine
        (serving/engine.py) reports request metrics into, so batch and
        online inference read off one rollup."""
        if not self._built:
            raise RuntimeError("build the model before predict()")
        from distributed_tensorflow_tpu.training.loops import StepTelemetry
        predict_fn = self._make_predict_function()
        step_telemetry = StepTelemetry(event_name="predict.step",
                                       metric_prefix="inference")
        if isinstance(x, Dataset) or not isinstance(
                x, (np.ndarray, jnp.ndarray, list, tuple)):
            outs = []
            static = None
            for step, el in enumerate(Dataset.from_iterable(x)):
                bx = el[0] if isinstance(el, (tuple, list)) else el
                bx = np.asarray(bx)
                n = len(bx)
                if static is None:
                    static = n
                if n < static:
                    width = [(0, static - n)] + [(0, 0)] * (bx.ndim - 1)
                    bx = np.pad(bx, width)
                preds = predict_fn(self._state["params"],
                                   self._state.get("model_state", {}),
                                   self._place(bx))
                outs.append(np.asarray(preds)[:n])
                step_telemetry.step_completed(step, batch_size=n)
            return np.concatenate(outs, axis=0)
        outs, total = [], 0
        x = np.asarray(x)
        for step, start in enumerate(range(0, len(x), batch_size)):
            bx = x[start:start + batch_size]
            n = len(bx)
            if n < batch_size:
                width = [(0, batch_size - n)] + [(0, 0)] * (bx.ndim - 1)
                bx = np.pad(bx, width)
            preds = predict_fn(self._state["params"],
                               self._state.get("model_state", {}),
                               self._place(bx))
            outs.append(np.asarray(preds)[:n])
            total += n
            step_telemetry.step_completed(step, batch_size=n)
        return np.concatenate(outs, axis=0)

    def __call__(self, x):
        return self._make_predict_function()(
            self._state["params"], self._state.get("model_state", {}), x)

    # -- weights -----------------------------------------------------------
    @property
    def params(self):
        return self._state["params"]

    def get_weights(self):
        return jax.tree_util.tree_map(np.asarray, self._state["params"])

    def set_weights(self, weights):
        shardings = jax.tree_util.tree_map(
            lambda a: a.sharding, self._state["params"])
        self._state["params"] = jax.tree_util.tree_map(
            lambda w, s: jax.device_put(jnp.asarray(w), s),
            weights, shardings)

    def summary(self, print_fn=print):
        """≙ keras Model.summary: per-top-level-module parameter counts
        (shim models list their layers; plain flax modules list the
        params tree's top-level groups)."""
        if not self._built:
            raise ValueError("build the model (or fit once) before "
                             "summary()")
        import numpy as _np
        params = self._state["params"]
        rows = []
        for name, sub in (params.items() if hasattr(params, "items")
                          else [("params", params)]):
            n = sum(int(_np.prod(x.shape))
                    for x in jax.tree_util.tree_leaves(sub))
            rows.append((name, n))
        width = max([len(r[0]) for r in rows] + [10]) + 2
        print_fn(f"Model: {type(self).__name__}")
        print_fn("-" * (width + 14))
        for name, n in rows:
            print_fn(f"{name:<{width}}{n:>12,}")
        total = sum(n for _, n in rows)
        n_state = sum(int(_np.prod(x.shape)) for x in
                      jax.tree_util.tree_leaves(
                          self._state.get("model_state", {})))
        print_fn("-" * (width + 14))
        print_fn(f"Total params: {total:,}")
        if n_state:
            print_fn(f"Non-trainable state: {n_state:,}")

    def save(self, filepath: str):
        """≙ keras Model.save (TFK/src/engine/training.py:2779):
        architecture + weights to a directory; reload with
        ``keras.models.load_model``. Supported for shim Sequential and Functional models."""
        from distributed_tensorflow_tpu.training.saving import save_model
        save_model(self, filepath)

    def save_weights(self, path: str):
        """Params AND non-param model state (BN running stats — the
        Keras non-trainable weights) when present."""
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint)
        extra = ({"model_state": self._state["model_state"]}
                 if self._state.get("model_state") else {})
        Checkpoint(params=self._state["params"], **extra).write(path)

    def load_weights(self, path: str):
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint)
        extra = ({"model_state": self._state["model_state"]}
                 if self._state.get("model_state") else {})
        try:
            restored = Checkpoint(params=self._state["params"],
                                  **extra).restore(path)
        except KeyError:
            # weights file predates model_state support: params only
            extra = {}
            restored = Checkpoint(
                params=self._state["params"]).restore(path)
        tree = _unflatten_like(self._state["params"], restored, "params")
        self.set_weights(tree)
        if extra:
            self._state["model_state"] = self._replaced_like(
                self._state["model_state"],
                _unflatten_like(self._state["model_state"], restored,
                                "model_state"))

    @staticmethod
    def _replaced_like(current, restored):
        """device_put restored host arrays with the current leaves'
        shardings (mirrors the params restore path — restored state must
        live on the mesh, not as process-local arrays)."""
        return jax.tree_util.tree_map(
            lambda cur, new: jax.device_put(
                jnp.asarray(new, getattr(cur, "dtype", None)),
                cur.sharding) if hasattr(cur, "sharding")
            else jnp.asarray(new),
            current, restored)

    # -- backup/restore (≙ worker_training_state.py:34) -------------------
    def _back_up(self, backup_dir: str, epoch: int):
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint)
        extra = {}
        if self._state.get("model_state"):
            extra["model_state"] = self._state["model_state"]
        Checkpoint(
            params=self._state["params"],
            opt_state=self._state["opt_state"],
            epoch=np.asarray(epoch, np.int64),
            **extra,
        ).write(os.path.join(backup_dir, "backup"))

    def _maybe_restore_backup(self, backup_dir: str):
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint)
        path = os.path.join(backup_dir, "backup")
        if not os.path.exists(os.path.join(path, "checkpoint.index.json")):
            return
        extra = {}
        if self._state.get("model_state"):
            extra["model_state"] = self._state["model_state"]
        try:
            restored = Checkpoint(params=self._state["params"],
                                  opt_state=self._state["opt_state"],
                                  epoch=np.zeros((), np.int64),
                                  **extra).restore(path)
        except KeyError:
            # backup predates model_state support: restore what exists
            extra = {}
            restored = Checkpoint(params=self._state["params"],
                                  opt_state=self._state["opt_state"],
                                  epoch=np.zeros((), np.int64)
                                  ).restore(path)
        params = _unflatten_like(self._state["params"], restored, "params")
        opt = _unflatten_like(self._state["opt_state"], restored,
                              "opt_state")
        if extra:
            self._state["model_state"] = self._replaced_like(
                self._state["model_state"],
                _unflatten_like(self._state["model_state"], restored,
                                "model_state"))
        shardings = jax.tree_util.tree_map(
            lambda a: a.sharding, self._state["params"])
        self._state["params"] = jax.tree_util.tree_map(
            lambda w, s: jax.device_put(jnp.asarray(w), s), params,
            shardings)
        self._state["opt_state"] = jax.tree_util.tree_map(
            lambda w, a: jax.device_put(
                jnp.asarray(w, getattr(a, "dtype", None)),
                getattr(a, "sharding", None)) if hasattr(a, "sharding")
            else w,
            opt, self._state["opt_state"])
        self._restored_initial_epoch = int(restored["epoch"]) + 1
