"""tf.keras.layers-shaped layer shim backed by flax.

≙ TFK/src/engine/base_layer.py + TFK/src/layers/ (Dense:
TFK/src/layers/core/dense.py, Conv2D: convolutional/base_conv.py,
BatchNormalization: normalization/batch_normalization.py, …) — the
minimal surface that lets a verbatim reference-style script (configs
#1-#3: MNIST CNN / CNN classifiers / embedding+dense stacks) run
against this framework with only its import line changed
(``from distributed_tensorflow_tpu import keras``).

Every layer keeps the KERAS constructor signature and the KERAS weight
layout (Conv kernels (H, W, Cin, Cout); Dense kernels (in, out)) — the
layouts flax already shares, as pinned by tests/test_reference_parity —
so ``get_weights``/``set_weights`` interoperate with real tf_keras
models. ``Sequential`` composes the layers into one flax module and IS
a ``training.Model``: compile/fit/evaluate/predict come from the
SPMD training loop (training/model.py), not a port of the Keras one.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_tensorflow_tpu.training import regularizers as reg_lib
from distributed_tensorflow_tpu.training.model import Model

_ACTIVATIONS = {
    None: lambda x: x,
    "linear": lambda x: x,
    "relu": nn.relu,
    "gelu": nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": nn.sigmoid,
    "softmax": lambda x: nn.softmax(x, axis=-1),
    "silu": nn.silu,
    "swish": nn.silu,
    "elu": nn.elu,
    # keras's leaky_relu ACTIVATION slope is 0.2 (Keras 3 activations
    # default; flax nn.leaky_relu defaults to 0.01). NOTE the known
    # discrepancy vs the LeakyReLU LAYER, whose tf_keras default alpha
    # is 0.3: activation="leaky_relu" and layers.LeakyReLU() give
    # different slopes, exactly as the two defaults differ upstream —
    # pass the slope explicitly when switching between the forms.
    "leaky_relu": lambda x: nn.leaky_relu(x, negative_slope=0.2),
    "softplus": nn.softplus,
    "exponential": jnp.exp,
}


def _activation(identifier) -> Callable:
    if callable(identifier):
        return identifier
    try:
        return _ACTIVATIONS[identifier]
    except KeyError:
        raise ValueError(
            f"Unknown activation {identifier!r}; known: "
            f"{sorted(k for k in _ACTIVATIONS if k)}") from None


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


def _single(v):
    """1-D window arg: int -> (int,), sequence -> tuple."""
    return (v,) if isinstance(v, int) else tuple(v)


class Layer:
    """Base shim layer: a configuration object whose ``apply`` runs
    inside the owning flax module's compact scope (so flax handles
    parameter creation/naming). ``module`` is the enclosing flax module
    (for layers that need rngs, e.g. Dropout).

    Calling a layer on symbolic tensors (``keras.Input`` outputs)
    records a functional-graph node (training/functional.py) — the
    keras functional API. Multi-arg calls (``mha(q, v)``) record the
    args tuple; ``apply`` receives the same structure back."""

    #: set on layers like Dropout/BatchNormalization that behave
    #: differently in training
    has_train_behavior = False

    @property
    def symbolic_outputs(self) -> int:
        """Number of outputs a symbolic call yields (1 for almost all
        layers; RNNs with return_state=True return [out, *states] and
        declare it here so ``out, h, c = lstm(x)`` unpacks)."""
        return 1

    def apply(self, x, *, train: bool, module=None):
        raise NotImplementedError

    def __call__(self, *args):
        from distributed_tensorflow_tpu.training.functional import (
            is_symbolic, symbolic_call)
        call_args = args[0] if len(args) == 1 else tuple(args)
        if is_symbolic(call_args):
            return symbolic_call(self, call_args)
        raise TypeError(
            f"{type(self).__name__} called on concrete values; shim "
            "layers are callable only on symbolic tensors (keras.Input) "
            "to build functional models — for eager use put the layer "
            "in a Sequential/Model and call that")

    def compute_input_shape(self):
        """(sample-less) input shape if the layer pins one, else None."""
        return getattr(self, "input_shape", None)

    def get_config(self) -> dict:
        """≙ keras Layer.get_config: constructor kwargs, reconstructable
        via ``type(self)(**config)``. Derived generically from the
        constructor signature (every shim layer stores its args under
        the parameter name; ``activation`` serializes its string
        identifier)."""
        import inspect
        cfg = {}
        params = inspect.signature(type(self).__init__).parameters
        for name, p in params.items():
            if name == "self" or p.kind in (p.VAR_POSITIONAL, p.VAR_KEYWORD):
                continue
            key = "activation_id" if name == "activation" else name
            if not hasattr(self, key):
                raise ValueError(
                    f"{type(self).__name__} cannot serialize constructor "
                    f"param {name!r} (no matching attribute)")
            v = getattr(self, key)
            if isinstance(v, reg_lib.Regularizer):
                v = reg_lib.serialize(v)
            elif callable(v) and not isinstance(v, str):
                raise ValueError(
                    f"{type(self).__name__}.{name} is a Python callable; "
                    "only string-identified values are serializable")
            cfg[name] = list(v) if isinstance(v, tuple) else v
        return cfg

    def _sow_reg(self, child, module):
        """Sow this layer's weight-regularizer penalties into the
        ``reg_losses`` collection (summed into the objective by
        training/model.py — ≙ keras layer.losses)."""
        kr = getattr(self, "kernel_regularizer", None)
        br = getattr(self, "bias_regularizer", None)
        if module is None or (kr is None and br is None):
            return
        params = child.variables["params"]
        # one slot per (layer instance, param): a REUSED layer replays
        # its compact body per call, but the penalty must count once
        # (keras registers regularizers per weight, not per call) —
        # the overwrite reduce_fn keeps a single value per slot.
        keep_last = dict(reduce_fn=lambda prev, new: new,
                         init_fn=lambda: 0.0)
        if kr is not None:
            module.sow("reg_losses", f"reg_{id(self)}_k",
                       kr(params["kernel"]), **keep_last)
        if br is not None and "bias" in params:
            module.sow("reg_losses", f"reg_{id(self)}_b",
                       br(params["bias"]), **keep_last)

    @classmethod
    def from_config(cls, config: dict):
        return cls(**config)


class InputLayer(Layer):
    """≙ keras.layers.InputLayer — records the per-sample input shape
    so Sequential can build eagerly. (``keras.Input`` itself is the
    functional-API symbolic-tensor factory; Sequential converts it to
    this layer, as tf_keras does.)"""

    def __init__(self, input_shape=None, *, shape=None):
        shape = shape if shape is not None else input_shape
        if shape is None:
            raise ValueError("InputLayer requires a shape")
        self.input_shape = tuple(shape)

    def apply(self, x, *, train, module=None):
        return x

    def get_config(self):
        return {"input_shape": list(self.input_shape)}


class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name: str | None = None):
        self.units = int(units)
        self.activation = _activation(activation)
        self.activation_id = activation
        self.use_bias = use_bias
        self.kernel_regularizer = reg_lib.get(kernel_regularizer)
        self.bias_regularizer = reg_lib.get(bias_regularizer)
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        dense = nn.Dense(self.units, use_bias=self.use_bias,
                         name=self.name)
        x = dense(x)
        self._sow_reg(dense, module)
        return self.activation(x)


class Conv2D(Layer):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, kernel_regularizer=None,
                 bias_regularizer=None, input_shape=None,
                 name: str | None = None):
        self.filters = int(filters)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.upper()
        self.activation = _activation(activation)
        self.activation_id = activation
        self.use_bias = use_bias
        self.kernel_regularizer = reg_lib.get(kernel_regularizer)
        self.bias_regularizer = reg_lib.get(bias_regularizer)
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        conv = nn.Conv(self.filters, self.kernel_size,
                       strides=self.strides, padding=self.padding,
                       use_bias=self.use_bias, name=self.name)
        x = conv(x)
        self._sow_reg(conv, module)
        return self.activation(x)


class Conv1D(Layer):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, input_shape=None,
                 name: str | None = None):
        self.filters = int(filters)
        self.kernel_size = _single(kernel_size)
        self.strides = _single(strides)
        self.padding = padding.upper()
        self.activation = _activation(activation)
        self.activation_id = activation
        self.use_bias = use_bias
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        x = nn.Conv(self.filters, self.kernel_size, strides=self.strides,
                    padding=self.padding, use_bias=self.use_bias,
                    name=self.name)(x)
        return self.activation(x)


class _DepthwiseModule(nn.Module):
    """Grouped conv holding the kernel in the KERAS depthwise layout
    (H, W, Cin, 1) so get/set_weights round-trips with tf_keras
    (flax nn.Conv would store (H, W, 1, Cin))."""
    kernel_size: tuple
    strides: tuple
    padding: str
    use_bias: bool

    @nn.compact
    def __call__(self, x):
        cin = x.shape[-1]
        kernel = self.param("kernel", nn.initializers.lecun_normal(),
                            (*self.kernel_size, cin, 1))
        y = jax.lax.conv_general_dilated(
            x, jnp.transpose(kernel, (0, 1, 3, 2)).astype(x.dtype),
            window_strides=self.strides, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=cin)
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (cin,))
        return y


class DepthwiseConv2D(Layer):
    """≙ keras DepthwiseConv2D (depth_multiplier=1): one filter per
    input channel; kernel kept in the KERAS layout (H, W, Cin, 1)."""

    def __init__(self, kernel_size, strides=1, padding: str = "valid",
                 activation=None, use_bias: bool = True,
                 name: str | None = None):
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.upper()
        self.activation = _activation(activation)
        self.activation_id = activation
        self.use_bias = use_bias
        self.name = name

    def apply(self, x, *, train, module=None):
        x = _DepthwiseModule(self.kernel_size, self.strides,
                             self.padding, self.use_bias,
                             name=self.name)(x)
        return self.activation(x)


class UpSampling2D(Layer):
    def __init__(self, size=2, interpolation: str = "nearest"):
        self.size = _pair(size)
        if interpolation != "nearest":
            raise NotImplementedError(
                "UpSampling2D supports interpolation='nearest'")
        self.interpolation = interpolation

    def apply(self, x, *, train, module=None):
        sh, sw = self.size
        return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)


class Permute(Layer):
    """≙ keras Permute: 1-indexed dims over the non-batch axes."""

    def __init__(self, dims):
        self.dims = tuple(int(d) for d in dims)

    def apply(self, x, *, train, module=None):
        return jnp.transpose(x, (0,) + self.dims)


class Lambda(Layer):
    """≙ keras Lambda — arbitrary stateless function. Not serializable
    (model.save raises), same as tf_keras without safe_mode=False."""

    def __init__(self, function):
        self.function = function

    def apply(self, x, *, train, module=None):
        return self.function(x)

    def get_config(self):
        raise ValueError(
            "Lambda layers are not serializable; rebuild the model in "
            "code and use load_weights")


class MaxPooling2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding: str = "valid"):
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None \
            else self.pool_size
        self.padding = padding.upper()

    def apply(self, x, *, train, module=None):
        return nn.max_pool(x, self.pool_size, strides=self.strides,
                           padding=self.padding)


class MaxPooling1D(Layer):
    def __init__(self, pool_size=2, strides=None, padding: str = "valid"):
        self.pool_size = _single(pool_size)
        self.strides = _single(strides) if strides is not None \
            else self.pool_size
        self.padding = padding.upper()

    def apply(self, x, *, train, module=None):
        return nn.max_pool(x, self.pool_size, strides=self.strides,
                           padding=self.padding)


class AveragePooling1D(MaxPooling1D):
    def apply(self, x, *, train, module=None):
        # count_include_pad=False: keras excludes padded cells from the
        # mean under padding='same'
        return nn.avg_pool(x, self.pool_size, strides=self.strides,
                           padding=self.padding,
                           count_include_pad=False)


class AveragePooling2D(MaxPooling2D):
    def apply(self, x, *, train, module=None):
        # count_include_pad=False: keras excludes padded cells from the
        # mean under padding='same'
        return nn.avg_pool(x, self.pool_size, strides=self.strides,
                           padding=self.padding,
                           count_include_pad=False)


class GlobalAveragePooling2D(Layer):
    def apply(self, x, *, train, module=None):
        return jnp.mean(x, axis=(1, 2))


class GlobalAveragePooling1D(Layer):
    def apply(self, x, *, train, module=None):
        return jnp.mean(x, axis=1)


class GlobalMaxPooling2D(Layer):
    def apply(self, x, *, train, module=None):
        return jnp.max(x, axis=(1, 2))


class GlobalMaxPooling1D(Layer):
    def apply(self, x, *, train, module=None):
        return jnp.max(x, axis=1)


class Flatten(Layer):
    def apply(self, x, *, train, module=None):
        return x.reshape((x.shape[0], -1))


class Dropout(Layer):
    has_train_behavior = True

    def __init__(self, rate: float, seed: int | None = None):
        self.rate = float(rate)
        self.seed = seed

    def apply(self, x, *, train, module=None):
        if not train or self.rate == 0.0:
            return x
        rng = module.make_rng("dropout")
        if self.seed is not None:       # keras per-layer seed honored
            rng = jax.random.fold_in(rng, self.seed)
        return nn.Dropout(self.rate, deterministic=False)(x, rng=rng)


class BatchNormalization(Layer):
    """≙ keras BatchNormalization: running averages live in the flax
    ``batch_stats`` collection, which training.Model carries as
    model_state (the Keras non-trainable-weights analogue)."""
    has_train_behavior = True

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 name: str | None = None):
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self.name = name

    def apply(self, x, *, train, module=None):
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum, epsilon=self.epsilon,
                            name=self.name)(x)


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-3, name: str | None = None):
        self.epsilon = float(epsilon)
        self.name = name

    def apply(self, x, *, train, module=None):
        return nn.LayerNorm(epsilon=self.epsilon, name=self.name)(x)


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, input_shape=None,
                 name: str | None = None):
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        return nn.Embed(self.input_dim, self.output_dim,
                        name=self.name)(x.astype(jnp.int32))


class ReLU(Layer):
    def apply(self, x, *, train, module=None):
        return nn.relu(x)


class LeakyReLU(Layer):
    def __init__(self, alpha: float = 0.3):
        self.alpha = float(alpha)

    def apply(self, x, *, train, module=None):
        return nn.leaky_relu(x, negative_slope=self.alpha)


class ELU(Layer):
    def __init__(self, alpha: float = 1.0):
        self.alpha = float(alpha)

    def apply(self, x, *, train, module=None):
        return nn.elu(x, alpha=self.alpha)


class Softmax(Layer):
    def apply(self, x, *, train, module=None):
        return nn.softmax(x, axis=-1)


class Activation(Layer):
    def __init__(self, activation):
        self.activation = _activation(activation)
        self.activation_id = activation

    def apply(self, x, *, train, module=None):
        return self.activation(x)


class Add(Layer):
    """≙ keras.layers.Add — residual merges in functional graphs."""

    def apply(self, x, *, train, module=None):
        if not isinstance(x, (list, tuple)) or len(x) < 2:
            raise ValueError("Add expects a list of >= 2 tensors")
        out = x[0]
        for t in x[1:]:
            out = out + t
        return out


class Multiply(Layer):
    def apply(self, x, *, train, module=None):
        if not isinstance(x, (list, tuple)) or len(x) < 2:
            raise ValueError("Multiply expects a list of >= 2 tensors")
        out = x[0]
        for t in x[1:]:
            out = out * t
        return out


class Concatenate(Layer):
    def __init__(self, axis: int = -1):
        self.axis = axis

    def apply(self, x, *, train, module=None):
        if not isinstance(x, (list, tuple)) or len(x) < 2:
            raise ValueError("Concatenate expects a list of >= 2 tensors")
        return jnp.concatenate(list(x), axis=self.axis)


class ZeroPadding2D(Layer):
    """≙ keras.layers.ZeroPadding2D (NHWC)."""

    def __init__(self, padding=1):
        if isinstance(padding, int):
            pads = ((padding, padding), (padding, padding))
        else:
            pads = tuple(_pair(p) for p in padding)
        self.padding = pads

    def apply(self, x, *, train, module=None):
        (t, b), (l, r) = self.padding
        return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))


class Reshape(Layer):
    def __init__(self, target_shape):
        self.target_shape = tuple(target_shape)

    def apply(self, x, *, train, module=None):
        return x.reshape((x.shape[0], *self.target_shape))


class MultiHeadAttention(Layer):
    """≙ keras.layers.MultiHeadAttention with the KERAS weight layout
    (query/key/value kernels (D_in, heads, key_dim), output kernel
    (heads, key_dim, D_out)) so weights map 1:1 onto a real tf_keras
    MHA (TFK/src/layers/attention/multi_head_attention.py). Call:
    ``mha(query, value)`` or ``mha(query, value, key)``."""
    has_train_behavior = True

    def __init__(self, num_heads: int, key_dim: int, dropout: float = 0.0,
                 use_bias: bool = True, output_shape=None,
                 name: str | None = None):
        self.num_heads = int(num_heads)
        self.key_dim = int(key_dim)
        self.dropout = float(dropout)
        self.use_bias = use_bias
        self.output_shape = output_shape
        self.name = name

    def apply(self, x, *, train, module=None):
        if isinstance(x, (list, tuple)):
            q, v = x[0], x[1]
            k = x[2] if len(x) > 2 else v
        else:                       # self-attention on one tensor
            q = v = k = x
        H, hd = self.num_heads, self.key_dim
        out_dim = self.output_shape or q.shape[-1]

        def heads_proj(name):
            return nn.DenseGeneral(features=(H, hd), axis=-1,
                                   use_bias=self.use_bias, name=name)

        qh = heads_proj("query")(q)                 # (B, S, H, hd)
        kh = heads_proj("key")(k)
        vh = heads_proj("value")(v)
        scores = jnp.einsum("bqhd,bkhd->bhqk", qh, kh) / jnp.sqrt(
            jnp.asarray(hd, qh.dtype))
        probs = nn.softmax(scores, axis=-1)
        if train and self.dropout > 0.0:
            rng = module.make_rng("dropout")
            probs = nn.Dropout(self.dropout, deterministic=False)(
                probs, rng=rng)
        o = jnp.einsum("bhqk,bkhd->bqhd", probs, vh)
        return nn.DenseGeneral(features=out_dim, axis=(-2, -1),
                               use_bias=self.use_bias,
                               name="attention_output")(o)


class _LSTMModule(nn.Module):
    """LSTM with the KERAS parameter layout: ``kernel`` (D, 4H),
    ``recurrent_kernel`` (H, 4H), ``bias`` (4H,), gate order
    [i, f, c, o], unit_forget_bias init (forget bias starts at 1) —
    so get/set_weights round-trips with tf_keras LSTM
    (TFK/src/layers/rnn/lstm.py). Time loop via lax.scan."""
    units: int
    use_bias: bool
    unit_forget_bias: bool

    @nn.compact
    def __call__(self, x):
        B, T, D = x.shape
        H = self.units
        kernel = self.param("kernel", nn.initializers.glorot_uniform(),
                            (D, 4 * H))
        rec = self.param("recurrent_kernel",
                         nn.initializers.orthogonal(), (H, 4 * H))
        if self.use_bias:
            if self.unit_forget_bias:
                def bias_init(key, shape, dtype=jnp.float32):
                    return jnp.concatenate([
                        jnp.zeros((H,), dtype), jnp.ones((H,), dtype),
                        jnp.zeros((2 * H,), dtype)])
                bias = self.param("bias", bias_init, (4 * H,))
            else:
                bias = self.param("bias", nn.initializers.zeros,
                                  (4 * H,))
        else:
            bias = None

        xz = jnp.einsum("btd,dh->bth", x, kernel)
        if bias is not None:
            xz = xz + bias

        def step(carry, zt):
            h, c = carry
            z = zt + h @ rec
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i = nn.sigmoid(i)
            f = nn.sigmoid(f)
            g = jnp.tanh(g)
            o = nn.sigmoid(o)
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

        init = (jnp.zeros((B, H), xz.dtype), jnp.zeros((B, H), xz.dtype))
        (h_last, c_last), hs = jax.lax.scan(step, init,
                                            xz.swapaxes(0, 1))
        return hs.swapaxes(0, 1), h_last, c_last


class LSTM(Layer):
    """≙ keras.layers.LSTM (default activations; keras weight layout —
    see _LSTMModule). ``return_sequences``/``return_state`` supported."""

    def __init__(self, units: int, return_sequences: bool = False,
                 return_state: bool = False, use_bias: bool = True,
                 unit_forget_bias: bool = True, name: str | None = None):
        self.units = int(units)
        self.return_sequences = return_sequences
        self.return_state = return_state
        self.use_bias = use_bias
        self.unit_forget_bias = unit_forget_bias
        self.name = name

    @property
    def symbolic_outputs(self):
        return 3 if self.return_state else 1

    def apply(self, x, *, train, module=None):
        seq, h, c = _LSTMModule(self.units, self.use_bias,
                                self.unit_forget_bias,
                                name=self.name)(x)
        out = seq if self.return_sequences else h
        if self.return_state:
            return [out, h, c]
        return out


class _GRUModule(nn.Module):
    """GRU with the KERAS v2 layout (reset_after=True): ``kernel``
    (D, 3H), ``recurrent_kernel`` (H, 3H), ``bias`` (2, 3H) [input row,
    recurrent row], gate order [z, r, h]
    (TFK/src/layers/rnn/gru.py)."""
    units: int
    use_bias: bool

    @nn.compact
    def __call__(self, x):
        B, T, D = x.shape
        H = self.units
        kernel = self.param("kernel", nn.initializers.glorot_uniform(),
                            (D, 3 * H))
        rec = self.param("recurrent_kernel",
                         nn.initializers.orthogonal(), (H, 3 * H))
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros, (2, 3 * H))
            b_in, b_rec = bias[0], bias[1]
        else:
            b_in = b_rec = jnp.zeros((3 * H,), x.dtype)

        xz = jnp.einsum("btd,dh->bth", x, kernel) + b_in

        def step(h, zt):
            hz = h @ rec + b_rec
            xz_z, xz_r, xz_h = jnp.split(zt, 3, axis=-1)
            hz_z, hz_r, hz_h = jnp.split(hz, 3, axis=-1)
            z = nn.sigmoid(xz_z + hz_z)
            r = nn.sigmoid(xz_r + hz_r)
            hh = jnp.tanh(xz_h + r * hz_h)     # reset_after semantics
            h2 = z * h + (1.0 - z) * hh
            return h2, h2

        h_last, hs = jax.lax.scan(
            step, jnp.zeros((B, H), xz.dtype), xz.swapaxes(0, 1))
        return hs.swapaxes(0, 1), h_last


class GRU(Layer):
    """≙ keras.layers.GRU (v2 defaults: reset_after=True, keras weight
    layout — see _GRUModule)."""

    def __init__(self, units: int, return_sequences: bool = False,
                 return_state: bool = False, use_bias: bool = True,
                 name: str | None = None):
        self.units = int(units)
        self.return_sequences = return_sequences
        self.return_state = return_state
        self.use_bias = use_bias
        self.name = name

    @property
    def symbolic_outputs(self):
        return 2 if self.return_state else 1

    def apply(self, x, *, train, module=None):
        seq, h = _GRUModule(self.units, self.use_bias,
                            name=self.name)(x)
        out = seq if self.return_sequences else h
        if self.return_state:
            return [out, h]
        return out


class _SimpleRNNModule(nn.Module):
    """Vanilla RNN, keras layout: kernel (D, H), recurrent_kernel
    (H, H), bias (H,) (TFK/src/layers/rnn/simple_rnn.py)."""
    units: int
    use_bias: bool

    @nn.compact
    def __call__(self, x):
        B, T, D = x.shape
        H = self.units
        kernel = self.param("kernel", nn.initializers.glorot_uniform(),
                            (D, H))
        rec = self.param("recurrent_kernel",
                         nn.initializers.orthogonal(), (H, H))
        xz = jnp.einsum("btd,dh->bth", x, kernel)
        if self.use_bias:
            xz = xz + self.param("bias", nn.initializers.zeros, (H,))

        def step(h, zt):
            h2 = jnp.tanh(zt + h @ rec)
            return h2, h2

        h_last, hs = jax.lax.scan(
            step, jnp.zeros((B, H), xz.dtype), xz.swapaxes(0, 1))
        return hs.swapaxes(0, 1), h_last


class SimpleRNN(Layer):
    """≙ keras.layers.SimpleRNN (tanh)."""

    def __init__(self, units: int, return_sequences: bool = False,
                 use_bias: bool = True, name: str | None = None):
        self.units = int(units)
        self.return_sequences = return_sequences
        self.use_bias = use_bias
        self.name = name

    def apply(self, x, *, train, module=None):
        seq, h = _SimpleRNNModule(self.units, self.use_bias,
                                  name=self.name)(x)
        return seq if self.return_sequences else h


class Bidirectional(Layer):
    """≙ keras.layers.Bidirectional (concat merge) over a shim RNN
    layer. The wrapped layer's config is duplicated for the backward
    direction (independent weights, like keras)."""

    def __init__(self, layer, merge_mode: str = "concat"):
        if not isinstance(layer, (LSTM, GRU, SimpleRNN)):
            raise TypeError(
                "Bidirectional wraps a shim LSTM/GRU/SimpleRNN layer")
        if merge_mode != "concat":
            raise NotImplementedError(
                "Bidirectional supports merge_mode='concat'")
        self.layer = layer
        self.backward_layer = type(layer).from_config(layer.get_config())
        self.backward_layer.name = (layer.name + "_backward"
                                    if layer.name else None)
        self.merge_mode = merge_mode

    @property
    def symbolic_outputs(self):
        n = self.layer.symbolic_outputs
        return 1 if n == 1 else 1 + 2 * (n - 1)   # out + fwd/bwd states

    def apply(self, x, *, train, module=None):
        fwd = self.layer.apply(x, train=train, module=module)
        bwd = self.backward_layer.apply(x[:, ::-1], train=train,
                                        module=module)
        if isinstance(fwd, list):          # return_state
            out_b = bwd[0]
            if self.layer.return_sequences:
                out_b = out_b[:, ::-1]
            return [jnp.concatenate([fwd[0], out_b], axis=-1),
                    *fwd[1:], *bwd[1:]]
        if self.layer.return_sequences:
            bwd = bwd[:, ::-1]
        return jnp.concatenate([fwd, bwd], axis=-1)

    def get_config(self):
        raise ValueError(
            "Bidirectional serialization is not supported; rebuild in "
            "code and use load_weights")


class _SequentialModule(nn.Module):
    """One flax module applying the shim layers in order."""
    layer_stack: tuple
    train: bool

    @nn.compact
    def __call__(self, x):
        for layer in self.layer_stack:
            x = layer.apply(x, train=self.train, module=self)
        return x


class Sequential(Model):
    """≙ tf_keras.Sequential — a Model built from a layer list.

    Builds eagerly when any layer pins an input shape (keras Input /
    ``input_shape=`` kwarg), otherwise lazily on the first fit/call.
    """

    @staticmethod
    def _as_layer(lyr):
        """Accept keras.Input symbolic tensors in the layer list (the
        tf_keras Sequential convention) by converting them to
        InputLayer; everything else must be a shim Layer."""
        from distributed_tensorflow_tpu.training.functional import (
            SymbolicTensor)
        if isinstance(lyr, SymbolicTensor):
            if lyr.layer is not None:
                raise TypeError(
                    "Sequential only accepts keras.Input symbolic "
                    "tensors, not intermediate graph tensors — use "
                    "keras.Model(inputs, outputs) for functional graphs")
            return InputLayer(lyr.shape)
        if not isinstance(lyr, Layer):
            raise TypeError(
                f"Sequential expects shim layers "
                f"(distributed_tensorflow_tpu.keras.layers), got "
                f"{type(lyr).__name__}")
        if lyr.symbolic_outputs != 1:
            raise ValueError(
                f"{type(lyr).__name__} with return_state=True has "
                "multiple outputs; Sequential layers must have exactly "
                "one output (use the functional API)")
        return lyr

    def __init__(self, layers: Sequence[Layer] | None = None, *,
                 seed: int = 0):
        stack = tuple(self._as_layer(lyr) for lyr in (layers or ()))
        super().__init__(
            _SequentialModule(layer_stack=stack, train=True),
            eval_module=_SequentialModule(layer_stack=stack, train=False),
            seed=seed)
        self.layers = list(stack)
        shape = next((lyr.compute_input_shape() for lyr in stack
                      if lyr.compute_input_shape()), None)
        if shape is not None:
            self.build(jnp.zeros((1, *shape), jnp.float32))

    def add(self, layer: Layer):
        """≙ keras Sequential.add: incremental construction, tf_keras
        semantics — adding to an already-built stack PRESERVES the
        existing layers' weights (flax auto-names are call-order
        stable, so appending a layer never renames earlier ones; the
        rebuilt parameter tree is re-seeded only for the new layer and
        the old subtrees are copied back in). The optimizer state is
        re-initialized for the grown parameter set on the next
        compile/fit, matching keras's lazy slot creation."""
        layer = self._as_layer(layer)
        old_params = old_model_state = rebuild_sample = None
        if self._built and self._state is not None:
            old_params = self._state["params"]
            old_model_state = self._state.get("model_state", {})
            rebuild_sample = getattr(self, "_build_sample", None)
        self.layers.append(layer)
        stack = tuple(self.layers)
        self.module = _SequentialModule(layer_stack=stack, train=True)
        self.eval_module = _SequentialModule(layer_stack=stack,
                                             train=False)
        self._built = False
        self._train_fn = self._eval_fn = self._predict_fn = None
        shape = next((lyr.compute_input_shape() for lyr in stack
                      if lyr.compute_input_shape()), None)
        if rebuild_sample is not None:
            self.build(rebuild_sample)
        elif shape is not None:
            self.build(jnp.zeros((1, *shape), jnp.float32))
        if old_params is not None and self._built:
            merged = dict(self._state["params"])
            for k in old_params:
                if k in merged:
                    merged[k] = old_params[k]
            self._state["params"] = merged
            new_ms = dict(self._state.get("model_state", {}))
            for coll, sub in dict(old_model_state or {}).items():
                cur = dict(new_ms.get(coll, {}))
                for k in sub:
                    if k in cur:
                        cur[k] = sub[k]
                new_ms[coll] = cur
            self._state["model_state"] = new_ms
            if self._compiled:
                self._state["opt_state"] = self.strategy.init_state(
                    lambda: self._tx.init(self._state["params"]))


# keras.layers.Input is the same symbolic-tensor factory as keras.Input
# (tf_keras exposes it in both places); imported at the bottom because
# functional.py is import-independent of this module (no cycle).
from distributed_tensorflow_tpu.training.functional import Input  # noqa: E402,F401
