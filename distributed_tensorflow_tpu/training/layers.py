"""tf.keras.layers-shaped layer shim backed by flax.

≙ TFK/src/engine/base_layer.py + TFK/src/layers/ (Dense:
TFK/src/layers/core/dense.py, Conv2D: convolutional/base_conv.py,
BatchNormalization: normalization/batch_normalization.py, …) — the
minimal surface that lets a verbatim reference-style script (configs
#1-#3: MNIST CNN / CNN classifiers / embedding+dense stacks) run
against this framework with only its import line changed
(``from distributed_tensorflow_tpu import keras``).

Every layer keeps the KERAS constructor signature and the KERAS weight
layout (Conv kernels (H, W, Cin, Cout); Dense kernels (in, out)) — the
layouts flax already shares, as pinned by tests/test_reference_parity —
so ``get_weights``/``set_weights`` interoperate with real tf_keras
models. ``Sequential`` composes the layers into one flax module and IS
a ``training.Model``: compile/fit/evaluate/predict come from the
SPMD training loop (training/model.py), not a port of the Keras one.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_tensorflow_tpu.training.model import Model

_ACTIVATIONS = {
    None: lambda x: x,
    "linear": lambda x: x,
    "relu": nn.relu,
    "gelu": nn.gelu,
    "tanh": jnp.tanh,
    "sigmoid": nn.sigmoid,
    "softmax": lambda x: nn.softmax(x, axis=-1),
    "silu": nn.silu,
    "swish": nn.silu,
}


def _activation(identifier) -> Callable:
    if callable(identifier):
        return identifier
    try:
        return _ACTIVATIONS[identifier]
    except KeyError:
        raise ValueError(
            f"Unknown activation {identifier!r}; known: "
            f"{sorted(k for k in _ACTIVATIONS if k)}") from None


def _pair(v):
    return tuple(v) if isinstance(v, (tuple, list)) else (v, v)


class Layer:
    """Base shim layer: a configuration object whose ``apply`` runs
    inside the Sequential flax module's compact scope (so flax handles
    parameter creation/naming). ``module`` is the enclosing flax module
    (for layers that need rngs, e.g. Dropout)."""

    #: set on layers like Dropout/BatchNormalization that behave
    #: differently in training
    has_train_behavior = False

    def apply(self, x, *, train: bool, module=None):
        raise NotImplementedError

    def compute_input_shape(self):
        """(sample-less) input shape if the layer pins one, else None."""
        return getattr(self, "input_shape", None)


@dataclasses.dataclass
class Input(Layer):
    """≙ keras.Input / InputLayer — records the per-sample input shape
    so Sequential can build eagerly."""
    shape: Sequence[int]

    def __post_init__(self):
        self.input_shape = tuple(self.shape)

    def apply(self, x, *, train, module=None):
        return x


class InputLayer(Input):
    def __init__(self, input_shape):
        super().__init__(shape=input_shape)


class Dense(Layer):
    def __init__(self, units: int, activation=None, use_bias: bool = True,
                 input_shape=None, name: str | None = None):
        self.units = int(units)
        self.activation = _activation(activation)
        self.use_bias = use_bias
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        x = nn.Dense(self.units, use_bias=self.use_bias,
                     name=self.name)(x)
        return self.activation(x)


class Conv2D(Layer):
    def __init__(self, filters: int, kernel_size, strides=1,
                 padding: str = "valid", activation=None,
                 use_bias: bool = True, input_shape=None,
                 name: str | None = None):
        self.filters = int(filters)
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding.upper()
        self.activation = _activation(activation)
        self.use_bias = use_bias
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        x = nn.Conv(self.filters, self.kernel_size, strides=self.strides,
                    padding=self.padding, use_bias=self.use_bias,
                    name=self.name)(x)
        return self.activation(x)


class MaxPooling2D(Layer):
    def __init__(self, pool_size=2, strides=None, padding: str = "valid"):
        self.pool_size = _pair(pool_size)
        self.strides = _pair(strides) if strides is not None \
            else self.pool_size
        self.padding = padding.upper()

    def apply(self, x, *, train, module=None):
        return nn.max_pool(x, self.pool_size, strides=self.strides,
                           padding=self.padding)


class AveragePooling2D(MaxPooling2D):
    def apply(self, x, *, train, module=None):
        return nn.avg_pool(x, self.pool_size, strides=self.strides,
                           padding=self.padding)


class GlobalAveragePooling2D(Layer):
    def apply(self, x, *, train, module=None):
        return jnp.mean(x, axis=(1, 2))


class Flatten(Layer):
    def apply(self, x, *, train, module=None):
        return x.reshape((x.shape[0], -1))


class Dropout(Layer):
    has_train_behavior = True

    def __init__(self, rate: float, seed: int | None = None):
        self.rate = float(rate)
        self.seed = seed

    def apply(self, x, *, train, module=None):
        if not train or self.rate == 0.0:
            return x
        rng = module.make_rng("dropout")
        if self.seed is not None:       # keras per-layer seed honored
            rng = jax.random.fold_in(rng, self.seed)
        return nn.Dropout(self.rate, deterministic=False)(x, rng=rng)


class BatchNormalization(Layer):
    """≙ keras BatchNormalization: running averages live in the flax
    ``batch_stats`` collection, which training.Model carries as
    model_state (the Keras non-trainable-weights analogue)."""
    has_train_behavior = True

    def __init__(self, momentum: float = 0.99, epsilon: float = 1e-3,
                 name: str | None = None):
        self.momentum = float(momentum)
        self.epsilon = float(epsilon)
        self.name = name

    def apply(self, x, *, train, module=None):
        return nn.BatchNorm(use_running_average=not train,
                            momentum=self.momentum, epsilon=self.epsilon,
                            name=self.name)(x)


class LayerNormalization(Layer):
    def __init__(self, epsilon: float = 1e-3, name: str | None = None):
        self.epsilon = float(epsilon)
        self.name = name

    def apply(self, x, *, train, module=None):
        return nn.LayerNorm(epsilon=self.epsilon, name=self.name)(x)


class Embedding(Layer):
    def __init__(self, input_dim: int, output_dim: int, input_shape=None,
                 name: str | None = None):
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.input_shape = tuple(input_shape) if input_shape else None
        self.name = name

    def apply(self, x, *, train, module=None):
        return nn.Embed(self.input_dim, self.output_dim,
                        name=self.name)(x.astype(jnp.int32))


class ReLU(Layer):
    def apply(self, x, *, train, module=None):
        return nn.relu(x)


class Softmax(Layer):
    def apply(self, x, *, train, module=None):
        return nn.softmax(x, axis=-1)


class Activation(Layer):
    def __init__(self, activation):
        self.activation = _activation(activation)

    def apply(self, x, *, train, module=None):
        return self.activation(x)


class _SequentialModule(nn.Module):
    """One flax module applying the shim layers in order."""
    layer_stack: tuple
    train: bool

    @nn.compact
    def __call__(self, x):
        for layer in self.layer_stack:
            x = layer.apply(x, train=self.train, module=self)
        return x


class Sequential(Model):
    """≙ tf_keras.Sequential — a Model built from a layer list.

    Builds eagerly when any layer pins an input shape (keras Input /
    ``input_shape=`` kwarg), otherwise lazily on the first fit/call.
    """

    def __init__(self, layers: Sequence[Layer] | None = None, *,
                 seed: int = 0):
        stack = tuple(layers or ())
        for lyr in stack:
            if not isinstance(lyr, Layer):
                raise TypeError(
                    f"Sequential expects shim layers "
                    f"(distributed_tensorflow_tpu.keras.layers), got "
                    f"{type(lyr).__name__}")
        super().__init__(
            _SequentialModule(layer_stack=stack, train=True),
            eval_module=_SequentialModule(layer_stack=stack, train=False),
            seed=seed)
        self.layers = list(stack)
        shape = next((lyr.compute_input_shape() for lyr in stack
                      if lyr.compute_input_shape()), None)
        if shape is not None:
            self.build(jnp.zeros((1, *shape), jnp.float32))

    def add(self, layer: Layer):
        """≙ keras Sequential.add: incremental construction. Adding to
        an already-built stack re-initializes the parameters (the keras
        incremental-build pattern adds layers BEFORE training, so fresh
        init is indistinguishable there)."""
        if not isinstance(layer, Layer):
            raise TypeError(
                f"Sequential expects shim layers "
                f"(distributed_tensorflow_tpu.keras.layers), got "
                f"{type(layer).__name__}")
        self.layers.append(layer)
        stack = tuple(self.layers)
        self.module = _SequentialModule(layer_stack=stack, train=True)
        self.eval_module = _SequentialModule(layer_stack=stack,
                                             train=False)
        self._built = False
        self._train_fn = self._eval_fn = self._predict_fn = None
        shape = next((lyr.compute_input_shape() for lyr in stack
                      if lyr.compute_input_shape()), None)
        if shape is not None:
            self.build(jnp.zeros((1, *shape), jnp.float32))
