"""Training callbacks for Model.fit.

≙ tf_keras/src/callbacks.py: Callback/CallbackList/History/EarlyStopping/
ModelCheckpoint, and BackupAndRestore backed by an epoch-granular training
state (≙ tf_keras/src/distribute/worker_training_state.py:34 back_up/
restore — the reference checkpoints {weights, optimizer state, epoch} to a
backup dir every epoch and deletes it when fit() completes).
"""

from __future__ import annotations

import os
import shutil

import numpy as np


class Callback:
    """Base callback (≙ tf_keras Callback). Overridable hooks only."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, batch, logs=None):
        pass

    def on_train_batch_end(self, batch, logs=None):
        pass

    def on_test_begin(self, logs=None):
        pass

    def on_test_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model, params):
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            cb.set_model(model)
            cb.set_params(params)

    def _call(self, hook, *args):
        for cb in self.callbacks:
            getattr(cb, hook)(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, batch, logs=None):
        self._call("on_train_batch_begin", batch, logs)

    def on_train_batch_end(self, batch, logs=None):
        self._call("on_train_batch_end", batch, logs)

    def on_test_begin(self, logs=None):
        self._call("on_test_begin", logs)

    def on_test_end(self, logs=None):
        self._call("on_test_end", logs)


class History(Callback):
    """Records epoch logs; Model.fit returns it (≙ tf_keras History)."""

    def on_train_begin(self, logs=None):
        if not hasattr(self, "history"):
            self.history = {}
            self.epoch = []

    def on_epoch_end(self, epoch, logs=None):
        self.epoch.append(epoch)
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ProgbarLogger(Callback):
    """One line per epoch (the TPU-friendly verbose=1)."""

    def on_epoch_end(self, epoch, logs=None):
        items = "  ".join(f"{k}={v:.4f}" for k, v in (logs or {}).items()
                          if isinstance(v, (int, float, np.floating)))
        epochs = self.params.get("epochs", "?")
        print(f"epoch {epoch + 1}/{epochs}  {items}", flush=True)


def _improved(current, best, mode: str, min_delta: float) -> bool:
    if mode == "min":
        return current < best - min_delta
    return current > best + min_delta


class EarlyStopping(Callback):
    """≙ tf_keras EarlyStopping (monitor/patience/min_delta/mode +
    restore_best_weights)."""

    def __init__(self, monitor="val_loss", min_delta=0.0, patience=0,
                 mode="auto", restore_best_weights=False, baseline=None):
        super().__init__()
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        self.restore_best_weights = restore_best_weights
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.stopped_epoch = 0
        self.best = (self.baseline if self.baseline is not None
                     else (np.inf if self.mode == "min" else -np.inf))
        self.best_weights = None

    def on_epoch_end(self, epoch, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            return
        if _improved(current, self.best, self.mode, self.min_delta):
            self.best = current
            self.wait = 0
            if self.restore_best_weights:
                self.best_weights = self.model.get_weights()
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True
                if self.restore_best_weights and self.best_weights is not None:
                    self.model.set_weights(self.best_weights)


class ModelCheckpoint(Callback):
    """≙ tf_keras ModelCheckpoint: save weights each epoch, optionally only
    on monitored improvement. ``filepath`` may contain ``{epoch}``."""

    def __init__(self, filepath, monitor="val_loss", save_best_only=False,
                 mode="auto", save_weights_only=False):
        # save_weights_only default matches tf_keras (False = full
        # model); plain training.Model users (no serializable
        # architecture) should pass save_weights_only=True.
        super().__init__()
        self.filepath = str(filepath)
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.save_weights_only = save_weights_only
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = np.inf if self.mode == "min" else -np.inf

    def on_train_begin(self, logs=None):
        # Fail FAST when full-model saving can't work for this model —
        # not after a full epoch of compute. Attempting the actual
        # architecture serialization catches both unsupported model
        # kinds (plain training.Model) AND unserializable layers
        # (Lambda) up front.
        if not self.save_weights_only:
            from distributed_tensorflow_tpu.training.saving import (
                model_config)
            try:
                model_config(self.model)
            except (NotImplementedError, ValueError) as e:
                raise type(e)(
                    f"ModelCheckpoint(save_weights_only=False) cannot "
                    f"serialize this model ({e}); pass "
                    "save_weights_only=True") from e

    def on_epoch_end(self, epoch, logs=None):
        path = self.filepath.format(epoch=epoch + 1)
        if self.save_best_only:
            current = (logs or {}).get(self.monitor)
            if current is None or not _improved(current, self.best,
                                                self.mode, 0.0):
                return
            self.best = current
        if self.save_weights_only:
            self.model.save_weights(path)
        else:
            self.model.save(path)       # full model (arch + weights)


class LearningRateScheduler(Callback):
    """≙ tf_keras LearningRateScheduler. Requires the optimizer to expose
    a mutable learning rate — compile with an optax
    ``inject_hyperparams``-wrapped optimizer or pass ``learning_rate=`` to
    Model.compile (which wraps for you)."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        lr = self.schedule(epoch, self.model.learning_rate)
        self.model.learning_rate = lr


class BackupAndRestore(Callback):
    """Epoch-granular fault-tolerance for fit().

    ≙ tf_keras BackupAndRestore + worker_training_state.py:34: at the end
    of every epoch, back up {weights, optimizer state, completed epoch} to
    ``backup_dir``; when fit() starts, restore if a backup exists and
    resume from the next epoch; delete the backup when training completes
    normally.
    """

    def __init__(self, backup_dir: str):
        super().__init__()
        self.backup_dir = str(backup_dir)

    def on_train_begin(self, logs=None):
        self.model._maybe_restore_backup(self.backup_dir)

    def on_epoch_end(self, epoch, logs=None):
        self.model._back_up(self.backup_dir, epoch)

    def on_train_end(self, logs=None):
        shutil.rmtree(self.backup_dir, ignore_errors=True)


class TensorBoard(Callback):
    """Stream epoch metrics (and optionally weight histograms) to
    TensorBoard event files (≙ tf_keras.callbacks.TensorBoard, backed by
    utils/summary.SummaryWriter — no TF dependency).

    Layout matches Keras: ``logdir/train`` for training metrics,
    ``logdir/validation`` for ``val_*`` metrics.
    """

    def __init__(self, log_dir: str = "logs",
                 histogram_freq: int = 0):
        super().__init__()
        self.log_dir = log_dir
        self.histogram_freq = histogram_freq
        self._writers = {}

    def _writer(self, name: str):
        """Lazy per-run writer: no spurious empty 'validation' run when
        fit() has no validation data (matches Keras)."""
        if name not in self._writers:
            from distributed_tensorflow_tpu.utils.summary import \
                SummaryWriter
            self._writers[name] = SummaryWriter(
                os.path.join(self.log_dir, name))
        return self._writers[name]

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            if not isinstance(v, (int, float, np.floating)):
                continue
            if k.startswith("val_"):
                self._writer("validation").scalar(
                    f"epoch_{k[4:]}", float(v), epoch)
            else:
                self._writer("train").scalar(f"epoch_{k}", float(v), epoch)
        if (self.histogram_freq and self.model is not None
                and epoch % self.histogram_freq == 0):   # Keras phase
            state = getattr(self.model, "_state", None) or {}
            params = state.get("params")
            if params is not None:
                import jax
                flat = jax.tree_util.tree_flatten_with_path(params)[0]
                for path, leaf in flat:
                    name = "/".join(getattr(p, "key", str(p))
                                    for p in path)
                    self._writer("train").histogram(
                        name, np.asarray(leaf), epoch)
        for w in self._writers.values():
            w.flush()

    def on_train_end(self, logs=None):
        for w in self._writers.values():
            w.close()
        self._writers = {}
