"""Training callbacks for Model.fit.

≙ tf_keras/src/callbacks.py: Callback/CallbackList/History/EarlyStopping/
ModelCheckpoint, and BackupAndRestore backed by an epoch-granular training
state (≙ tf_keras/src/distribute/worker_training_state.py:34 back_up/
restore — the reference checkpoints {weights, optimizer state, epoch} to a
backup dir every epoch and deletes it when fit() completes).
"""

from __future__ import annotations

import os
import shutil

import numpy as np


class Callback:
    """Base callback (≙ tf_keras Callback). Overridable hooks only."""

    #: How often this callback needs per-batch LOGS. Computing batch
    #: logs materializes every metric on the host (a device sync that
    #: defeats async dispatch), so Model.fit only builds them on steps
    #: where some overriding callback's interval divides the step.
    batch_log_interval = 1

    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, batch, logs=None):
        pass

    def on_train_batch_end(self, batch, logs=None):
        pass

    def on_test_begin(self, logs=None):
        pass

    def on_test_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks, model, params):
        self.callbacks = list(callbacks or [])
        for cb in self.callbacks:
            cb.set_model(model)
            cb.set_params(params)

    def _call(self, hook, *args):
        for cb in self.callbacks:
            getattr(cb, hook)(*args)

    def on_train_begin(self, logs=None):
        self._call("on_train_begin", logs)

    def on_train_end(self, logs=None):
        self._call("on_train_end", logs)

    def on_epoch_begin(self, epoch, logs=None):
        self._call("on_epoch_begin", epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        self._call("on_epoch_end", epoch, logs)

    def on_train_batch_begin(self, batch, logs=None):
        self._call("on_train_batch_begin", batch, logs)

    def on_train_batch_end(self, batch, logs=None):
        self._call("on_train_batch_end", batch, logs)

    def on_test_begin(self, logs=None):
        self._call("on_test_begin", logs)

    def on_test_end(self, logs=None):
        self._call("on_test_end", logs)


class History(Callback):
    """Records epoch logs; Model.fit returns it (≙ tf_keras History)."""

    def on_train_begin(self, logs=None):
        if not hasattr(self, "history"):
            self.history = {}
            self.epoch = []

    def on_epoch_end(self, epoch, logs=None):
        self.epoch.append(epoch)
        for k, v in (logs or {}).items():
            self.history.setdefault(k, []).append(v)


class ProgbarLogger(Callback):
    """One line per epoch (the TPU-friendly verbose=1)."""

    def on_epoch_end(self, epoch, logs=None):
        items = "  ".join(f"{k}={v:.4f}" for k, v in (logs or {}).items()
                          if isinstance(v, (int, float, np.floating)))
        epochs = self.params.get("epochs", "?")
        print(f"epoch {epoch + 1}/{epochs}  {items}", flush=True)


def _improved(current, best, mode: str, min_delta: float) -> bool:
    if mode == "min":
        return current < best - min_delta
    return current > best + min_delta


class ReduceLROnPlateau(Callback):
    """≙ tf_keras ReduceLROnPlateau: multiply the (mutable) learning
    rate by ``factor`` after ``patience`` epochs without monitored
    improvement; stop at ``min_lr``; ``cooldown`` epochs pause the
    patience counter after each reduction.

    Requires a FLOAT learning rate: compiling with a ``schedules.*``
    callable makes ``model.learning_rate`` schedule-driven
    (inject_hyperparams re-evaluates it every update), so the reduction
    would be silently clobbered — the learning_rate setter raises on
    that combination instead (≙ tf_keras, which also fails loudly)."""

    def __init__(self, monitor="val_loss", factor=0.1, patience=10,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0.0,
                 verbose=0):
        super().__init__()
        if factor >= 1.0:
            raise ValueError("ReduceLROnPlateau requires factor < 1.0")
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.min_delta = abs(min_delta)
        self.cooldown = int(cooldown)
        self.min_lr = float(min_lr)
        self.verbose = verbose
        self._reset()

    def _reset(self):
        self.best = np.inf if self.mode == "min" else -np.inf
        self.wait = 0
        self.cooldown_counter = 0

    def on_train_begin(self, logs=None):
        self._reset()       # reusable across fit() calls, like keras

    def on_epoch_end(self, epoch, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            return
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.wait = 0
        if _improved(current, self.best, self.mode, self.min_delta):
            self.best = current
            self.wait = 0
        elif self.cooldown_counter <= 0:
            self.wait += 1
            if self.wait >= self.patience:
                old = self.model.learning_rate
                new = max(old * self.factor, self.min_lr)
                if new < old:
                    self.model.learning_rate = new
                    if self.verbose:
                        print(f"ReduceLROnPlateau: epoch {epoch + 1}: "
                              f"lr -> {new:.3e}", flush=True)
                self.cooldown_counter = self.cooldown
                self.wait = 0


class CSVLogger(Callback):
    """≙ tf_keras CSVLogger: one row of epoch logs per epoch."""

    def __init__(self, filename, separator=",", append=False):
        super().__init__()
        self.filename = str(filename)
        self.sep = separator
        self.append = append
        self._file = None
        self._keys = None

    def on_train_begin(self, logs=None):
        import os
        # append mode resumes an existing file WITHOUT a second header
        # (tf_keras checks existing content the same way)
        has_content = (self.append and os.path.exists(self.filename)
                       and os.path.getsize(self.filename) > 0)
        self._file = open(self.filename,
                          "a" if self.append else "w", buffering=1)
        self._keys = None
        self._header_written = has_content

    def on_epoch_end(self, epoch, logs=None):
        logs = dict(logs or {})
        if self._keys is None:
            self._keys = sorted(logs)
            if not self._header_written:
                self._file.write(
                    self.sep.join(["epoch"] + self._keys) + "\n")
                self._header_written = True
        row = [str(epoch)] + [f"{logs.get(k, '')}" for k in self._keys]
        self._file.write(self.sep.join(row) + "\n")

    def on_train_end(self, logs=None):
        if self._file is not None:
            self._file.close()
            self._file = None


class TerminateOnNaN(Callback):
    """≙ tf_keras TerminateOnNaN — WITH ONE DELIBERATE DEVIATION:
    the loss is checked every ``check_every`` batches (default 10), not
    every batch like tf_keras. The epoch loss metric is a running mean,
    so one NaN batch poisons it permanently and a sparse check still
    catches it within ``check_every`` steps — without forcing the
    per-batch host-device metric sync that defeats async dispatch.

    The cost of the sparse default: up to ``check_every - 1`` additional
    optimizer steps run on NaN parameters before training stops, so
    params (and any checkpoint taken in that window) may be poisoned.
    Pass ``check_every=1`` for tf_keras-exact behavior when debugging
    divergence or checkpointing every batch; see README "Training
    callbacks" for the trade-off.
    """

    def __init__(self, check_every: int = 10):
        super().__init__()
        self.batch_log_interval = max(1, int(check_every))

    def on_train_batch_end(self, batch, logs=None):
        loss = (logs or {}).get("loss")
        if loss is not None and not np.isfinite(loss):
            print(f"TerminateOnNaN: batch {batch}: invalid loss "
                  f"{loss}, terminating", flush=True)
            self.model.stop_training = True


class EarlyStopping(Callback):
    """≙ tf_keras EarlyStopping (monitor/patience/min_delta/mode +
    restore_best_weights)."""

    def __init__(self, monitor="val_loss", min_delta=0.0, patience=0,
                 mode="auto", restore_best_weights=False, baseline=None):
        super().__init__()
        self.monitor = monitor
        self.min_delta = abs(min_delta)
        self.patience = patience
        self.restore_best_weights = restore_best_weights
        self.baseline = baseline
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.stopped_epoch = 0
        self.best = (self.baseline if self.baseline is not None
                     else (np.inf if self.mode == "min" else -np.inf))
        self.best_weights = None

    def on_epoch_end(self, epoch, logs=None):
        current = (logs or {}).get(self.monitor)
        if current is None:
            return
        if _improved(current, self.best, self.mode, self.min_delta):
            self.best = current
            self.wait = 0
            if self.restore_best_weights:
                self.best_weights = self.model.get_weights()
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = epoch
                self.model.stop_training = True
                if self.restore_best_weights and self.best_weights is not None:
                    self.model.set_weights(self.best_weights)


class ModelCheckpoint(Callback):
    """≙ tf_keras ModelCheckpoint: save weights each epoch, optionally only
    on monitored improvement. ``filepath`` may contain ``{epoch}``."""

    def __init__(self, filepath, monitor="val_loss", save_best_only=False,
                 mode="auto", save_weights_only=False):
        # save_weights_only default matches tf_keras (False = full
        # model); plain training.Model users (no serializable
        # architecture) should pass save_weights_only=True.
        super().__init__()
        self.filepath = str(filepath)
        self.monitor = monitor
        self.save_best_only = save_best_only
        self.save_weights_only = save_weights_only
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self.best = np.inf if self.mode == "min" else -np.inf

    def on_train_begin(self, logs=None):
        # Fail FAST when full-model saving can't work for this model —
        # not after a full epoch of compute. Attempting the actual
        # architecture serialization catches both unsupported model
        # kinds (plain training.Model) AND unserializable layers
        # (Lambda) up front.
        if not self.save_weights_only:
            from distributed_tensorflow_tpu.training.saving import (
                model_config)
            try:
                model_config(self.model)
            except (NotImplementedError, ValueError) as e:
                raise type(e)(
                    f"ModelCheckpoint(save_weights_only=False) cannot "
                    f"serialize this model ({e}); pass "
                    "save_weights_only=True") from e

    def on_epoch_end(self, epoch, logs=None):
        path = self.filepath.format(epoch=epoch + 1)
        if self.save_best_only:
            current = (logs or {}).get(self.monitor)
            if current is None or not _improved(current, self.best,
                                                self.mode, 0.0):
                return
            self.best = current
        if self.save_weights_only:
            self.model.save_weights(path)
        else:
            self.model.save(path)       # full model (arch + weights)


class LearningRateScheduler(Callback):
    """≙ tf_keras LearningRateScheduler. Requires the optimizer to expose
    a mutable learning rate — compile with an optax
    ``inject_hyperparams``-wrapped optimizer or pass ``learning_rate=`` to
    Model.compile (which wraps for you)."""

    def __init__(self, schedule):
        super().__init__()
        self.schedule = schedule

    def on_epoch_begin(self, epoch, logs=None):
        lr = self.schedule(epoch, self.model.learning_rate)
        self.model.learning_rate = lr


class BackupAndRestore(Callback):
    """Epoch-granular fault-tolerance for fit().

    ≙ tf_keras BackupAndRestore + worker_training_state.py:34: at the end
    of every epoch, back up {weights, optimizer state, completed epoch} to
    ``backup_dir``; when fit() starts, restore if a backup exists and
    resume from the next epoch; delete the backup when training completes
    normally.
    """

    def __init__(self, backup_dir: str):
        super().__init__()
        self.backup_dir = str(backup_dir)

    def on_train_begin(self, logs=None):
        self.model._maybe_restore_backup(self.backup_dir)

    def on_epoch_end(self, epoch, logs=None):
        self.model._back_up(self.backup_dir, epoch)

    def on_train_end(self, logs=None):
        shutil.rmtree(self.backup_dir, ignore_errors=True)


class TensorBoard(Callback):
    """Stream epoch metrics (and optionally weight histograms) to
    TensorBoard event files (≙ tf_keras.callbacks.TensorBoard, backed by
    utils/summary.SummaryWriter — no TF dependency).

    Layout matches Keras: ``logdir/train`` for training metrics,
    ``logdir/validation`` for ``val_*`` metrics.
    """

    def __init__(self, log_dir: str = "logs",
                 histogram_freq: int = 0):
        super().__init__()
        self.log_dir = log_dir
        self.histogram_freq = histogram_freq
        self._writers = {}

    def _writer(self, name: str):
        """Lazy per-run writer: no spurious empty 'validation' run when
        fit() has no validation data (matches Keras)."""
        if name not in self._writers:
            from distributed_tensorflow_tpu.utils.summary import \
                SummaryWriter
            self._writers[name] = SummaryWriter(
                os.path.join(self.log_dir, name))
        return self._writers[name]

    def on_epoch_end(self, epoch, logs=None):
        for k, v in (logs or {}).items():
            if not isinstance(v, (int, float, np.floating)):
                continue
            if k.startswith("val_"):
                self._writer("validation").scalar(
                    f"epoch_{k[4:]}", float(v), epoch)
            else:
                self._writer("train").scalar(f"epoch_{k}", float(v), epoch)
        if (self.histogram_freq and self.model is not None
                and epoch % self.histogram_freq == 0):   # Keras phase
            state = getattr(self.model, "_state", None) or {}
            params = state.get("params")
            if params is not None:
                import jax
                flat = jax.tree_util.tree_flatten_with_path(params)[0]
                for path, leaf in flat:
                    name = "/".join(getattr(p, "key", str(p))
                                    for p in path)
                    self._writer("train").histogram(
                        name, np.asarray(leaf), epoch)
        for w in self._writers.values():
            w.flush()

    def on_train_end(self, logs=None):
        for w in self._writers.values():
            w.close()
        self._writers = {}
