"""keras.regularizers-shaped weight regularizers.

≙ TFK/src/regularizers.py — the same L1/L2/L1L2 penalties and factory
aliases. Shim layers (training/layers.py) sow each penalty into the
flax ``reg_losses`` collection during the forward pass; the training
loop (training/model.py) sums the collection into the objective AND
into the reported loss, matching keras (model.losses are included in
the printed/monitored loss for both fit and evaluate).
"""

from __future__ import annotations

import jax.numpy as jnp


class Regularizer:
    def __call__(self, x):
        raise NotImplementedError

    def get_config(self) -> dict:
        raise NotImplementedError

    @classmethod
    def from_config(cls, config: dict):
        return cls(**config)


class L1L2(Regularizer):
    def __init__(self, l1: float = 0.0, l2: float = 0.0):
        self.l1 = float(l1)
        self.l2 = float(l2)

    def __call__(self, x):
        x = x.astype(jnp.float32)
        out = jnp.zeros((), jnp.float32)
        if self.l1:
            out = out + self.l1 * jnp.sum(jnp.abs(x))
        if self.l2:
            out = out + self.l2 * jnp.sum(jnp.square(x))
        return out

    def get_config(self):
        return {"l1": self.l1, "l2": self.l2}


class L1(L1L2):
    def __init__(self, l1: float = 0.01):
        super().__init__(l1=l1)

    def get_config(self):
        return {"l1": self.l1}


class L2(L1L2):
    def __init__(self, l2: float = 0.01):
        super().__init__(l2=l2)

    def get_config(self):
        return {"l2": self.l2}


def l1(l1: float = 0.01) -> L1:
    return L1(l1)


def l2(l2: float = 0.01) -> L2:
    return L2(l2)


def l1_l2(l1: float = 0.01, l2: float = 0.01) -> L1L2:
    return L1L2(l1=l1, l2=l2)


_CLASSES = {"L1": L1, "L2": L2, "L1L2": L1L2}


def serialize(reg) -> dict | None:
    if reg is None:
        return None
    return {"class_name": type(reg).__name__, "config": reg.get_config()}


def deserialize(config) -> Regularizer | None:
    if config is None:
        return None
    if isinstance(config, Regularizer):
        return config
    cls = _CLASSES.get(config.get("class_name"))
    if cls is None:
        raise ValueError(f"unknown regularizer {config!r}")
    return cls.from_config(config["config"])


def get(identifier):
    """Resolve a constructor argument: None | Regularizer | "l1"/"l2"
    string | serialized dict."""
    if identifier is None or isinstance(identifier, Regularizer):
        return identifier
    if isinstance(identifier, str):
        key = identifier.lower()
        if key == "l1":
            return L1()
        if key == "l2":
            return L2()
        if key in ("l1_l2", "l1l2"):
            return L1L2(0.01, 0.01)
        raise ValueError(f"unknown regularizer {identifier!r}")
    if isinstance(identifier, dict):
        return deserialize(identifier)
    raise TypeError(f"cannot interpret regularizer {identifier!r}")
