"""Keras functional API shim: symbolic tensors + DAG models.

≙ TFK/src/engine/functional.py:84 ``Functional`` — ``keras.Input``
returns a symbolic tensor, calling a shim layer on symbolic tensors
records a graph node (≙ KerasTensor + Node, TFK/src/engine/node.py),
and ``keras.Model(inputs, outputs)`` compiles the recorded DAG into one
flax module running on the SPMD training loop (training/model.py). The
surface that reference functional scripts need: residual adds, layer
REUSE (same layer instance called twice shares weights, like Keras),
multi-input models, nested layer call arguments.

Weight layout stays keras-shaped per layer (training/layers.py), and
layer naming follows keras's class-based auto-naming ("conv2d",
"conv2d_1", …) in graph order so per-layer weight mapping against a
real tf_keras Functional model is mechanical.
"""

from __future__ import annotations

import itertools
import re
from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from distributed_tensorflow_tpu.training.model import Model as _TrainModel


class SymbolicTensor:
    """A node in the functional graph (≙ KerasTensor). ``layer`` is None
    for graph inputs; ``call_args`` preserves the structure the layer
    was called with (a single tensor, a list, ...). A multi-output
    layer call (e.g. ``LSTM(return_state=True)``) produces ALIAS
    tensors carrying ``source`` (the producing call node) and
    ``index`` into its output list."""

    _ids = itertools.count()

    def __init__(self, *, shape=None, dtype="float32", layer=None,
                 call_args=None, name=None, source=None, index=0):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.layer = layer
        self.call_args = call_args
        self.name = name
        self.source = source
        self.index = index
        self.uid = next(self._ids)

    def __repr__(self):
        if self.source is not None:
            return (f"<SymbolicTensor {self.uid} = output {self.index} "
                    f"of node {self.source.uid}>")
        src = "Input" if self.layer is None else type(self.layer).__name__
        return f"<SymbolicTensor {self.uid} from {src}>"


def Input(shape=None, *, dtype="float32", name=None, batch_size=None):
    """≙ keras.Input: a symbolic tensor with per-sample ``shape``.
    Also accepted as the first entry of a ``Sequential`` layer list
    (converted to an InputLayer there, like tf_keras)."""
    if shape is None:
        raise ValueError("Input() requires shape")
    return SymbolicTensor(shape=tuple(shape), dtype=dtype, name=name)


def _sym_leaves(args):
    return [x for x in jax.tree_util.tree_leaves(args)
            if isinstance(x, SymbolicTensor)]


def is_symbolic(args) -> bool:
    return bool(_sym_leaves(args))


def symbolic_call(layer, args):
    """Record layer(args) as a graph node (called by Layer.__call__).
    A layer declaring ``symbolic_outputs > 1`` returns a LIST of alias
    tensors — the keras ``out, h, c = LSTM(...)(x)`` unpack idiom."""
    node = SymbolicTensor(layer=layer, call_args=args)
    n = getattr(layer, "symbolic_outputs", 1)
    if n == 1:
        return node
    return [SymbolicTensor(source=node, index=i) for i in range(n)]


def _keras_auto_name(layer) -> str:
    """keras-style base name: CamelCase class -> snake_case."""
    explicit = getattr(layer, "name", None)
    if explicit:
        return explicit
    return re.sub(r"(?<!^)(?=[A-Z])", "_", type(layer).__name__).lower()


class _LayerModule(nn.Module):
    """One shim layer as a flax submodule; calling the SAME instance
    twice replays the compact body on the same scope, so parameters are
    shared — the keras layer-reuse semantics."""
    layer: Any
    train: bool

    @nn.compact
    def __call__(self, x):
        return self.layer.apply(x, train=self.train, module=self)


class _FunctionalModule(nn.Module):
    """Evaluate the recorded DAG. ``nodes`` is the topological order
    (inputs excluded); ``layer_names`` maps layer id -> submodule name
    (stable, keras-style, assigned at graph-build time)."""
    input_nodes: tuple
    nodes: tuple
    output_nodes: tuple
    layer_names: Any        # dict str(id(layer)) -> name (static; string
                            # keys — flax 0.10 serialization walks Module
                            # attribute dicts and asserts on non-str keys)
    train: bool

    @nn.compact
    def __call__(self, x):
        xs = x if isinstance(x, (list, tuple)) else (x,)
        if len(xs) != len(self.input_nodes):
            raise ValueError(
                f"model expects {len(self.input_nodes)} inputs, "
                f"got {len(xs)}")
        memo = {inp.uid: v for inp, v in zip(self.input_nodes, xs)}

        def resolve(s):
            if s.source is not None:        # alias into a multi-output
                return memo[s.source.uid][s.index]
            return memo[s.uid]

        mods = {}
        for node in self.nodes:
            key = id(node.layer)
            if key not in mods:
                mods[key] = _LayerModule(layer=node.layer,
                                         train=self.train,
                                         name=self.layer_names[str(key)])
            args = jax.tree_util.tree_map(
                lambda s: resolve(s) if isinstance(s, SymbolicTensor)
                else s,
                node.call_args,
                is_leaf=lambda s: isinstance(s, SymbolicTensor))
            memo[node.uid] = mods[key](args)
        outs = [resolve(o) for o in self.output_nodes]
        return outs[0] if len(self.output_nodes) == 1 else tuple(outs)


def _toposort(inputs: Sequence[SymbolicTensor],
              outputs: Sequence[SymbolicTensor]):
    """DFS topological order of layer nodes from outputs back to the
    declared inputs; raises on graph tensors not reachable from
    ``inputs`` (the keras 'disconnected graph' error)."""
    input_ids = {i.uid for i in inputs}
    order, seen, visiting = [], set(), set()

    def visit(node):
        if node.uid in seen:
            return
        if node.uid in input_ids:
            seen.add(node.uid)
            return
        if node.source is not None:         # alias -> visit producer
            seen.add(node.uid)
            visit(node.source)
            return
        if node.layer is None:
            raise ValueError(
                f"Graph disconnected: {node!r} is an Input not listed "
                f"in Model(inputs=...)")
        if node.uid in visiting:
            raise ValueError("Cycle in functional graph")
        visiting.add(node.uid)
        for dep in _sym_leaves(node.call_args):
            visit(dep)
        visiting.discard(node.uid)
        seen.add(node.uid)
        order.append(node)

    for out in outputs:
        visit(out)
    return tuple(order)


class Model(_TrainModel):
    """≙ keras.Model: ``Model(inputs=sym, outputs=sym)`` builds a
    Functional model over the recorded DAG; any other construction
    defers to the module-based training Model (so subclass-style usage
    keeps working)."""

    def __init__(self, *args, inputs=None, outputs=None, **kwargs):
        if inputs is None and args and is_symbolic(args[0]):
            inputs, args = args[0], args[1:]
            if outputs is None and args:
                outputs, args = args[0], args[1:]
        if inputs is None:
            super().__init__(*args, **kwargs)
            return
        if outputs is None:
            raise ValueError("Model(inputs=...) requires outputs=")
        self._functional_init(inputs, outputs,
                              seed=kwargs.pop("seed", 0),
                              name=kwargs.pop("name", None))

    def _functional_init(self, inputs, outputs, *, seed=0, name=None):
        self.inputs = list(inputs) if isinstance(
            inputs, (list, tuple)) else [inputs]
        self.outputs = list(outputs) if isinstance(
            outputs, (list, tuple)) else [outputs]
        for i in self.inputs:
            if not (isinstance(i, SymbolicTensor) and i.layer is None):
                raise TypeError(
                    "Model(inputs=...) expects keras.Input tensors, got "
                    f"{i!r}")
        nodes = _toposort(self.inputs, self.outputs)

        # keras-style stable names in graph order; one name per layer
        # INSTANCE (reused layers keep one name = one parameter set).
        counters, names = {}, {}
        for node in nodes:
            key = str(id(node.layer))
            if key in names:
                continue
            base = _keras_auto_name(node.layer)
            n = counters.get(base, 0)
            counters[base] = n + 1
            names[key] = base if n == 0 else f"{base}_{n}"

        self._graph_nodes = nodes
        self.layers = []
        seen_layers = set()
        for node in nodes:
            if id(node.layer) not in seen_layers:
                seen_layers.add(id(node.layer))
                self.layers.append(node.layer)
        mk = lambda train: _FunctionalModule(
            input_nodes=tuple(self.inputs), nodes=nodes,
            output_nodes=tuple(self.outputs), layer_names=names,
            train=train)
        super().__init__(mk(True), eval_module=mk(False), seed=seed)
        self.name = name
        if all(i.shape is not None for i in self.inputs):
            sample = [jnp.zeros((1, *i.shape),
                                jnp.dtype(i.dtype)) for i in self.inputs]
            self.build(sample[0] if len(sample) == 1 else tuple(sample))
