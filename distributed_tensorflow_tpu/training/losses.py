"""Loss functions for the training-loop layer.

≙ tf_keras losses as used by ``Model.compile`` (reference:
tf_keras/src/losses.py). Each loss maps (y_true, y_pred) -> per-example
loss; the Model applies sample weights and takes the GLOBAL mean inside
the SPMD program, so the reference's per-replica loss scaling by
``num_replicas_in_sync`` (tensorflow/python/distribute/distribute_lib.py:1675,
tf_keras compile_utils) is satisfied by construction — there is one global
mean, not N per-replica means.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


def _align_ranks(y_true, y_pred):
    """keras's rank alignment (losses_utils.squeeze_or_expand): a
    trailing size-1 prediction dim pairs with rank-1-lower labels —
    WITHOUT this, ``(B,) vs (B, 1)`` elementwise math silently
    broadcasts to (B, B) and trains garbage."""
    if y_true.ndim == y_pred.ndim - 1 and y_pred.shape[-1] == 1:
        y_true = y_true[..., None]
    elif y_pred.ndim == y_true.ndim - 1 and y_true.shape[-1] == 1:
        y_pred = y_pred[..., None]
    return y_true, y_pred


class Loss:
    """Base loss: ``call`` returns per-example losses (batch leading)."""

    def __init__(self, name: str | None = None):
        self.name = name or type(self).__name__

    def call(self, y_true, y_pred):
        raise NotImplementedError

    def __call__(self, y_true, y_pred):
        return self.call(y_true, y_pred)


class SparseCategoricalCrossentropy(Loss):
    def __init__(self, from_logits: bool = True, name=None):
        super().__init__(name)
        self.from_logits = from_logits

    def call(self, y_true, y_pred):
        logits = y_pred if self.from_logits else jnp.log(
            jnp.clip(y_pred, 1e-9, 1.0))
        labels = y_true.astype(jnp.int32)
        per = optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels)
        # collapse any extra (e.g. sequence) dims to one loss per example
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class CategoricalCrossentropy(Loss):
    def __init__(self, from_logits: bool = True, name=None):
        super().__init__(name)
        self.from_logits = from_logits

    def call(self, y_true, y_pred):
        logits = y_pred if self.from_logits else jnp.log(
            jnp.clip(y_pred, 1e-9, 1.0))
        per = optax.softmax_cross_entropy(logits.astype(jnp.float32),
                                          y_true.astype(jnp.float32))
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class BinaryCrossentropy(Loss):
    def __init__(self, from_logits: bool = True, name=None):
        super().__init__(name)
        self.from_logits = from_logits

    def call(self, y_true, y_pred):
        y_true, y_pred = _align_ranks(y_true, y_pred)
        y = y_true.astype(jnp.float32)
        p = y_pred.astype(jnp.float32)
        if self.from_logits:
            per = optax.sigmoid_binary_cross_entropy(p, y)
        else:
            p = jnp.clip(p, 1e-7, 1 - 1e-7)
            per = -(y * jnp.log(p) + (1 - y) * jnp.log1p(-p))
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class MeanSquaredError(Loss):
    def call(self, y_true, y_pred):
        y_true, y_pred = _align_ranks(y_true, y_pred)
        per = jnp.square(y_pred.astype(jnp.float32)
                         - y_true.astype(jnp.float32))
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class MeanAbsoluteError(Loss):
    def call(self, y_true, y_pred):
        y_true, y_pred = _align_ranks(y_true, y_pred)
        per = jnp.abs(y_pred.astype(jnp.float32)
                      - y_true.astype(jnp.float32))
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class Huber(Loss):
    """≙ keras Huber: quadratic below ``delta``, linear above."""

    def __init__(self, delta: float = 1.0, name: str = "huber"):
        super().__init__(name)
        self.delta = float(delta)

    def call(self, y_true, y_pred):
        y_true, y_pred = _align_ranks(y_true, y_pred)
        err = y_pred.astype(jnp.float32) - y_true.astype(jnp.float32)
        a = jnp.abs(err)
        per = jnp.where(a <= self.delta, 0.5 * jnp.square(err),
                        self.delta * (a - 0.5 * self.delta))
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class Hinge(Loss):
    """≙ keras Hinge: labels in {0,1} are mapped to {-1,1}."""

    def __init__(self, name: str = "hinge"):
        super().__init__(name)

    def call(self, y_true, y_pred):
        y_true, y_pred = _align_ranks(y_true, y_pred)
        t = y_true.astype(jnp.float32)
        t = jnp.where(t <= 0.0, -1.0, t)
        per = jnp.maximum(1.0 - t * y_pred.astype(jnp.float32), 0.0)
        return per.reshape(per.shape[0], -1).mean(axis=-1)


class KLDivergence(Loss):
    """≙ keras KLDivergence: sum over classes of y·log(y/ŷ)."""

    def __init__(self, name: str = "kl_divergence"):
        super().__init__(name)

    def call(self, y_true, y_pred):
        y_true, y_pred = _align_ranks(y_true, y_pred)
        y = jnp.clip(y_true.astype(jnp.float32), 1e-7, 1.0)
        p = jnp.clip(y_pred.astype(jnp.float32), 1e-7, 1.0)
        per = jnp.sum(y * jnp.log(y / p), axis=-1)
        return per.reshape(per.shape[0], -1).mean(axis=-1)


_ALIASES = {
    "sparse_categorical_crossentropy": SparseCategoricalCrossentropy,
    "categorical_crossentropy": CategoricalCrossentropy,
    "binary_crossentropy": BinaryCrossentropy,
    "mse": MeanSquaredError,
    "mean_squared_error": MeanSquaredError,
    "mae": MeanAbsoluteError,
    "mean_absolute_error": MeanAbsoluteError,
    "huber": Huber,
    "hinge": Hinge,
    "kld": KLDivergence,
    "kl_divergence": KLDivergence,
}


def get(identifier) -> Loss:
    if isinstance(identifier, Loss):
        return identifier
    if callable(identifier):
        loss = Loss(getattr(identifier, "__name__", "loss"))
        loss.call = identifier
        return loss
    if isinstance(identifier, str):
        key = identifier.lower()
        if key in _ALIASES:
            return _ALIASES[key]()
    raise ValueError(f"Unknown loss: {identifier!r}; "
                     f"known: {sorted(_ALIASES)}")
