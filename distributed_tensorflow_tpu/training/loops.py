"""On-device training loops + infeed-style data staging.

≙ tensorflow/python/tpu/training_loop.py (``while_loop`` :31,
``repeat`` :182 — keep N steps on-device so the host is out of the loop)
and tpu_feed.py ``InfeedQueue`` (SURVEY.md §2.6). On a JAX TPU the
"infeed queue" collapses to two native forms:

- **scan-staged** (:func:`run_steps`): the next N batches are staged on
  device as one stacked array and a ``lax.scan`` consumes them — the
  whole N-step epoch is ONE XLA program, the direct analogue of
  infeed-driven ``tpu.repeat``.
- **host-streamed** (:class:`InfeedLoop`): batches stream through a
  background device_put pipeline (double buffering) while the compiled
  step runs — async dispatch overlaps H2D with compute, which is what
  the infeed hardware queue achieved.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.utils import profiler


def repeat(n: int, body_fn: Callable, inputs):
    """Run ``body_fn`` n times on-device (≙ training_loop.repeat :182).

    ``body_fn(state) -> state``; the loop is a single compiled
    ``lax.fori_loop`` — the host dispatches once for all ``n`` steps.
    """
    return jax.lax.fori_loop(0, n, lambda _, s: body_fn(s), inputs)


def while_loop(condition_fn: Callable, body_fn: Callable, inputs):
    """≙ training_loop.while_loop (:31): on-device while with state.

    ``condition_fn(state) -> bool``; ``body_fn(state) -> state``.
    """
    return jax.lax.while_loop(condition_fn, body_fn, inputs)


def run_steps(step_fn: Callable, state, batches):
    """Consume a leading-axis stack of batches in ONE compiled program.

    ``step_fn(state, batch) -> (state, metrics)``; ``batches`` is a
    pytree whose leaves have a leading axis of n_steps (staged on device
    — the infeed queue's contents). Returns (state, stacked_metrics).
    ≙ tpu.repeat + InfeedQueue: device-resident multi-step loop.
    """
    def body(s, batch):
        s2, metrics = step_fn(s, batch)
        return s2, metrics

    return jax.lax.scan(body, state, batches)


def stack_batches(batches: Iterable):
    """Stage an iterable of same-shaped batches as one stacked pytree
    (host-side helper for :func:`run_steps`)."""
    batches = list(batches)
    if not batches:
        raise ValueError("no batches to stack")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *batches)


#: Step-phase names StepTelemetry accepts (seconds within one step):
#: host compute proper is whatever remains after the others.
#: - ``compute``    time in the compiled step's compute (measured or
#:   calibrated — see bench.py's phase breakdown)
#: - ``collective`` EXPOSED gradient-collective time (host-timed sync
#:   like the elastic worker's allgather, or calibrated residual)
#: - ``host``       host-side callback/bookkeeping time (Model.fit
#:   times its callback list here)
#: - ``ckpt_block`` step-loop time blocked on checkpoint capture/commit
STEP_PHASES = ("compute", "collective", "host", "ckpt_block")


class StepTelemetry:
    """Per-step telemetry for a host-driven step loop.

    One object per training run; call :meth:`step_completed` after each
    step. Feeds the unified instruments every export path reads —
    ``training/step_time`` (histogram percentiles), ``training/
    steps_completed`` (the counter fleet rollups and the stall detector
    key on), ``training/last_loss`` — emits a ``train.step`` event per
    step into the structured log (step time, infeed wait, loss), and
    re-arms an attached :class:`telemetry.StallDetector`.

        steps = StepTelemetry(infeed=loop, stall_detector=detector)
        for i in range(n):
            state, metrics = step_fn(state, loop.next())
            steps.step_completed(i, loss=metrics["loss"])

    **Phase attribution:** pass ``phases={"compute": s, "collective": s,
    ...}`` (keys from :data:`STEP_PHASES`) and optionally
    ``overlap_eff`` (fraction of collective time hidden behind the
    backward pass). Phases land as ``<name>_s`` fields on the
    ``train.step`` event — ``tools/obs_report.py`` renders the per-step
    phase table and names the bottleneck from them — and as
    ``training/phase/<name>_frac`` histograms plus a
    ``training/overlap_eff`` gauge in the registry, so fleet rollups
    (telemetry/aggregate.py) carry p50/p95 phase fractions and the
    mean/max overlap efficiency across workers.

    With telemetry off (no event log configured) the per-step cost is
    a few instrument updates; the event write is skipped.

    **Inference sharing:** ``event_name``/``metric_prefix`` re-point the
    same instrument set at another step loop — ``Model.predict`` uses
    ``StepTelemetry(event_name="predict.step",
    metric_prefix="inference")`` so batch prediction and the serving
    engine report into ONE ``inference/`` metric namespace
    (``inference/step_time`` is the batch-latency histogram; the
    serving engine's request instruments live alongside it).
    """

    def __init__(self, infeed: "InfeedLoop | None" = None,
                 stall_detector=None, reg=None,
                 event_name: str = "train.step",
                 metric_prefix: str = "training"):
        reg = reg or telemetry.get_registry()
        self._event_name = event_name
        self._timer = reg.histogram(f"{metric_prefix}/step_time",
                                    "host-observed step seconds")
        self._steps = reg.counter(f"{metric_prefix}/steps_completed")
        self._loss = reg.gauge(f"{metric_prefix}/last_loss")
        self._phase_hists = {
            name: reg.histogram(f"{metric_prefix}/phase/{name}_frac",
                                f"per-step {name} share of step time")
            for name in STEP_PHASES}
        self._overlap = reg.gauge(
            f"{metric_prefix}/overlap_eff",
            "fraction of collective time hidden behind backward")
        self._infeed = infeed
        self._stall = stall_detector
        self._last_t = time.monotonic()
        self._last_wait = 0.0

    def step_completed(self, step=None, loss=None,
                       dur_s: float | None = None,
                       phases: "dict[str, float] | None" = None,
                       overlap_eff: float | None = None,
                       **extra_fields):
        """``extra_fields`` land verbatim on the emitted event (e.g.
        ``batch_size`` on ``predict.step``)."""
        now = time.monotonic()
        if dur_s is None:
            dur_s = now - self._last_t
        self._last_t = now
        self._timer.record(dur_s)
        self._steps.increment()
        wait_s = None
        if self._infeed is not None:
            total = self._infeed.total_wait_s
            wait_s = total - self._last_wait
            self._last_wait = total
        if phases:
            for name, seconds in phases.items():
                hist = self._phase_hists.get(name)
                if hist is not None and dur_s > 0:
                    hist.record(seconds / dur_s)
        if overlap_eff is not None:
            self._overlap.set(round(float(overlap_eff), 4))
        if loss is not None:
            try:
                loss = float(loss)
            except (TypeError, ValueError):
                loss = None
        if loss is not None:
            self._loss.set(loss)
        if self._event_name == "train.step":
            # feed the live goodput ledger (if one is active): step time
            # minus the blocked shares is goodput, the blocked shares
            # are named badput
            from distributed_tensorflow_tpu.telemetry import goodput
            ledger = goodput.active_ledger()
            if ledger is not None:
                ledger.step_completed(
                    dur_s, infeed_s=wait_s or 0.0,
                    ckpt_s=(phases or {}).get("ckpt_block", 0.0))
        if telemetry.enabled():
            fields = {"dur_s": round(dur_s, 6)}
            if step is not None:
                fields["step"] = int(step)
            if loss is not None:
                fields["loss"] = loss
            if wait_s is not None:
                fields["infeed_wait_s"] = round(wait_s, 6)
            if phases:
                for name, seconds in phases.items():
                    fields[f"{name}_s"] = round(float(seconds), 6)
            if overlap_eff is not None:
                fields["overlap_eff"] = round(float(overlap_eff), 4)
            for k, v in extra_fields.items():
                if v is not None:
                    fields[k] = v
            telemetry.event(self._event_name, **fields)
        if self._stall is not None:
            self._stall.step_completed(step=step, dur_s=dur_s)


class InfeedLoop:
    """Host-streamed stepping with background device staging.

    ≙ tpu_feed.InfeedQueue + the session infeed thread: a daemon thread
    device_puts upcoming batches (``buffer_size`` deep) while compiled
    steps consume them — H2D overlaps compute without the host blocking
    the step loop.

        loop = InfeedLoop(iter(dataset), place_fn=strategy.shard_batch)
        for _ in range(steps):
            state, metrics = step_fn(state, loop.next())

    Host-boundedness is a measured number, not a guess: ``next()``
    accumulates the time the step loop spent BLOCKED on the infeed
    (``total_wait_s`` over ``batches`` delivered), and
    ``wait_fraction(elapsed_s)`` gives the per-run infeed-wait share of
    wall time — the bench's "input pipeline is not the bottleneck"
    criterion. The counters also register as an ``infeed`` stage in
    ``utils.profiler.pipeline_stats()``.
    """

    def __init__(self, iterator: Iterator, place_fn: Callable | None = None,
                 buffer_size: int = 2, name: str | None = None):
        self._it = iterator
        self._place = place_fn or (lambda b: jax.tree_util.tree_map(
            jnp.asarray, b))
        self._buf: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._size = buffer_size
        self._done = False
        self._err: BaseException | None = None
        self.total_wait_s = 0.0
        self.batches = 0
        self._stats = profiler.StageStats(name or "infeed")
        self._wait_timer = telemetry.timer(
            "training/infeed_wait",
            "per-step time the step loop blocked on the infeed")
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self):
        try:
            src = iter(self._it)
            while True:
                t0 = time.monotonic()
                try:
                    batch = next(src)
                except StopIteration:
                    return
                t1 = time.monotonic()
                staged = self._place(batch)
                t2 = time.monotonic()
                with self._cv:
                    while len(self._buf) >= self._size and not self._done:
                        self._cv.wait(0.1)
                    if self._done:
                        return
                    self._buf.append(staged)
                    depth = len(self._buf)
                    self._cv.notify_all()
                self._stats.record(
                    elements=1, busy_s=t2 - t1,       # device_put time
                    producer_wait_s=t1 - t0,          # host pipeline time
                    blocked_put_s=time.monotonic() - t2,
                    queue_depth=depth)
        except BaseException as e:      # surfaced on next()
            self._err = e
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def next(self, timeout: float = 60.0):
        t0 = time.monotonic()
        with self._cv:
            ready = self._cv.wait_for(
                lambda: self._buf or self._done or self._err, timeout)
            waited = time.monotonic() - t0
            if self._err is not None:
                raise self._err
            if not self._buf:
                if not ready:
                    # producer still alive but slow: NOT end-of-data
                    raise TimeoutError(
                        f"infeed produced nothing in {timeout}s "
                        f"(source iterator or device staging stalled)")
                raise StopIteration
            batch = self._buf.popleft()
            self._cv.notify_all()
        self.total_wait_s += waited
        self.batches += 1
        self._stats.record(consumer_wait_s=waited)
        self._wait_timer.record(waited)
        return batch

    @property
    def mean_wait_s(self) -> float:
        """Mean per-step time the consumer blocked on the infeed."""
        return self.total_wait_s / self.batches if self.batches else 0.0

    def wait_fraction(self, elapsed_s: float) -> float:
        """Share of ``elapsed_s`` the step loop spent infeed-blocked —
        < 0.05 means the host input pipeline is not the bottleneck."""
        return self.total_wait_s / elapsed_s if elapsed_s > 0 else 0.0

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def stop(self):
        with self._cv:
            self._done = True
            self._cv.notify_all()
