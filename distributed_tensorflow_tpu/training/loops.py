"""On-device training loops + infeed-style data staging.

≙ tensorflow/python/tpu/training_loop.py (``while_loop`` :31,
``repeat`` :182 — keep N steps on-device so the host is out of the loop)
and tpu_feed.py ``InfeedQueue`` (SURVEY.md §2.6). On a JAX TPU the
"infeed queue" collapses to two native forms:

- **scan-staged** (:func:`run_steps`): the next N batches are staged on
  device as one stacked array and a ``lax.scan`` consumes them — the
  whole N-step epoch is ONE XLA program, the direct analogue of
  infeed-driven ``tpu.repeat``.
- **host-streamed** (:class:`InfeedLoop`): batches stream through a
  background device_put pipeline (double buffering) while the compiled
  step runs — async dispatch overlaps H2D with compute, which is what
  the infeed hardware queue achieved.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Iterable, Iterator

import jax
import jax.numpy as jnp


def repeat(n: int, body_fn: Callable, inputs):
    """Run ``body_fn`` n times on-device (≙ training_loop.repeat :182).

    ``body_fn(state) -> state``; the loop is a single compiled
    ``lax.fori_loop`` — the host dispatches once for all ``n`` steps.
    """
    return jax.lax.fori_loop(0, n, lambda _, s: body_fn(s), inputs)


def while_loop(condition_fn: Callable, body_fn: Callable, inputs):
    """≙ training_loop.while_loop (:31): on-device while with state.

    ``condition_fn(state) -> bool``; ``body_fn(state) -> state``.
    """
    return jax.lax.while_loop(condition_fn, body_fn, inputs)


def run_steps(step_fn: Callable, state, batches):
    """Consume a leading-axis stack of batches in ONE compiled program.

    ``step_fn(state, batch) -> (state, metrics)``; ``batches`` is a
    pytree whose leaves have a leading axis of n_steps (staged on device
    — the infeed queue's contents). Returns (state, stacked_metrics).
    ≙ tpu.repeat + InfeedQueue: device-resident multi-step loop.
    """
    def body(s, batch):
        s2, metrics = step_fn(s, batch)
        return s2, metrics

    return jax.lax.scan(body, state, batches)


def stack_batches(batches: Iterable):
    """Stage an iterable of same-shaped batches as one stacked pytree
    (host-side helper for :func:`run_steps`)."""
    batches = list(batches)
    if not batches:
        raise ValueError("no batches to stack")
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *batches)


class InfeedLoop:
    """Host-streamed stepping with background device staging.

    ≙ tpu_feed.InfeedQueue + the session infeed thread: a daemon thread
    device_puts upcoming batches (``buffer_size`` deep) while compiled
    steps consume them — H2D overlaps compute without the host blocking
    the step loop.

        loop = InfeedLoop(iter(dataset), place_fn=strategy.shard_batch)
        for _ in range(steps):
            state, metrics = step_fn(state, loop.next())
    """

    def __init__(self, iterator: Iterator, place_fn: Callable | None = None,
                 buffer_size: int = 2):
        self._it = iterator
        self._place = place_fn or (lambda b: jax.tree_util.tree_map(
            jnp.asarray, b))
        self._buf: collections.deque = collections.deque()
        self._cv = threading.Condition()
        self._size = buffer_size
        self._done = False
        self._err: BaseException | None = None
        self._thread = threading.Thread(target=self._fill, daemon=True)
        self._thread.start()

    def _fill(self):
        try:
            for batch in self._it:
                staged = self._place(batch)
                with self._cv:
                    while len(self._buf) >= self._size and not self._done:
                        self._cv.wait(0.1)
                    if self._done:
                        return
                    self._buf.append(staged)
                    self._cv.notify_all()
        except BaseException as e:      # surfaced on next()
            self._err = e
        finally:
            with self._cv:
                self._done = True
                self._cv.notify_all()

    def next(self, timeout: float = 60.0):
        with self._cv:
            ready = self._cv.wait_for(
                lambda: self._buf or self._done or self._err, timeout)
            if self._err is not None:
                raise self._err
            if not self._buf:
                if not ready:
                    # producer still alive but slow: NOT end-of-data
                    raise TimeoutError(
                        f"infeed produced nothing in {timeout}s "
                        f"(source iterator or device staging stalled)")
                raise StopIteration
            batch = self._buf.popleft()
            self._cv.notify_all()
            return batch

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self

    def stop(self):
        with self._cv:
            self._done = True
            self._cv.notify_all()
