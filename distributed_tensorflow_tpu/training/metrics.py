"""Metrics with functional (pytree) state for SPMD training loops.

≙ tf_keras metrics as aggregated across replicas by Model.fit (reference:
tf_keras/src/metrics/, aggregation in compile_utils.MetricsContainer).
TF metrics are stateful objects whose variables are SyncOnRead with SUM
aggregation (tensorflow/python/distribute/values.py:1294): each replica
accumulates locally and reads reduce across replicas. Here metric state
is an explicit pytree *inside* the jitted SPMD program: updates are
computed on globally-sharded batches, so totals are already global —
``result`` is pure arithmetic, no cross-replica read needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Metric:
    """Functional metric: init() -> state, update(state, y, p, w) -> state,
    result(state) -> scalar. States are tiny replicated arrays."""

    def __init__(self, name: str):
        self.name = name

    def init(self):
        return {"total": jnp.zeros((), jnp.float32),
                "count": jnp.zeros((), jnp.float32)}

    def update(self, state, y_true, y_pred, sample_weight=None):
        values = self._values(y_true, y_pred).astype(jnp.float32)
        values = values.reshape(values.shape[0], -1).mean(axis=-1)
        if sample_weight is None:
            sample_weight = jnp.ones_like(values)
        w = sample_weight.astype(jnp.float32)
        return {"total": state["total"] + jnp.sum(values * w),
                "count": state["count"] + jnp.sum(w)}

    def result(self, state):
        return state["total"] / jnp.maximum(state["count"], 1e-9)

    def _values(self, y_true, y_pred):
        raise NotImplementedError


class Mean(Metric):
    """Weighted running mean of directly-supplied values (used for loss)."""

    def __init__(self, name: str = "mean"):
        super().__init__(name)

    def update_values(self, state, values, sample_weight=None):
        values = jnp.asarray(values, jnp.float32).reshape(-1)
        if sample_weight is None:
            sample_weight = jnp.ones_like(values)
        w = jnp.asarray(sample_weight, jnp.float32).reshape(-1)
        return {"total": state["total"] + jnp.sum(values * w),
                "count": state["count"] + jnp.sum(w)}

    def _values(self, y_true, y_pred):  # Mean used standalone
        return jnp.asarray(y_pred, jnp.float32)


class SparseCategoricalAccuracy(Metric):
    def __init__(self, name: str = "accuracy"):
        super().__init__(name)

    def _values(self, y_true, y_pred):
        pred = jnp.argmax(y_pred, axis=-1)
        return (pred == y_true.astype(pred.dtype)).astype(jnp.float32)


class CategoricalAccuracy(Metric):
    def __init__(self, name: str = "accuracy"):
        super().__init__(name)

    def _values(self, y_true, y_pred):
        return (jnp.argmax(y_pred, axis=-1)
                == jnp.argmax(y_true, axis=-1)).astype(jnp.float32)


class BinaryAccuracy(Metric):
    def __init__(self, name: str = "accuracy", threshold: float = 0.5,
                 from_logits: bool = True):
        super().__init__(name)
        self.threshold = threshold
        self.from_logits = from_logits

    def _values(self, y_true, y_pred):
        from distributed_tensorflow_tpu.training.losses import _align_ranks
        y_true, y_pred = _align_ranks(y_true, y_pred)
        p = jax.nn.sigmoid(y_pred) if self.from_logits else y_pred
        pred = (p > self.threshold).astype(jnp.float32)
        return (pred == y_true.astype(jnp.float32)).astype(jnp.float32)


class TopKCategoricalAccuracy(Metric):
    """≙ keras (Sparse)TopKCategoricalAccuracy: hit iff the true class
    is among the k highest-scoring predictions. Accepts sparse integer
    OR one-hot labels (resolved by rank, like keras's sparse variant
    pairing with the compiled loss)."""

    def __init__(self, k: int = 5, name: str | None = None):
        super().__init__(name or f"top_{k}_accuracy")
        self.k = int(k)

    def _values(self, y_true, y_pred):
        if y_true.ndim == y_pred.ndim:
            if y_true.shape[-1] == y_pred.shape[-1]:   # one-hot
                y_true = jnp.argmax(y_true, axis=-1)
            else:                                      # sparse (B, 1)
                y_true = jnp.squeeze(y_true, axis=-1)
        # k >= num_classes: everything is in the top k (tf in_top_k)
        k = min(self.k, y_pred.shape[-1])
        _, topk = jax.lax.top_k(y_pred, k)
        hit = jnp.any(topk == y_true[..., None].astype(topk.dtype),
                      axis=-1)
        return hit.astype(jnp.float32)


class _ConfusionMetric(Metric):
    """Threshold-based confusion-count metric base (Precision/Recall):
    state carries the relevant counts (SUM-reducible across replicas
    and steps, ≙ keras's update_confusion_matrix_variables)."""

    def __init__(self, name: str, threshold: float, from_logits: bool):
        super().__init__(name)
        self.threshold = float(threshold)
        self.from_logits = from_logits

    def init(self):
        return {"true_pos": jnp.zeros((), jnp.float32),
                "denom": jnp.zeros((), jnp.float32)}

    def _pred(self, y_pred):
        p = jax.nn.sigmoid(y_pred) if self.from_logits else y_pred
        return (p > self.threshold).astype(jnp.float32)

    def update(self, state, y_true, y_pred, sample_weight=None):
        pred = self._pred(y_pred).reshape(y_pred.shape[0], -1)
        true = jnp.asarray(y_true, jnp.float32).reshape(pred.shape)
        if sample_weight is None:
            w = jnp.ones((pred.shape[0], 1), jnp.float32)
        else:
            w = jnp.asarray(sample_weight, jnp.float32)
            if w.size == pred.size:
                # keras also accepts ELEMENT-wise weights matching
                # y_true's shape — broadcast against the flattened
                # prediction shape, not a forced (-1, 1)
                w = w.reshape(pred.shape)
            else:
                w = w.reshape(-1, 1)            # strictly per-sample
        tp = jnp.sum(pred * true * w)
        denom = jnp.sum(self._denom_mask(true, pred) * w)
        return {"true_pos": state["true_pos"] + tp,
                "denom": state["denom"] + denom}

    def result(self, state):
        return state["true_pos"] / jnp.maximum(state["denom"], 1e-9)

    def _denom_mask(self, true, pred):
        raise NotImplementedError


class Precision(_ConfusionMetric):
    def __init__(self, name: str = "precision", threshold: float = 0.5,
                 from_logits: bool = False):
        super().__init__(name, threshold, from_logits)

    def _denom_mask(self, true, pred):
        return pred                                 # TP + FP


class Recall(_ConfusionMetric):
    def __init__(self, name: str = "recall", threshold: float = 0.5,
                 from_logits: bool = False):
        super().__init__(name, threshold, from_logits)

    def _denom_mask(self, true, pred):
        return true                                 # TP + FN


class MeanMetricWrapper(Metric):
    """Wrap a ``fn(y_true, y_pred) -> per-example values`` as a metric."""

    def __init__(self, fn, name: str | None = None):
        super().__init__(name or getattr(fn, "__name__", "metric"))
        self._fn = fn

    def _values(self, y_true, y_pred):
        return self._fn(y_true, y_pred)


def get(identifier, *, loss=None) -> Metric:
    """Resolve a metric identifier; "accuracy" picks the flavor matching
    the compiled loss (≙ tf_keras compile_utils.get_metric)."""
    from distributed_tensorflow_tpu.training import losses as losses_lib
    if isinstance(identifier, Metric):
        return identifier
    if callable(identifier) and not isinstance(identifier, str):
        return MeanMetricWrapper(identifier)
    key = str(identifier).lower()
    if key in ("accuracy", "acc"):
        if isinstance(loss, losses_lib.BinaryCrossentropy):
            return BinaryAccuracy()
        if isinstance(loss, losses_lib.CategoricalCrossentropy):
            return CategoricalAccuracy()
        return SparseCategoricalAccuracy()
    table = {
        "sparse_categorical_accuracy": SparseCategoricalAccuracy,
        "categorical_accuracy": CategoricalAccuracy,
        "binary_accuracy": BinaryAccuracy,
        "precision": Precision,
        "recall": Recall,
        "top_k_categorical_accuracy": TopKCategoricalAccuracy,
        "sparse_top_k_categorical_accuracy": TopKCategoricalAccuracy,
    }
    if key in table:
        metric = table[key]()
        # history/monitor keys must equal the compiled string (tf_keras
        # names the metric exactly what compile() was given)
        metric.name = key
        return metric
    raise ValueError(f"Unknown metric: {identifier!r}")
