"""keras.optimizers.schedules-shaped learning-rate schedules.

≙ TFK/src/optimizers/schedules/learning_rate_schedule.py — the same
constructor signatures and step semantics, as jit-traceable callables
``schedule(step) -> lr``. ``optax.inject_hyperparams`` detects callables
and re-evaluates them every update, so a schedule passed to any
``keras.optimizers.*`` constructor (or used directly with optax) decays
per OPTIMIZER STEP, exactly like the reference.
"""

from __future__ import annotations

import jax.numpy as jnp


class LearningRateSchedule:
    """Base class (≙ keras LearningRateSchedule): callable on a step."""

    def __call__(self, step):
        raise NotImplementedError

    def get_config(self) -> dict:
        raise NotImplementedError


class ExponentialDecay(LearningRateSchedule):
    def __init__(self, initial_learning_rate, decay_steps, decay_rate,
                 staircase: bool = False, name: str | None = None):
        self.initial_learning_rate = float(initial_learning_rate)
        self.decay_steps = int(decay_steps)
        self.decay_rate = float(decay_rate)
        self.staircase = bool(staircase)
        self.name = name

    def __call__(self, step):
        p = jnp.asarray(step, jnp.float32) / self.decay_steps
        if self.staircase:
            p = jnp.floor(p)
        return self.initial_learning_rate * jnp.power(self.decay_rate, p)

    def get_config(self):
        return {"initial_learning_rate": self.initial_learning_rate,
                "decay_steps": self.decay_steps,
                "decay_rate": self.decay_rate,
                "staircase": self.staircase, "name": self.name}


class CosineDecay(LearningRateSchedule):
    def __init__(self, initial_learning_rate, decay_steps,
                 alpha: float = 0.0, name: str | None = None):
        self.initial_learning_rate = float(initial_learning_rate)
        self.decay_steps = int(decay_steps)
        self.alpha = float(alpha)
        self.name = name

    def __call__(self, step):
        frac = jnp.minimum(jnp.asarray(step, jnp.float32)
                           / self.decay_steps, 1.0)
        cosine = 0.5 * (1.0 + jnp.cos(jnp.pi * frac))
        return self.initial_learning_rate * (
            (1.0 - self.alpha) * cosine + self.alpha)

    def get_config(self):
        return {"initial_learning_rate": self.initial_learning_rate,
                "decay_steps": self.decay_steps, "alpha": self.alpha,
                "name": self.name}


class PiecewiseConstantDecay(LearningRateSchedule):
    def __init__(self, boundaries, values, name: str | None = None):
        if len(values) != len(boundaries) + 1:
            raise ValueError(
                f"values needs len(boundaries)+1 entries; got "
                f"{len(values)} values for {len(boundaries)} boundaries")
        self.boundaries = [int(b) for b in boundaries]
        self.values = [float(v) for v in values]
        self.name = name

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        lr = jnp.asarray(self.values[0], jnp.float32)
        for b, v in zip(self.boundaries, self.values[1:]):
            lr = jnp.where(step > b, v, lr)
        return lr

    def get_config(self):
        return {"boundaries": self.boundaries, "values": self.values,
                "name": self.name}


class PolynomialDecay(LearningRateSchedule):
    def __init__(self, initial_learning_rate, decay_steps,
                 end_learning_rate: float = 1e-4, power: float = 1.0,
                 cycle: bool = False, name: str | None = None):
        self.initial_learning_rate = float(initial_learning_rate)
        self.decay_steps = int(decay_steps)
        self.end_learning_rate = float(end_learning_rate)
        self.power = float(power)
        self.cycle = bool(cycle)
        self.name = name

    def __call__(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.cycle:
            mult = jnp.maximum(
                1.0, jnp.ceil(step / jnp.maximum(self.decay_steps, 1)))
            decay_steps = self.decay_steps * mult
        else:
            decay_steps = jnp.asarray(self.decay_steps, jnp.float32)
            step = jnp.minimum(step, decay_steps)
        frac = 1.0 - step / decay_steps
        return ((self.initial_learning_rate - self.end_learning_rate)
                * jnp.power(frac, self.power) + self.end_learning_rate)

    def get_config(self):
        return {"initial_learning_rate": self.initial_learning_rate,
                "decay_steps": self.decay_steps,
                "end_learning_rate": self.end_learning_rate,
                "power": self.power, "cycle": self.cycle,
                "name": self.name}
