"""Whole-model persistence: ``model.save`` / ``keras.models.load_model``.

≙ TFK/src/engine/training.py:2779 ``Model.save`` + TFK/src/saving/ —
scoped to the shim surface: a saved model is a directory holding
``model_config.json`` (the Sequential layer stack — or the Functional
DAG with node records — as keras-style ``{class_name, config}``
records) plus a dtx Checkpoint of the weights
(params + model_state), written with the same index-last commit
protocol as every other checkpoint in the framework
(checkpoint/checkpoint.py). ``load_model`` reconstructs the layer
stack from the registry (training/layers.py), builds, and restores the
weights; compile state is NOT serialized (call ``compile`` after
loading, like tf_keras ``load_model(compile=False)``).
"""

from __future__ import annotations

import json
import os

MODEL_CONFIG = "model_config.json"
WEIGHTS_SUBDIR = "weights"


def _encode_args(args, node_ids):
    """call_args structure -> JSON: symbolic tensors become
    {"__node__": id} markers; TUPLES (multi-positional layer calls,
    e.g. mha(q, v)) are tagged so decoding can distinguish them from
    plain list arguments (e.g. Add()([a, b]))."""
    from distributed_tensorflow_tpu.training.functional import (
        SymbolicTensor)
    if isinstance(args, SymbolicTensor):
        if args.source is not None:     # output i of a multi-output call
            return {"__node_out__": [node_ids[args.source.uid],
                                     args.index]}
        return {"__node__": node_ids[args.uid]}
    if isinstance(args, tuple):
        return {"__tuple__": [_encode_args(a, node_ids) for a in args]}
    if isinstance(args, list):
        return [_encode_args(a, node_ids) for a in args]
    if isinstance(args, (int, float, str, bool, type(None))):
        return args
    raise ValueError(
        f"functional call argument {args!r} is not serializable")


def _decode_args(enc, nodes):
    if isinstance(enc, dict) and "__node__" in enc:
        return nodes[enc["__node__"]]
    if isinstance(enc, dict) and "__node_out__" in enc:
        i, idx = enc["__node_out__"]
        return nodes[i][idx]
    if isinstance(enc, dict) and "__tuple__" in enc:
        return tuple(_decode_args(a, nodes) for a in enc["__tuple__"])
    if isinstance(enc, list):
        return [_decode_args(a, nodes) for a in enc]
    return enc


def _functional_config(model) -> dict:
    """Serialize a Functional model's DAG (≙ TFK Functional.get_config:
    layers by index + node records with encoded call args)."""
    layer_index = {id(lyr): i for i, lyr in enumerate(model.layers)}
    node_ids = {}
    for i, inp in enumerate(model.inputs):
        node_ids[inp.uid] = i
    nodes = []
    for n, node in enumerate(model._graph_nodes):
        node_ids[node.uid] = len(model.inputs) + n
        nodes.append({
            "layer": layer_index[id(node.layer)],
            "args": _encode_args(node.call_args, node_ids),
        })
    return {
        "class_name": "Functional",
        "config": {
            "layers": [{"class_name": type(lyr).__name__,
                        "config": lyr.get_config()}
                       for lyr in model.layers],
            "inputs": [{"shape": list(i.shape), "dtype": str(i.dtype)}
                       for i in model.inputs],
            "nodes": nodes,
            "outputs": [_encode_args(o, node_ids)
                        for o in model.outputs],
        },
    }


def _rebuild_functional(config: dict):
    from distributed_tensorflow_tpu import keras
    from distributed_tensorflow_tpu.training import layers as layers_lib

    layers = [ _layer_from_record(rec, layers_lib)
               for rec in config["layers"] ]
    nodes = [keras.Input(shape=tuple(i["shape"]), dtype=i["dtype"])
             for i in config["inputs"]]
    inputs = list(nodes)
    for rec in config["nodes"]:
        layer = layers[rec["layer"]]
        args = _decode_args(rec["args"], nodes)
        # tuple = original multi-positional call (mha(q, v)); anything
        # else was a single argument (tensor or list of tensors)
        nodes.append(layer(*args) if isinstance(args, tuple)
                     else layer(args))
    outputs = [_decode_args(o, nodes) for o in config["outputs"]]
    return keras.Model(inputs=inputs if len(inputs) > 1 else inputs[0],
                       outputs=outputs if len(outputs) > 1 else outputs[0])


def _layer_from_record(rec: dict, layers_lib):
    cls = getattr(layers_lib, rec["class_name"], None)
    if cls is None or not (isinstance(cls, type)
                           and issubclass(cls, layers_lib.Layer)):
        raise ValueError(
            f"unknown layer class {rec['class_name']!r} in saved "
            f"model config")
    return cls.from_config(rec["config"])


def model_config(model) -> dict:
    """Architecture-only serialization (raises for unsupported model
    kinds or unserializable layers — used by ModelCheckpoint's
    fail-fast check as well as save_model)."""
    from distributed_tensorflow_tpu.training import functional
    from distributed_tensorflow_tpu.training import layers as layers_lib

    if isinstance(model, layers_lib.Sequential):
        return {
            "class_name": "Sequential",
            "config": {"layers": [
                {"class_name": type(lyr).__name__,
                 "config": lyr.get_config()}
                for lyr in model.layers]},
        }
    if isinstance(model, functional.Model) and hasattr(model,
                                                       "_graph_nodes"):
        return _functional_config(model)
    raise NotImplementedError(
        f"save_model supports shim Sequential and Functional "
        f"models; got {type(model).__name__}. For other models use "
        "save_weights/load_weights (weights only).")


def save_model(model, filepath: str) -> None:
    """Serialize a shim Sequential or Functional: architecture +
    weights."""
    config = model_config(model)
    if not model._built:
        raise ValueError("build the model (or fit once) before save()")
    os.makedirs(filepath, exist_ok=True)
    tmp = os.path.join(filepath, MODEL_CONFIG + ".tmp")
    with open(tmp, "w") as f:
        json.dump(config, f, indent=1)
    os.replace(tmp, os.path.join(filepath, MODEL_CONFIG))
    model.save_weights(os.path.join(filepath, WEIGHTS_SUBDIR))


def load_model(filepath: str):
    """Rebuild a saved Sequential and restore its weights (uncompiled —
    call ``compile`` before further training, like tf_keras
    ``load_model(compile=False)``)."""
    from distributed_tensorflow_tpu.training import layers as layers_lib

    config_path = os.path.join(filepath, MODEL_CONFIG)
    if not os.path.exists(config_path):
        raise FileNotFoundError(
            f"no saved model at {filepath!r} ({MODEL_CONFIG} missing)")
    with open(config_path) as f:
        config = json.load(f)
    kind = config.get("class_name")
    if kind == "Functional":
        model = _rebuild_functional(config["config"])
    elif kind == "Sequential":
        stack = [_layer_from_record(rec, layers_lib)
                 for rec in config["config"]["layers"]]
        model = layers_lib.Sequential(stack)
    else:
        raise NotImplementedError(
            f"load_model supports Sequential/Functional; got {kind!r}")
    if not model._built:
        raise ValueError(
            "saved model has no shape-pinning layer (Input/input_shape=) "
            "— cannot rebuild parameters before loading weights")
    model.load_weights(os.path.join(filepath, WEIGHTS_SUBDIR))
    return model
