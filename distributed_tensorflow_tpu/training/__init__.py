"""Training-loop layer: Model.compile/fit/evaluate on a Strategy.

≙ the reference's Keras engine layer (SURVEY.md §1 L7,
tf_keras/src/engine/training.py)."""

from distributed_tensorflow_tpu.training.model import Model
from distributed_tensorflow_tpu.training import losses
from distributed_tensorflow_tpu.training import metrics
from distributed_tensorflow_tpu.training import callbacks
from distributed_tensorflow_tpu.training.callbacks import (
    BackupAndRestore,
    Callback,
    CSVLogger,
    EarlyStopping,
    History,
    LearningRateScheduler,
    ModelCheckpoint,
    ReduceLROnPlateau,
    TensorBoard,
    TerminateOnNaN,
)

__all__ = [
    "Model", "losses", "metrics", "callbacks", "Callback", "History",
    "EarlyStopping", "ModelCheckpoint", "LearningRateScheduler",
    "BackupAndRestore", "TensorBoard", "ReduceLROnPlateau",
    "CSVLogger", "TerminateOnNaN",
]
