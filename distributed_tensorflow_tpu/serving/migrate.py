"""KV-block migration: the primitive behind disaggregated serving.

The reference's WorkerService separates roles so each process does one
thing well; this module does the same for serving (DistServe, Zhong et
al. OSDI'24; Splitwise, Patel et al. ISCA'24): **prefill replicas** run
admission + prompt prefill only, **decode replicas** run the
memory-bound token loop, and a prompt's computed KV blocks move between
them as a :class:`MigrationPayload` — raw pool block rows (quantisation
scales included), the request, and every token generated so far carried
as LIVE state so the adopter replays nothing.

One primitive, three uses:

- **Disaggregation** (:class:`DisaggregatedEngine`) — a compute-bound
  prefill burst runs on the prefill replica while decode replicas keep
  emitting tokens: decode p99 stops paying for other requests'
  prompts. Greedy outputs are byte-identical to the monolithic engine
  (placement never changes argmax), which the tests pin per
  ``kv_dtype``.
- **Drain-by-migration** — a scale-down/preempted replica exports its
  live sequences to blobs a survivor adopts, instead of requeueing and
  REPLAYING generated tokens: the ``preempt_replay`` badput bucket
  goes to ~0 and handoff cost is priced honestly in the new
  ``kv_migrate`` bucket (telemetry/goodput.py).
- **Rescue** — when a decode replica's pool is exhausted, the
  scheduler's preemption hook first tries to migrate the victim to a
  sibling replica with free capacity; only when nobody can take it
  does the classic replay-requeue run.

**Wire format.** :func:`pack_payload` serializes a payload to one
blob: an 8-byte big-endian length, a JSON header (request fields +
per-array ``(name, shape, dtype)``), then each array's raw bytes in
header order. Arrays round-trip bit-exactly for every pool dtype —
bfloat16 included — because bytes are never reinterpreted through a
lossy dtype. The blob travels over the chunked (≤2 MiB) write-once
transport factored out of ``checkpoint/peer_snapshot.py``
(:func:`~distributed_tensorflow_tpu.checkpoint.peer_snapshot.
kv_put_blob`): chunks first, the chunk COUNT last, so a publisher
SIGKILLed mid-migration never leaves an adoptable half-blob — the
request is simply re-served from its prompt, and duplicates stay
byte-identical.

:class:`FileKV` is a filesystem agent for that transport (atomic
``os.replace`` per key), so migration works replica→replica through a
shared run directory without a coordination service; in-process
disaggregation skips the wire entirely unless asked to prove it
(``wire=True`` packs/unpacks every payload through the real format).
"""

from __future__ import annotations

import dataclasses
import json
import os
import struct
import time

import numpy as np

from distributed_tensorflow_tpu.checkpoint.peer_snapshot import (
    kv_blob_committed, kv_get_blob, kv_put_blob)


class FileKV:
    """Filesystem key-value agent for the chunked blob transport.

    Quacks like the coordination service's KV surface
    (``key_value_set`` / ``key_value_get`` / ``key_value_try_get``):
    every key is one file, committed atomically via ``os.replace`` —
    a reader never observes a torn value, and a writer SIGKILLed
    mid-``set`` leaves only an ignored ``.tmp`` file. Keys may contain
    ``/`` (flattened to ``__`` on disk)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key.replace("/", "__"))

    def key_value_set(self, key: str, value):
        if isinstance(value, str):
            value = value.encode("utf-8")
        path = self._path(key)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(value)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    def key_value_try_get(self, key: str) -> "bytes | None":
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def key_value_get(self, key: str, timeout_s: float = 10.0) -> bytes:
        deadline = time.monotonic() + timeout_s
        while True:
            val = self.key_value_try_get(key)
            if val is not None:
                return val
            if time.monotonic() >= deadline:
                raise TimeoutError(f"FileKV: key {key!r} not published "
                                   f"within {timeout_s}s")
            time.sleep(0.005)

    def list(self, prefix: str = "") -> list[str]:
        """Committed keys under ``prefix`` (tmp files excluded)."""
        flat = prefix.replace("/", "__")
        out = []
        try:
            names = os.listdir(self.root)
        except FileNotFoundError:
            return []
        for name in names:
            if ".tmp." in name:
                continue
            if name.startswith(flat):
                out.append(name.replace("__", "/"))
        return sorted(out)


@dataclasses.dataclass
class MigrationPayload:
    """Everything a replica needs to CONTINUE someone else's sequence.

    ``arrays`` are the sequence's pool block rows gathered source-side:
    ``k``/``v`` shaped ``(n_layers, n_blocks * block_size, n_heads,
    head_dim)`` in the pool's storage dtype, plus ``k_scale`` /
    ``v_scale`` ``(n_layers, rows, n_heads)`` f32 when quantized — the
    scales travel WITH their blocks, so int8 pools migrate ~4× cheaper
    than f32 on the wire and still dequantize identically.
    ``generated`` is live state (the adopter appends to it; nothing is
    replayed); ``generated_prefix`` preserves replay provenance from
    preemptions that happened BEFORE this migration. ``fingerprint``
    must equal the adopter's pool fingerprint; ``pool_epoch`` names the
    source incarnation (drain handoffs are fenced against staleness by
    the ADOPTER's policy, not here)."""

    request_id: str
    tokens: tuple
    max_new_tokens: int
    eos_id: "int | None"
    generated_prefix: tuple
    generated: tuple
    length: int
    fingerprint: dict
    pool_epoch: str
    arrival_wall: "float | None"
    ttft_s: "float | None"
    preemptions: int
    arrays: dict

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays.values())

    @property
    def n_blocks(self) -> int:
        bs = self.fingerprint["block_size"]
        return self.arrays["k"].shape[1] // bs


def _np_dtype(name: str) -> np.dtype:
    """Dtype from its string name, extended dtypes included —
    ``np.dtype("bfloat16")`` fails in plain numpy, but jax's ml_dtypes
    registration makes ``np.dtype(jnp.bfloat16)`` real."""
    try:
        return np.dtype(name)
    except TypeError:
        import jax.numpy as jnp
        return np.dtype(getattr(jnp, name))


def pack_payload(payload: MigrationPayload) -> bytes:
    """One self-describing blob: ``[8B header length][JSON header]
    [array bytes...]``. Raw ``tobytes`` per array — bit-exact for
    every ``kv_dtype``."""
    names = sorted(payload.arrays)
    header = {
        "request_id": payload.request_id,
        "tokens": list(payload.tokens),
        "max_new_tokens": payload.max_new_tokens,
        "eos_id": payload.eos_id,
        "generated_prefix": list(payload.generated_prefix),
        "generated": list(payload.generated),
        "length": payload.length,
        "fingerprint": payload.fingerprint,
        "pool_epoch": payload.pool_epoch,
        "arrival_wall": payload.arrival_wall,
        "ttft_s": payload.ttft_s,
        "preemptions": payload.preemptions,
        "arrays": [{"name": n,
                    "shape": list(payload.arrays[n].shape),
                    "dtype": str(payload.arrays[n].dtype)}
                   for n in names],
    }
    head = json.dumps(header).encode("utf-8")
    parts = [struct.pack(">Q", len(head)), head]
    parts.extend(np.ascontiguousarray(payload.arrays[n]).tobytes()
                 for n in names)
    return b"".join(parts)


def unpack_payload(blob: bytes) -> MigrationPayload:
    (head_len,) = struct.unpack(">Q", blob[:8])
    header = json.loads(blob[8:8 + head_len].decode("utf-8"))
    arrays = {}
    off = 8 + head_len
    for spec in header["arrays"]:
        dt = _np_dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        n = dt.itemsize * int(np.prod(shape)) if shape else dt.itemsize
        arrays[spec["name"]] = np.frombuffer(
            blob[off:off + n], dtype=dt).reshape(shape)
        off += n
    if off != len(blob):
        raise ValueError(f"migration blob: {len(blob) - off} trailing "
                         f"bytes (corrupt or mismatched header)")
    return MigrationPayload(
        request_id=header["request_id"],
        tokens=tuple(header["tokens"]),
        max_new_tokens=header["max_new_tokens"],
        eos_id=header["eos_id"],
        generated_prefix=tuple(header["generated_prefix"]),
        generated=tuple(header["generated"]),
        length=header["length"],
        fingerprint=header["fingerprint"],
        pool_epoch=header["pool_epoch"],
        arrival_wall=header["arrival_wall"],
        ttft_s=header["ttft_s"],
        preemptions=header["preemptions"],
        arrays=arrays)


def publish_payload(agent, prefix: str, payload: MigrationPayload):
    """Ship a payload over the write-once chunked transport. The chunk
    COUNT commits last — :func:`payload_committed` (and any adopter)
    sees either nothing or the complete blob, never a torn one."""
    kv_put_blob(agent, prefix, pack_payload(payload))


def fetch_payload(agent, prefix: str,
                  timeout_s: float = 10.0) -> MigrationPayload:
    return unpack_payload(kv_get_blob(agent, prefix,
                                      timeout_s=timeout_s))


def payload_committed(agent, prefix: str) -> bool:
    return kv_blob_committed(agent, prefix)


class DisaggregatedEngine:
    """Prefill/decode disaggregation over in-process engine replicas.

    One ``role="prefill"`` :class:`~distributed_tensorflow_tpu.serving.
    engine.InferenceEngine` owns admission, the prefix cache and prompt
    prefill; ``num_decode`` full engines own the token loop. Each
    :meth:`step`:

    1. steps the prefill engine (admit + prefill; scoring and
       1-token requests complete right there);
    2. EXPORTS every prefilled, unfinished sequence to the decode
       replica with capacity (deterministic round-robin — placement
       never affects greedy outputs), ``wire=True`` proving the real
       pack/unpack wire format on every hop;
    3. steps every decode engine.

    A decode replica that must preempt (pool exhausted) first offers
    the victim to its siblings via the scheduler's preemption hook
    (**rescue** migration — no replay); only when every sibling is
    full does the classic replay-requeue run, on the victim's own
    replica, preserving monolithic semantics exactly.

    The public surface mirrors the monolithic engine where the bench,
    replica runtime and tests touch it: ``submit`` / ``step`` /
    ``run_until_idle`` / ``generate`` / ``stats`` / ``idle``.
    """

    def __init__(self, cfg, params, *, num_decode: int = 1,
                 wire: bool = False, rescue: bool = True,
                 **engine_kwargs):
        from distributed_tensorflow_tpu.serving.engine import (
            InferenceEngine)
        if num_decode < 1:
            raise ValueError("num_decode must be >= 1")
        pf_kwargs = dict(engine_kwargs)
        # the prefill replica never decodes: no draft model, and the
        # spill tier follows the prefix cache (which lives with
        # admission, i.e. here)
        for k in ("speculative_k", "draft_params", "draft_cfg"):
            pf_kwargs.pop(k, None)
        self.prefill = InferenceEngine(cfg, params, role="prefill",
                                       **pf_kwargs)
        dec_kwargs = dict(engine_kwargs)
        dec_kwargs.pop("spill_tier", None)
        # decode replicas run no admission-side prefix matching —
        # adopted blocks arrive private, and caching there would only
        # duplicate the prefill replica's cache
        dec_kwargs["prefix_caching"] = False
        self.decoders = [InferenceEngine(cfg, params, **dec_kwargs)
                         for _ in range(num_decode)]
        self.wire = bool(wire)
        self.rescue = bool(rescue)
        self._rr = 0                      # round-robin placement cursor
        self.migrations: list[dict] = []
        if rescue and num_decode > 1:
            for i, eng in enumerate(self.decoders):
                eng.scheduler.preempt_hook = (
                    lambda victim, _i=i: self._rescue(_i, victim))

    # -- placement ---------------------------------------------------------
    def _decoder_for(self, n_blocks: int,
                     exclude: "int | None" = None) -> "int | None":
        """First decode replica (round-robin from the cursor) with a
        free slot and ``n_blocks`` free blocks; None when all full."""
        n = len(self.decoders)
        for k in range(n):
            i = (self._rr + k) % n
            if i == exclude:
                continue
            eng = self.decoders[i]
            if (eng.scheduler._free_slots
                    and eng.scheduler.allocator.num_free >= n_blocks):
                self._rr = (i + 1) % n
                return i
        return None

    def _ship(self, src_engine, seq, dst: int, *, kind: str,
              src: str) -> None:
        t0 = time.monotonic()
        payload = src_engine.export_sequence(seq, reason=kind)
        if self.wire:
            payload = unpack_payload(pack_payload(payload))
        self.decoders[dst].adopt_sequence(payload)
        self.migrations.append({
            "id": payload.request_id, "kind": kind, "src": src,
            "dst": f"decode{dst}", "blocks": payload.n_blocks,
            "bytes": payload.nbytes,
            "ms": (time.monotonic() - t0) * 1e3})

    def _rescue(self, src: int, victim) -> bool:
        """Preemption hook on decode replica ``src``: migrate the
        victim to a sibling instead of replaying. True = taken."""
        dst = self._decoder_for(len(victim.table.blocks), exclude=src)
        if dst is None:
            return False
        self._ship(self.decoders[src], victim, dst, kind="rescue",
                   src=f"decode{src}")
        return True

    # -- engine surface ----------------------------------------------------
    def submit(self, request, *, arrival_wall: "float | None" = None):
        return self.prefill.submit(request, arrival_wall=arrival_wall)

    def step(self) -> list[dict]:
        """One disaggregated iteration; returns completion records from
        every replica (order: prefill-side completions first, then
        decode replicas in index order)."""
        finished = list(self.prefill.step())
        sched = self.prefill.scheduler
        ready = sorted((s for s in sched.running.values()
                        if s.prefilled and not s.done),
                       key=lambda s: s.slot)
        for seq in ready:
            dst = self._decoder_for(len(seq.table.blocks))
            if dst is None:
                break       # every decoder full: park in prefill slot
            self._ship(self.prefill, seq, dst, kind="prefill",
                       src="prefill")
        for eng in self.decoders:
            finished.extend(eng.step())
        return finished

    @property
    def idle(self) -> bool:
        return (self.prefill.scheduler.idle
                and all(e.scheduler.idle for e in self.decoders))

    def run_until_idle(self, *, max_steps: int = 100000,
                       retry_faults: bool = False) -> dict:
        from distributed_tensorflow_tpu.resilience.faults import (
            FaultInjected)
        out: dict[str, dict] = {}
        for _ in range(max_steps):
            if self.idle:
                break
            try:
                for rec in self.step():
                    out[rec["id"]] = rec
            except FaultInjected:
                # every chaos site fires BEFORE its engine mutates
                # state, so re-running the whole composite step is safe
                if not retry_faults:
                    raise
        return out

    def generate(self, prompts, *, max_new_tokens: int = 16,
                 eos_id: int | None = None) -> list[list[int]]:
        from distributed_tensorflow_tpu.serving.scheduler import (
            Request)
        for i, p in enumerate(prompts):
            self.submit(Request(id=f"g{i}", tokens=tuple(p),
                                max_new_tokens=max_new_tokens,
                                eos_id=eos_id))
        done = self.run_until_idle()
        return [done[f"g{i}"]["tokens"] for i in range(len(prompts))]

    def block_accounting(self) -> dict:
        """Per-replica conservation audit + fleet totals (the chaos
        gate's zero-leak check)."""
        per = {"prefill": self.prefill.block_accounting()}
        for i, eng in enumerate(self.decoders):
            per[f"decode{i}"] = eng.block_accounting()
        per["leaked_refs"] = sum(v["leaked_refs"] for v in per.values()
                                 if isinstance(v, dict))
        per["conserved"] = all(v["conserved"] for v in per.values()
                               if isinstance(v, dict))
        return per

    def stats(self) -> dict:
        lat = sorted(m["ms"] for m in self.migrations)

        def pct(p):
            return (lat[min(len(lat) - 1,
                            int(round(p / 100 * (len(lat) - 1))))]
                    if lat else 0.0)

        return {
            "prefill": self.prefill.stats(),
            "decode": [e.stats() for e in self.decoders],
            "migrations": len(self.migrations),
            "migrations_rescue": sum(1 for m in self.migrations
                                     if m["kind"] == "rescue"),
            "migrated_bytes": sum(m["bytes"] for m in self.migrations),
            "migrate_p50_ms": pct(50),
            "migrate_p99_ms": pct(99),
        }
