"""Multi-tenant serving frontend: cache-affinity routing over replicas.

The horizontal layer over the supervised replica fleet (serving/
replica.py): a *router* admits live per-tenant request streams and
decides WHICH replica serves each one.

**Cache-affinity routing.** The prefix cache's index key is the chain
``(parent_key, block_tokens)`` — a pure function of prompt content and
``block_size`` (serving/kv_cache.py), so the router can compute every
request's chain keys WITHOUT any device state and remember which
replica last prefilled each chain (:func:`prefix_chain_keys`,
:class:`AffinityMap`). Requests sharing a prompt prefix land on the
replica already holding those KV blocks; the fallback is least-loaded
by live queue depth scraped from each replica's exported metrics
(telemetry/exporter.py ``metrics-live.prom`` — atomic-rename, never
torn), then seeded-random. ``policy="random"`` keeps the degenerate
router as a same-workload baseline: the measured hit-rate uplift of
affinity over random is a chaos-sweep gate, not a claim.

**Priority + quotas.** Admission rides serving/tenancy.py: per-tenant
token-bucket quotas (refusal = ``serve.reject`` stamped
``tenant``/``cause="quota"``), weighted-fair admission under a token
budget with batch shed (deferred) first, and batch promoted into the
interactive round once queued past its starvation deadline — batch
never starves past its own SLO.

**Crash tolerance.** Every decision appends to a line-buffered journal
(``router-journal.jsonl``) BEFORE the request is handed to a replica:
``route`` / ``reroute`` / ``reject`` / ``ack`` records. A killed
replica's routed-but-unacked requests are re-routed to a survivor
(detected by its stale metrics scrape + ack age), extending the PR 9
completion-log contract across replicas: zero dropped, duplicates
byte-identical under greedy decode. A killed ROUTER restarts from the
journal: decided requests are never re-offered (quota decisions are
durable), routed-but-unacked ones stay with their replica (no
double-serving) — only death re-routes them.

Transport is pluggable: the elastic example uses per-replica
line-buffered inbox files a :func:`~distributed_tensorflow_tpu.serving.
replica.routed_replica` tails; ``bench.py --serving --router`` wires
``submit_fn`` straight into in-process engines.
"""

from __future__ import annotations

import json
import os
import random
import time

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.serving.scheduler import Request
from distributed_tensorflow_tpu.serving.tenancy import (
    TenancyController, TenantConfig)

ROUTER_JOURNAL = "router-journal.jsonl"


def prefix_chain_keys(tokens, block_size: int) -> list:
    """The PrefixCache chain keys of a prompt — the SAME
    ``(parent_key, block_tokens)`` chain serving/kv_cache.py indexes,
    computed from content alone. Only full blocks over ``tokens[:-1]``
    chain (prefill must always compute the final prompt position), so
    a router-side hit prediction never claims more than the replica's
    cache could actually serve."""
    toks = tuple(int(t) for t in tokens)
    limit = len(toks) - 1
    keys: list = []
    key = None
    n = 0
    while n + block_size <= limit:
        key = (key, toks[n:n + block_size])
        keys.append(key)
        n += block_size
    return keys


class AffinityMap:
    """chain key -> replica that last prefilled it (router-side view of
    where KV blocks live). Bounded LRU so a long run cannot grow it
    unboundedly — eviction order matches the replicas' own LRU bias."""

    def __init__(self, block_size: int, *, capacity: int = 4096):
        self.block_size = block_size
        self.capacity = capacity
        self._map: "dict[object, object]" = {}

    def observe(self, tokens, replica):
        """Record that ``replica`` (just) prefilled this prompt — its
        cache now holds every full block of the chain."""
        for k in prefix_chain_keys(tokens, self.block_size):
            self._map.pop(k, None)          # move-to-end (dict order)
            self._map[k] = replica
        while len(self._map) > self.capacity:
            self._map.pop(next(iter(self._map)))

    def forget(self, replica):
        """Drop a dead replica's entries (its cache died with it)."""
        self._map = {k: r for k, r in self._map.items() if r != replica}

    def lookup(self, tokens, live) -> "tuple[object, int] | None":
        """``(replica, depth)`` of the deepest chain hit on a live
        replica, or None. Depth = number of chained blocks matched —
        deeper means more KV served from cache."""
        best = None
        for depth, k in enumerate(
                prefix_chain_keys(tokens, self.block_size), start=1):
            r = self._map.get(k)
            if r is None:
                break
            if r in live:
                best = (r, depth)
        return best


class RoutingPolicy:
    """Pure routing decision: affinity > least-loaded > seeded random.

    ``policy`` narrows the cascade for baseline comparisons:
    ``"least_loaded"`` skips the affinity map, ``"random"`` ignores
    depth too. Queue depths come from :meth:`observe_depth` (the
    router's metrics scrape or its own outstanding counts).
    """

    def __init__(self, replicas, *, block_size: int = 8,
                 policy: str = "affinity", seed: int = 0,
                 affinity_capacity: int = 4096):
        if policy not in ("affinity", "least_loaded", "random"):
            raise ValueError(f"policy={policy!r}")
        self.policy = policy
        self.replicas = list(replicas)
        self.affinity = AffinityMap(block_size,
                                    capacity=affinity_capacity)
        self._rng = random.Random(f"dtx-router:{seed}")
        self._depth = {r: 0 for r in self.replicas}

    def set_replicas(self, replicas):
        self.replicas = list(replicas)
        for r in self.replicas:
            self._depth.setdefault(r, 0)

    def observe_depth(self, replica, depth: int):
        self._depth[replica] = int(depth)

    def observe_route(self, tokens, replica):
        if self.policy == "affinity":
            self.affinity.observe(tokens, replica)
        self._depth[replica] = self._depth.get(replica, 0) + 1

    def forget(self, replica):
        self.affinity.forget(replica)
        self._depth.pop(replica, None)

    def route(self, tokens, *, exclude=()) -> "tuple[object, str]":
        """``(replica, reason)`` with reason in
        ``{"affinity", "least_loaded", "random"}``."""
        live = [r for r in self.replicas if r not in exclude]
        if not live:
            raise RuntimeError("no live replicas to route to")
        if self.policy == "affinity":
            hit = self.affinity.lookup(tokens, set(live))
            if hit is not None:
                return hit[0], "affinity"
        if self.policy in ("affinity", "least_loaded"):
            depth = min(self._depth.get(r, 0) for r in live)
            tied = [r for r in live
                    if self._depth.get(r, 0) == depth]
            if len(tied) == 1:
                return tied[0], "least_loaded"
            return self._rng.choice(tied), "least_loaded"
        return self._rng.choice(live), "random"


class RouterJournal:
    """Line-buffered decision journal (the router's completion-log
    analogue): one JSON record per decision, appended BEFORE the
    decision takes effect, torn-tail tolerant on replay."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "a", buffering=1)
        self.seq = 0

    def record(self, kind: str, **fields):
        self.seq += 1
        self._f.write(json.dumps({"seq": self.seq, "kind": kind,
                                  **fields}) + "\n")

    def close(self):
        self._f.close()

    @staticmethod
    def replay(path: str) -> "list[dict]":
        """All intact records, in order; a torn trailing line (SIGKILL
        mid-write) is skipped — the decision it described never fully
        happened and will be re-taken."""
        out: list = []
        try:
            with open(path) as f:
                for line in f:
                    try:
                        rec = json.loads(line)
                    except ValueError:
                        continue
                    if isinstance(rec, dict) and "kind" in rec:
                        out.append(rec)
        except OSError:
            pass
        return out


def parse_queue_depth(prom_path: str) -> "int | None":
    """``serving/requests_queued`` from one replica's exported
    ``metrics-live.prom`` (PR 10 exporter; atomic rename — a read never
    sees a torn file). None when absent/unreadable."""
    try:
        with open(prom_path) as f:
            for line in f:
                if line.startswith("dtx_serving_requests_queued"):
                    try:
                        return int(float(line.rsplit(None, 1)[-1]))
                    except ValueError:
                        return None
    except OSError:
        return None
    return None


class Router:
    """Tenant-aware request router over a replica set.

    ``submit_fn(replica, request, meta)`` delivers a routed request
    (in-process: ``engine.submit``; elastic: an inbox-file append).
    With ``run_dir`` set, decisions journal to
    ``run_dir/router-journal.jsonl`` and a fresh Router resumes from
    it. ``clock`` is injectable for deterministic tests.
    """

    def __init__(self, *, replicas, tenants, submit_fn,
                 policy: str = "affinity", block_size: int = 8,
                 tick_token_budget: int = 96, seed: int = 0,
                 run_dir: "str | None" = None,
                 reroute_timeout_s: float = 8.0,
                 max_inflight_per_replica: int = 6,
                 clock=time.monotonic):
        self.policy = RoutingPolicy(replicas, block_size=block_size,
                                    policy=policy, seed=seed)
        tenants = tuple(tenants)
        if not all(isinstance(t, TenantConfig) for t in tenants):
            raise TypeError("tenants must be TenantConfig instances")
        self._clock = clock
        now = clock()
        self.tenancy = TenancyController(tenants, now=now)
        self.submit_fn = submit_fn
        self.tick_token_budget = tick_token_budget
        self.reroute_timeout_s = reroute_timeout_s
        #: flow control: routed-but-unacked cap per replica. Backlog
        #: beyond it waits at the ROUTER (where priority classes order
        #: the release), not in a replica's FIFO admission queue where
        #: an interactive request would sit behind every batch request
        #: dispatched before it.
        self.max_inflight_per_replica = max_inflight_per_replica
        self.run_dir = run_dir
        self.journal: "RouterJournal | None" = None
        #: rid -> route state {replica, tenant, pclass, request,
        #: routed_at, reroutes}
        self.inflight: "dict[str, dict]" = {}
        self.acked: set = set()
        #: rids decided in a PREVIOUS incarnation (never re-offered)
        self.decided: set = set()
        self.resumed = 0
        #: per-class queued-but-not-yet-routed requests
        self._queues: "dict[str, list]" = {}     # tenant -> [(enq, req)]
        #: deficit-round-robin credit: a backlogged tenant's unused
        #: grant carries over until it covers its head-of-line request
        #: (a tick budget smaller than one request cost still makes
        #: progress); resets when the tenant's queue empties
        self._credit: "dict[str, float]" = {}
        self.routes = 0
        self.reroutes = 0
        self.route_reasons: "dict[str, int]" = {}
        reg = telemetry.get_registry()
        self._m_inflight = reg.gauge(
            "router/inflight", "routed-but-unacked requests")
        self._m_queued = reg.gauge(
            "router/queued", "admitted requests awaiting dispatch")
        self._m_reroutes = reg.counter(
            "router/reroutes_total",
            "requests re-routed off a dead/unresponsive replica")
        if run_dir:
            path = os.path.join(run_dir, ROUTER_JOURNAL)
            self._resume(path, now)
            self.journal = RouterJournal(path)

    # -- journal resume ----------------------------------------------------
    def _resume(self, path: str, now: float):
        """Rebuild decision state from a previous incarnation's
        journal. Routed-but-unacked requests stay with their replica —
        resuming must NEVER double-serve; only a replica's death (or
        ack timeout) re-routes them later."""
        if not os.path.exists(path):
            return
        for rec in RouterJournal.replay(path):
            rid = rec.get("id")
            kind = rec.get("kind")
            if kind in ("route", "reroute") and rid is not None:
                self.decided.add(rid)
                st = self.inflight.setdefault(rid, {
                    "tenant": rec.get("tenant"),
                    "pclass": rec.get("pclass"),
                    "request": None, "reroutes": 0})
                st["replica"] = rec.get("replica")
                st["routed_at"] = now
                if kind == "reroute":
                    st["reroutes"] = st.get("reroutes", 0) + 1
            elif kind == "reject" and rid is not None:
                self.decided.add(rid)
            elif kind == "ack" and rid is not None:
                self.acked.add(rid)
                self.inflight.pop(rid, None)
        self.resumed = len(self.inflight)
        if self.resumed or self.acked:
            telemetry.event("router.resume",
                            inflight=self.resumed,
                            acked=len(self.acked),
                            decided=len(self.decided))

    # -- admission ---------------------------------------------------------
    def offer(self, request: Request, *, now: "float | None" = None
              ) -> str:
        """Admit one arriving request: quota-check, then queue for the
        next dispatch tick. Returns ``"admitted"``, ``"duplicate"``
        (decided by a previous incarnation) or ``"rejected:quota"``."""
        now = self._clock() if now is None else now
        if request.id in self.decided:
            return "duplicate"
        tenant = request.tenant or "-"
        if tenant not in self.tenancy.tenants:
            raise KeyError(f"unknown tenant {tenant!r} "
                           f"(request {request.id})")
        cost = TenancyController.cost_of(request)
        if not self.tenancy.charge(tenant, cost, now):
            if self.journal:
                self.journal.record("reject", id=request.id,
                                    tenant=tenant, cause="quota")
            self.decided.add(request.id)
            telemetry.event("serve.reject", id=request.id,
                            tenant=tenant, pclass=request.pclass,
                            cause="quota", queued=self.queued)
            return "rejected:quota"
        self._queues.setdefault(tenant, []).append((now, request))
        self._m_queued.set(self.queued)
        return "admitted"

    @property
    def queued(self) -> int:
        return sum(len(q) for q in self._queues.values())

    # -- dispatch ----------------------------------------------------------
    def dispatch(self, *, now: "float | None" = None,
                 budget: "int | None" = None,
                 stale: "set | frozenset" = frozenset()
                 ) -> "list[Request]":
        """One admission tick: weighted-fair token allocation across
        tenants (batch subordinate unless aged past its starvation
        deadline), then route + journal + submit each granted request
        in FIFO order.

        Overload-safe: a ``stale`` replica (caller's scrape-staleness
        verdict) or one already at ``max_inflight_per_replica``
        routed-but-unacked requests takes no new work. With every
        replica closed the whole queue holds HERE — no credit accrues,
        nothing is shed, and when capacity returns the backlog releases
        in priority order (interactive, aged batch, batch) instead of
        landing FIFO in a dead replica's inbox. Returns the dispatched
        requests."""
        now = self._clock() if now is None else now
        budget = self.tick_token_budget if budget is None else budget
        demands = {t: sum(TenancyController.cost_of(r)
                          for _, r in q)
                   for t, q in self._queues.items() if q}
        if not demands:
            return []
        counts: "dict[object, int]" = {}
        for st in self.inflight.values():
            r = st.get("replica")
            counts[r] = counts.get(r, 0) + 1

        def _closed():
            return {r for r in self.policy.replicas
                    if r in stale
                    or counts.get(r, 0) >= self.max_inflight_per_replica}

        if len(_closed()) == len(self.policy.replicas):
            self._m_queued.set(self.queued)
            return []              # fleet down/saturated: hold the queue
        aged = {t for t, q in self._queues.items()
                if q and self.tenancy.tenant(t).pclass == "batch"
                and now - q[0][0]
                >= self.tenancy.tenant(t).starvation_deadline_s}
        alloc = self.tenancy.plan_tick(demands, budget=budget,
                                       aged=aged)
        dispatched: "list[Request]" = []
        blocked = False

        def _rank(t):
            cfg = self.tenancy.tenant(t)
            tier = 0 if cfg.pclass != "batch" else (1 if t in aged
                                                    else 2)
            return (tier, t)

        for tenant in sorted(self._queues, key=_rank):
            q = self._queues[tenant]
            grant = self._credit.get(tenant, 0.0) \
                + alloc.get(tenant, 0.0)
            while q and not blocked:
                cost = TenancyController.cost_of(q[0][1])
                if cost > grant + 1e-9:
                    break
                closed = _closed()
                if len(closed) == len(self.policy.replicas):
                    blocked = True   # filled the fleet mid-tick
                    break
                enq, req = q.pop(0)
                grant -= cost
                replica = self._route(req, tenant, now,
                                      exclude=closed)
                counts[replica] = counts.get(replica, 0) + 1
                dispatched.append(req)
            # DRR: keep the remainder only while backlogged — an idle
            # tenant must not hoard credit across quiet periods
            self._credit[tenant] = grant if q else 0.0
            if q and not blocked \
                    and self.tenancy.tenant(tenant).pclass == "batch" \
                    and tenant not in aged:
                # deferred under pressure: observable shed, once per
                # tick per tenant (the count, not the event rate, is
                # what reports render)
                self.tenancy.note_shed(tenant)
                telemetry.event("router.shed", tenant=tenant,
                                queued=len(q),
                                oldest_wait_s=round(now - q[0][0], 4))
        self._m_queued.set(self.queued)
        return dispatched

    def _route(self, req: Request, tenant: str, now: float,
               *, exclude=(), cause: "str | None" = None):
        replica, reason = self.policy.route(req.tokens,
                                            exclude=exclude)
        kind = "reroute" if cause else "route"
        if self.journal:
            self.journal.record(kind, id=req.id, tenant=tenant,
                                pclass=req.pclass, replica=replica,
                                reason=reason, cause=cause)
        st = self.inflight.setdefault(req.id, {
            "tenant": tenant, "pclass": req.pclass, "reroutes": 0})
        st.update(replica=replica, request=req, routed_at=now)
        self.decided.add(req.id)
        self.policy.observe_route(req.tokens, replica)
        span = f"req/{req.id}"
        if cause:
            st["reroutes"] += 1
            self.reroutes += 1
            self._m_reroutes.increment()
            telemetry.event("router.reroute", id=req.id, span_id=span,
                            tenant=tenant, pclass=req.pclass,
                            replica=replica, cause=cause)
        else:
            self.routes += 1
            self.route_reasons[reason] = \
                self.route_reasons.get(reason, 0) + 1
            telemetry.event("router.route", id=req.id, span_id=span,
                            tenant=tenant, pclass=req.pclass,
                            replica=replica, reason=reason)
        self._m_inflight.set(len(self.inflight))
        self.submit_fn(replica, req,
                       {"tenant": tenant, "pclass": req.pclass,
                        "reroute": bool(cause)})
        return replica

    # -- acks + failure handling ------------------------------------------
    def note_completed(self, rids) -> int:
        """Mark completions (from the replicas' completion-log union);
        journals an ``ack`` per newly-acked rid so a restarted router
        knows they are done."""
        n = 0
        for rid in rids:
            if rid in self.acked:
                continue
            self.acked.add(rid)
            if self.inflight.pop(rid, None) is not None:
                n += 1
            if self.journal:
                self.journal.record("ack", id=rid)
        if n:
            self._m_inflight.set(len(self.inflight))
        return n

    def observe_depths(self, depths: "dict"):
        for r, d in depths.items():
            if d is not None:
                self.policy.observe_depth(r, d)

    #: a request is re-routed at most this many times — beyond that its
    #: OWN replica's respawn (inbox re-read) is the recovery path
    MAX_REROUTES = 2

    def replica_died(self, replica, *, now: "float | None" = None,
                     cause: str = "replica_dead",
                     exclude=()) -> int:
        """Re-route every routed-but-unacked request owned by a dead
        replica to a survivor (never to anything in ``exclude`` — e.g.
        other stale replicas). The dead replica's affinity entries are
        forgotten (its cache died with it). Returns re-route count."""
        now = self._clock() if now is None else now
        self.policy.forget(replica)
        avoid = set(exclude) | {replica}
        if not any(r not in avoid for r in self.policy.replicas):
            return 0                     # no survivor to route to
        victims = [rid for rid, st in self.inflight.items()
                   if st.get("replica") == replica
                   and st.get("request") is not None
                   and st.get("reroutes", 0) < self.MAX_REROUTES]
        for rid in sorted(victims):
            st = self.inflight[rid]
            self._route(st["request"], st["tenant"], now,
                        exclude=avoid, cause=cause)
        return len(victims)

    def tick_reroutes(self, *, now: "float | None" = None,
                      stale: "set | frozenset" = frozenset()) -> int:
        """Ack-timeout sweep: requests unacked past
        ``reroute_timeout_s`` whose replica looks dead (``stale`` — the
        caller's scrape-staleness verdict) are re-routed to a LIVE
        survivor. With every replica stale (a gang restart in flight)
        nothing moves — the respawned fleet re-reads its inboxes
        instead; ping-ponging work between dead replicas helps no one.
        Duplicates are safe: greedy decode is deterministic, so a
        false positive costs duplicate (byte-identical) work, never
        correctness."""
        now = self._clock() if now is None else now
        stale = set(stale)
        if not any(r not in stale for r in self.policy.replicas):
            return 0
        n = 0
        for replica in sorted(stale, key=str):
            if any(st.get("replica") == replica
                   and now - st.get("routed_at", now)
                   > self.reroute_timeout_s
                   for st in self.inflight.values()):
                n += self.replica_died(replica, now=now,
                                       cause="ack_timeout",
                                       exclude=stale)
        return n

    # -- reporting ---------------------------------------------------------
    def emit_tenant_summary(self, *, now: "float | None" = None):
        """One ``router.tenant`` event per tenant — the admit/reject/
        shed + quota-utilization counters obs_report renders."""
        now = self._clock() if now is None else now
        for name, s in self.tenancy.summary(now).items():
            telemetry.event("router.tenant", tenant=name,
                            pclass=s["pclass"],
                            admitted=s["admitted"],
                            rejected_quota=s["rejected"].get("quota",
                                                             0),
                            rejected_total=sum(s["rejected"]
                                               .values()),
                            sheds=s["sheds"],
                            tokens_admitted=s["tokens_admitted"],
                            quota_utilization=s["quota_utilization"])

    def stats(self) -> dict:
        return {
            "routes": self.routes,
            "reroutes": self.reroutes,
            "route_reasons": dict(self.route_reasons),
            "inflight": len(self.inflight),
            "acked": len(self.acked),
            "queued": self.queued,
            "resumed": self.resumed,
            "tenants": self.tenancy.summary(self._clock()),
        }

    def close(self):
        if self.journal:
            self.journal.close()


# -- seeded multi-tenant workloads ------------------------------------------

def seeded_tenant_workload(seed: int, *, duration_s: float = 20.0,
                           tenants=None,
                           rates: "dict[str, float] | None" = None,
                           spike: "tuple | None" = None,
                           sessions_per_tenant: int = 4,
                           session_prefix_blocks: int = 3,
                           block_size: int = 8,
                           suffix_range: tuple = (2, 5),
                           new_tokens_range: tuple = (2, 6),
                           vocab_size: int = 256) -> "list[Request]":
    """Deterministic two-class request stream (the resilience/faults.py
    string-seeding discipline): per tenant, Poisson arrivals whose
    prompts are a per-SESSION shared prefix (``session_prefix_blocks``
    full cache blocks — the affinity material: requests of one session
    hit each other's KV) plus a short unique suffix. ``spike=(start,
    end, factor)`` multiplies every INTERACTIVE tenant's rate inside
    the window — the overload that makes batch shed first observable.
    Arrival times land in ``Request.arrival_s``; ids are
    ``<tenant>-<i:04d>``. A pure function of the seed."""
    from distributed_tensorflow_tpu.serving.tenancy import \
        default_tenants
    tenants = tuple(tenants) if tenants is not None else \
        default_tenants()
    rng = random.Random(f"dtx-router-load:{seed}")
    prefix_len = session_prefix_blocks * block_size
    out: "list[Request]" = []
    for cfg in tenants:
        rate = (rates or {}).get(cfg.name,
                                 2.0 if cfg.pclass == "interactive"
                                 else 1.0)
        prefixes = [tuple(rng.randrange(vocab_size)
                          for _ in range(prefix_len))
                    for _ in range(sessions_per_tenant)]
        t, i = 0.0, 0
        while True:
            r = rate
            if spike and cfg.pclass == "interactive" \
                    and spike[0] <= t < spike[1]:
                r = rate * spike[2]
            t += rng.expovariate(r)
            if t >= duration_s:
                break
            sess = rng.randrange(sessions_per_tenant)
            toks = prefixes[sess] + tuple(
                rng.randrange(vocab_size)
                for _ in range(rng.randrange(*suffix_range)))
            out.append(Request(
                id=f"{cfg.name}-{i:04d}", tokens=toks,
                max_new_tokens=rng.randrange(*new_tokens_range),
                arrival_s=round(t, 6), tenant=cfg.name,
                pclass=cfg.pclass))
            i += 1
    out.sort(key=lambda r: (r.arrival_s, r.id))
    return out
