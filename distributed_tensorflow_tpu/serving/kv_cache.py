"""Block-allocated KV cache for incremental decode.

The serving engine keeps every running sequence's attention keys/values
on device between decode steps. A naive per-slot ``(max_seq,)`` buffer
wastes HBM proportional to the LONGEST request; instead the cache is a
pool of fixed-size *blocks* (PagedAttention, Kwon et al. SOSP'23 —
vLLM's core idea): a sequence of length ``L`` holds exactly
``ceil(L / block_size)`` blocks, mixed-length requests share one batch,
and a finished sequence's blocks return to the pool immediately.

Two layers:

- **Host side** — :class:`BlockAllocator` (the free list; physical
  block 0 is reserved as the *trash block*: padded positions of every
  sequence write there and reads from it are always masked) and
  :class:`BlockTable` (a sequence's logical-position → physical-block
  map plus the flat pool indices the device gather/scatter consume).
- **Device side** — the pool itself, ``(n_layers, num_blocks *
  block_size, n_heads, head_dim)`` per K and V (:func:`init_pool`),
  flat over the block dimension so position ``p`` of a sequence maps to
  row ``table[p // block_size] * block_size + p % block_size``. On a
  serving mesh the head axis is sharded over ``tp`` (the same axis
  training shards heads on) and the pool is replicated over ``dp`` —
  ``dp`` shards the decode batch's slots, and every slot's gather may
  touch any block (:func:`pool_shardings`).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

#: Physical block every allocator reserves: padded/inactive positions
#: scatter here and masked attention never reads it.
TRASH_BLOCK = 0


class OutOfBlocksError(RuntimeError):
    """The pool cannot satisfy an allocation (admission must wait or a
    running sequence must be preempted)."""


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Shape of the device-side KV pool."""

    n_layers: int
    n_heads: int
    head_dim: int
    num_blocks: int
    block_size: int = 16
    dtype: object = jnp.float32

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1          # minus the trash block

    @property
    def max_tokens(self) -> int:
        """Cache capacity in tokens (across all sequences)."""
        return self.usable_blocks * self.block_size

    def blocks_for(self, n_tokens: int) -> int:
        return max(1, math.ceil(n_tokens / self.block_size))

    @classmethod
    def for_model(cls, model_cfg, *, num_blocks: int,
                  block_size: int = 16, dtype=None) -> "CacheConfig":
        """Pool sized for a TransformerConfig-shaped model config."""
        return cls(n_layers=model_cfg.n_layers, n_heads=model_cfg.n_heads,
                   head_dim=model_cfg.head_dim, num_blocks=num_blocks,
                   block_size=block_size,
                   dtype=dtype if dtype is not None else model_cfg.dtype)


class BlockAllocator:
    """Free-list over the physical blocks of one pool.

    Blocks are interchangeable fixed-size units, so there is no external
    fragmentation by construction — any free block satisfies any
    request; the only waste is internal (the tail of a sequence's last
    block), bounded by ``block_size - 1`` tokens per sequence.
    Allocation is lowest-id-first so reuse is deterministic
    (test- and replay-friendly)."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, TRASH_BLOCK, -1))
        self._allocated: set[int] = set()

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._allocated)

    def alloc(self, n: int) -> list[int]:
        """``n`` blocks, lowest ids first; raises
        :class:`OutOfBlocksError` (allocating nothing) when fewer than
        ``n`` are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise OutOfBlocksError(
                f"need {n} blocks, {len(self._free)} free "
                f"(of {self.num_blocks - 1} usable)")
        out = [self._free.pop() for _ in range(n)]
        self._allocated.update(out)
        return out

    def free(self, blocks) -> None:
        """Return blocks to the pool. Double-free and freeing the trash
        block are programming errors and raise."""
        blocks = list(blocks)
        for b in blocks:
            if b == TRASH_BLOCK:
                raise ValueError("cannot free the reserved trash block")
            if b not in self._allocated:
                raise ValueError(f"double free of block {b}")
        for b in sorted(blocks, reverse=True):
            self._allocated.remove(b)
            self._free.append(b)
        # keep lowest-id-first allocation order deterministic
        self._free.sort(reverse=True)


class BlockTable:
    """One sequence's logical-position → physical-row mapping.

    ``max_blocks`` fixes the table's device-visible width (every slot's
    table has the same shape so the decode step compiles once); unused
    entries point at the trash block."""

    def __init__(self, cache_cfg: CacheConfig, max_blocks: int):
        self.cfg = cache_cfg
        self.max_blocks = max_blocks
        self.blocks: list[int] = []
        self.length = 0                     # tokens written

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self.cfg.block_size

    def ensure_room(self, n_tokens: int, allocator: BlockAllocator):
        """Grow the table so ``length + n_tokens`` fits; raises
        :class:`OutOfBlocksError` (allocating nothing) when the pool or
        the table width cannot hold it."""
        need = self.cfg.blocks_for(self.length + n_tokens)
        grow = need - len(self.blocks)
        if grow <= 0:
            return
        if need > self.max_blocks:
            raise OutOfBlocksError(
                f"sequence needs {need} blocks > max_blocks_per_seq="
                f"{self.max_blocks}")
        self.blocks.extend(allocator.alloc(grow))

    def row_of(self, position: int) -> int:
        """Flat pool row of logical ``position``."""
        bs = self.cfg.block_size
        return self.blocks[position // bs] * bs + position % bs

    def rows(self, positions) -> np.ndarray:
        """Flat pool rows for an array of logical positions; positions
        at/past the written blocks map into the trash block."""
        bs = self.cfg.block_size
        table = np.full(self.max_blocks, TRASH_BLOCK, np.int32)
        table[:len(self.blocks)] = self.blocks
        positions = np.asarray(positions, np.int64)
        return (table[np.minimum(positions // bs, self.max_blocks - 1)]
                * bs + positions % bs).astype(np.int32)

    def window_rows(self) -> np.ndarray:
        """Rows of the full ``max_blocks * block_size`` attention window
        (the decode step's gather index): logical positions 0.. in
        order, trash rows past the allocated blocks."""
        return self.rows(np.arange(self.max_blocks * self.cfg.block_size))

    def release(self, allocator: BlockAllocator):
        if self.blocks:
            allocator.free(self.blocks)
        self.blocks = []
        self.length = 0


def init_pool(cache_cfg: CacheConfig, mesh=None):
    """Zero-initialized ``{"k", "v"}`` pools, placed with
    :func:`pool_shardings` when a mesh is given."""
    shape = (cache_cfg.n_layers,
             cache_cfg.num_blocks * cache_cfg.block_size,
             cache_cfg.n_heads, cache_cfg.head_dim)
    pool = {"k": jnp.zeros(shape, cache_cfg.dtype),
            "v": jnp.zeros(shape, cache_cfg.dtype)}
    if mesh is not None:
        sh = pool_shardings(mesh)
        pool = {n: jax.device_put(a, sh) for n, a in pool.items()}
    return pool


def pool_shardings(mesh) -> NamedSharding:
    """Cache layout on a serving mesh: heads over ``tp`` (matching the
    training-side head sharding), rows replicated — ``dp`` shards the
    decode batch's SLOTS, and any slot's block gather may touch any
    physical row, so the row axis stays unsharded."""
    head_axis = "tp" if "tp" in mesh.shape else None
    return NamedSharding(mesh, P(None, None, head_axis, None))
