"""Block-allocated KV cache for incremental decode.

The serving engine keeps every running sequence's attention keys/values
on device between decode steps. A naive per-slot ``(max_seq,)`` buffer
wastes HBM proportional to the LONGEST request; instead the cache is a
pool of fixed-size *blocks* (PagedAttention, Kwon et al. SOSP'23 —
vLLM's core idea): a sequence of length ``L`` holds exactly
``ceil(L / block_size)`` blocks, mixed-length requests share one batch,
and a finished sequence's blocks return to the pool immediately.

Two layers:

- **Host side** — :class:`BlockAllocator` (the free list; physical
  block 0 is reserved as the *trash block*: padded positions of every
  sequence write there and reads from it are always masked),
  :class:`BlockTable` (a sequence's logical-position → physical-block
  map plus the flat pool indices the device gather/scatter consume) and
  :class:`PrefixCache` (cross-request block SHARING: committed prompt
  prefixes indexed by content so later requests with the same prefix
  adopt the blocks instead of recomputing prefill — see below).
- **Device side** — the pool itself, ``(n_layers, num_blocks *
  block_size, n_heads, head_dim)`` per K and V (:func:`init_pool`),
  flat over the block dimension so position ``p`` of a sequence maps to
  row ``table[p // block_size] * block_size + p % block_size``. On a
  serving mesh the head axis is sharded over ``tp`` (the same axis
  training shards heads on) and the pool is replicated over ``dp`` —
  ``dp`` shards the decode batch's slots, and every slot's gather may
  touch any block (:func:`pool_shardings`).

**Sharing & refcounts.** Blocks are reference-counted:
:meth:`BlockAllocator.alloc` hands out blocks at refcount 1,
:meth:`BlockAllocator.incref` adds an owner, and
:meth:`BlockAllocator.free` DECREFS — a block only returns to the free
list when its last owner lets go, so freeing a shared block is safe by
construction (and freeing an unowned block still raises). The
:class:`PrefixCache` holds one reference per cached block; sequences
that hash-match a prefix hold their own. A shared block is never
written in place: :meth:`BlockTable.ensure_writable` copies it first
(copy-on-write), so a request diverging after a shared prefix cannot
corrupt its siblings' cache.

**Quantized pools.** ``CacheConfig(kv_dtype=)`` selects the pool's
storage dtype: ``"f32"`` (reference), ``"bf16"`` (plain cast, 2x the
slots) or ``"int8"`` (quantize-on-write with one f32 scale per
quantisation block — a block here is one head's ``head_dim`` vector of
one pool row — dequantize-on-gather; 2-3.8x the slots depending on
``head_dim``, see :meth:`CacheConfig.bytes_per_token`).
"""

from __future__ import annotations

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

#: Physical block every allocator reserves: padded/inactive positions
#: scatter here and masked attention never reads it.
TRASH_BLOCK = 0

#: CacheConfig(kv_dtype=) spellings -> storage dtype.
KV_DTYPES = {
    "f32": jnp.float32, "float32": jnp.float32,
    "bf16": jnp.bfloat16, "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
}


class OutOfBlocksError(RuntimeError):
    """The pool cannot satisfy an allocation (admission must wait or a
    running sequence must be preempted)."""


@dataclasses.dataclass(frozen=True)
class CacheConfig:
    """Shape of the device-side KV pool.

    ``kv_dtype`` (``"f32"``/``"bf16"``/``"int8"``) overrides ``dtype``
    by name; ``"int8"`` switches the pool to quantized storage with
    per-(row, head) f32 scales (:func:`init_pool` adds ``k_scale`` /
    ``v_scale`` arrays)."""

    n_layers: int
    n_heads: int
    head_dim: int
    num_blocks: int
    block_size: int = 16
    dtype: object = jnp.float32
    kv_dtype: str | None = None

    def __post_init__(self):
        if self.num_blocks < 2:
            raise ValueError("num_blocks must be >= 2 (block 0 is the "
                             "reserved trash block)")
        if self.block_size < 1:
            raise ValueError("block_size must be >= 1")
        if self.kv_dtype is not None:
            if self.kv_dtype not in KV_DTYPES:
                raise ValueError(
                    f"kv_dtype={self.kv_dtype!r}; expected one of "
                    f"{sorted(KV_DTYPES)}")
            object.__setattr__(self, "dtype", KV_DTYPES[self.kv_dtype])

    @property
    def quantized(self) -> bool:
        return jnp.dtype(self.dtype) == jnp.dtype(jnp.int8)

    @property
    def usable_blocks(self) -> int:
        return self.num_blocks - 1          # minus the trash block

    @property
    def max_tokens(self) -> int:
        """Cache capacity in tokens (across all sequences)."""
        return self.usable_blocks * self.block_size

    @property
    def bytes_per_token(self) -> int:
        """Pool bytes one cached token costs (K + V, scales included
        for quantized dtypes) — the slots-per-chip arithmetic behind
        the README's KV-dtype table."""
        per = 2 * self.n_heads * self.head_dim \
            * jnp.dtype(self.dtype).itemsize
        if self.quantized:
            per += 2 * self.n_heads * 4          # f32 scale per head
        return per

    def blocks_for_budget(self, pool_bytes: int) -> int:
        """Usable blocks (+1 trash) a device-memory budget affords at
        this dtype — how ``kv_dtype="int8"`` turns into 2x+ servable
        slots at an equal byte budget."""
        per_block = self.block_size * self.bytes_per_token
        return max(0, pool_bytes // per_block)

    def blocks_for(self, n_tokens: int) -> int:
        return max(1, math.ceil(n_tokens / self.block_size))

    @classmethod
    def for_model(cls, model_cfg, *, num_blocks: int,
                  block_size: int = 16, dtype=None,
                  kv_dtype: str | None = None) -> "CacheConfig":
        """Pool sized for a TransformerConfig-shaped model config."""
        return cls(n_layers=model_cfg.n_layers, n_heads=model_cfg.n_heads,
                   head_dim=model_cfg.head_dim, num_blocks=num_blocks,
                   block_size=block_size,
                   dtype=dtype if dtype is not None else model_cfg.dtype,
                   kv_dtype=kv_dtype)


class BlockAllocator:
    """Refcounted free-list over the physical blocks of one pool.

    Blocks are interchangeable fixed-size units, so there is no external
    fragmentation by construction — any free block satisfies any
    request; the only waste is internal (the tail of a sequence's last
    block), bounded by ``block_size - 1`` tokens per sequence.
    Allocation is lowest-id-first so reuse is deterministic
    (test- and replay-friendly).

    Every owner of a block — the sequence that allocated it, each later
    sequence sharing it, the prefix cache — holds one reference:
    :meth:`free` decrefs and only the LAST owner's free returns the
    block to the pool. Freeing a block nobody owns is still a
    programming error and raises."""

    def __init__(self, num_blocks: int):
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is reserved)")
        self.num_blocks = num_blocks
        self._free = list(range(num_blocks - 1, TRASH_BLOCK, -1))
        self._refs: dict[int, int] = {}

    @property
    def num_free(self) -> int:
        return len(self._free)

    @property
    def num_allocated(self) -> int:
        return len(self._refs)

    @property
    def total_refs(self) -> int:
        """Sum of live references across all allocated blocks — the
        conservation quantity the disaggregated chaos gate audits:
        every ref must be owned by a running sequence's table or a
        prefix-cache entry, so ``total_refs - cache_entries -
        Σ len(table.blocks) == 0`` or blocks leaked."""
        return sum(self._refs.values())

    def refcount(self, block: int) -> int:
        """Live references on ``block`` (0 = free). Refcount > 1 means
        SHARED: writers must copy first (BlockTable.ensure_writable)."""
        return self._refs.get(block, 0)

    def alloc(self, n: int) -> list[int]:
        """``n`` blocks at refcount 1, lowest ids first; raises
        :class:`OutOfBlocksError` (allocating nothing) when fewer than
        ``n`` are free."""
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise OutOfBlocksError(
                f"need {n} blocks, {len(self._free)} free "
                f"(of {self.num_blocks - 1} usable)")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._refs[b] = 1
        return out

    def incref(self, block: int) -> None:
        """Add an owner to an allocated block (prefix-cache sharing)."""
        if block not in self._refs:
            raise ValueError(f"incref of unallocated block {block}")
        self._refs[block] += 1

    def free(self, blocks) -> None:
        """Drop one reference per block; a block whose LAST reference
        is dropped returns to the pool. Freeing an unowned block (true
        double-free) and freeing the trash block raise."""
        blocks = list(blocks)
        for b in blocks:
            if b == TRASH_BLOCK:
                raise ValueError("cannot free the reserved trash block")
            if b not in self._refs:
                raise ValueError(f"double free of block {b}")
        released = []
        for b in blocks:
            self._refs[b] -= 1
            if self._refs[b] == 0:
                del self._refs[b]
                released.append(b)
        if released:
            self._free.extend(released)
            # keep lowest-id-first allocation order deterministic
            self._free.sort(reverse=True)


class BlockTable:
    """One sequence's logical-position → physical-row mapping.

    ``max_blocks`` fixes the table's device-visible width (every slot's
    table has the same shape so the decode step compiles once); unused
    entries point at the trash block."""

    def __init__(self, cache_cfg: CacheConfig, max_blocks: int):
        self.cfg = cache_cfg
        self.max_blocks = max_blocks
        self.blocks: list[int] = []
        self.length = 0                     # tokens written

    @property
    def capacity(self) -> int:
        return len(self.blocks) * self.cfg.block_size

    def ensure_room(self, n_tokens: int, allocator: BlockAllocator):
        """Grow the table so ``length + n_tokens`` fits; raises
        :class:`OutOfBlocksError` (allocating nothing) when the pool or
        the table width cannot hold it."""
        need = self.cfg.blocks_for(self.length + n_tokens)
        grow = need - len(self.blocks)
        if grow <= 0:
            return
        if need > self.max_blocks:
            raise OutOfBlocksError(
                f"sequence needs {need} blocks > max_blocks_per_seq="
                f"{self.max_blocks}")
        self.blocks.extend(allocator.alloc(grow))

    def ensure_writable(self, start: int, end: int,
                        allocator: BlockAllocator) -> list[tuple]:
        """Copy-on-write: every block covering logical positions
        ``[start, end)`` that is SHARED (refcount > 1 — a prefix-cache
        entry or a sibling sequence also owns it) is swapped for a
        private fresh block. Returns ``(src_row0, dst_row0, n_rows)``
        device copy instructions the engine must apply to the pool
        BEFORE writing — the copy preserves the shared prefix content
        that precedes the divergent write inside the block."""
        if end <= start or not self.blocks:
            return []
        bs = self.cfg.block_size
        lo = start // bs
        hi = min(len(self.blocks) - 1, (end - 1) // bs)
        copies = []
        for bi in range(lo, hi + 1):
            b = self.blocks[bi]
            if allocator.refcount(b) > 1:
                new = allocator.alloc(1)[0]
                copies.append((b * bs, new * bs, bs))
                self.blocks[bi] = new
                allocator.free([b])          # drop OUR ref; others keep it
        return copies

    def row_of(self, position: int) -> int:
        """Flat pool row of logical ``position``."""
        bs = self.cfg.block_size
        return self.blocks[position // bs] * bs + position % bs

    def rows(self, positions) -> np.ndarray:
        """Flat pool rows for an array of logical positions; positions
        at/past the written blocks map into the trash block."""
        bs = self.cfg.block_size
        table = np.full(self.max_blocks, TRASH_BLOCK, np.int32)
        table[:len(self.blocks)] = self.blocks
        positions = np.asarray(positions, np.int64)
        return (table[np.minimum(positions // bs, self.max_blocks - 1)]
                * bs + positions % bs).astype(np.int32)

    def window_rows(self) -> np.ndarray:
        """Rows of the full ``max_blocks * block_size`` attention window
        (the decode step's gather index): logical positions 0.. in
        order, trash rows past the allocated blocks."""
        return self.rows(np.arange(self.max_blocks * self.cfg.block_size))

    def release(self, allocator: BlockAllocator):
        if self.blocks:
            allocator.free(self.blocks)
        self.blocks = []
        self.length = 0


class _CacheEntry:
    __slots__ = ("key", "parent", "block", "tokens", "last_used")

    def __init__(self, key, parent, block, tokens, last_used):
        self.key = key
        self.parent = parent
        self.block = block
        self.tokens = tokens
        self.last_used = last_used


class _SpillEntry:
    __slots__ = ("key", "parent", "tokens", "arrays", "epoch")

    def __init__(self, key, parent, tokens, arrays, epoch):
        self.key = key
        self.parent = parent
        self.tokens = tokens
        self.arrays = arrays          # host copies of the block's rows
        self.epoch = epoch            # pool epoch of the spilling engine

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays.values())


class HostTier:
    """Host-memory spill tier for cold :class:`PrefixCache` blocks.

    When the prefix cache must evict a block (pool pressure), the
    block's K/V rows — quantisation scales included — are copied to
    host RAM instead of being dropped; a later prompt that walks the
    same chain re-adopts the block into a fresh pool slot bit-exactly
    (tests/test_migrate.py pins the round-trip per ``kv_dtype``). The
    tier holds NO allocator references — its entries are plain host
    bytes keyed by the same chain key the cache indexes by.

    **Epoch fencing.** Every entry records the spilling engine's
    ``pool_epoch``. A restarted engine has a NEW epoch, so a stale
    spill (possibly from different weights or a different pool layout)
    is rejected at re-adoption rather than served — the cache then just
    prefill-recomputes, which is always correct.

    Capacity is bounded (``capacity_blocks``); insertion past it drops
    the least-recently-touched spilled block."""

    def __init__(self, capacity_blocks: int = 256):
        if capacity_blocks < 1:
            raise ValueError("capacity_blocks must be >= 1")
        self.capacity_blocks = capacity_blocks
        import collections
        self._entries: "collections.OrderedDict[tuple, _SpillEntry]" = \
            collections.OrderedDict()
        self.spilled = 0
        self.readopted = 0
        self.rejected = 0
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._entries)

    @property
    def nbytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def put(self, key, parent, tokens, arrays, epoch):
        if key in self._entries:
            self._entries.pop(key)
        while len(self._entries) >= self.capacity_blocks:
            self._entries.popitem(last=False)
            self.dropped += 1
        self._entries[key] = _SpillEntry(key, parent, tokens, arrays,
                                         epoch)
        self.spilled += 1

    def get(self, key) -> "_SpillEntry | None":
        e = self._entries.get(key)
        if e is not None:
            self._entries.move_to_end(key)
        return e

    def drop(self, key):
        self._entries.pop(key, None)

    def stats(self) -> dict:
        return {"entries": len(self._entries), "nbytes": self.nbytes,
                "spilled": self.spilled, "readopted": self.readopted,
                "rejected": self.rejected, "dropped": self.dropped}


class PrefixCache:
    """Content index over committed prompt-prefix blocks (cross-request
    KV reuse — the vLLM "automatic prefix caching" idea on this pool).

    **Granularity.** The index key of a block is the CHAIN
    ``(parent_key, block_tokens)``: a hit certifies the entire prefix
    up to and including that block, not just the block's own
    ``block_size`` tokens, so matching is a plain walk down the chain.
    The last hop may be a *partial* match — a cached block whose tokens
    merely START with the remaining prompt — which is what makes
    copy-on-write real: the matching sequence will later write its own
    tokens into that block's tail, and ``BlockTable.ensure_writable``
    copies the block first.

    **References.** The cache holds ONE allocator reference per entry;
    :meth:`match` bumps each returned block once more (the caller —
    the admitting sequence — owns those refs and drops them via the
    normal ``BlockTable.release``). Eviction (:meth:`evict`) is LRU
    over entries with NO references beyond the cache's own
    (refcount == 1) and only over chain LEAVES, so an entry a running
    sequence shares — or one a cached longer chain still hangs off —
    is never reclaimed out from under its users.

    At most ``len(prompt) - 1`` tokens ever match: prefill must compute
    at least the final prompt position to produce the first generated
    token's logits."""

    def __init__(self, allocator: BlockAllocator, block_size: int):
        self._alloc = allocator
        self.block_size = block_size
        self._entries: dict[tuple, _CacheEntry] = {}
        self._children: dict[object, set] = {}
        self._clock = 0
        self.hit_tokens = 0
        self.lookup_tokens = 0
        self.hit_requests = 0
        self.lookups = 0
        self.evictions = 0
        self._spill: HostTier | None = None
        self._spill_extract = None
        self._spill_insert = None
        self._spill_epoch = None
        self.spill_hits = 0
        self.spill_rejects = 0
        self.fences = 0
        self.fence_dropped = 0

    def __len__(self) -> int:
        return len(self._entries)

    def attach_spill(self, tier: HostTier, *, extract, insert, epoch):
        """Wire a :class:`HostTier` behind this cache. ``extract(block)
        -> {name: np.ndarray}`` copies one block's pool rows (plus
        scales) to host; ``insert(block, arrays)`` writes them back
        into a freshly allocated block; ``epoch`` is the engine's
        ``pool_epoch`` fence (stale entries from a previous engine
        incarnation are rejected on re-adoption). The engine provides
        all three — the cache stays device-agnostic."""
        self._spill = tier
        self._spill_extract = extract
        self._spill_insert = insert
        self._spill_epoch = epoch

    def match(self, tokens) -> tuple[int, list[int]]:
        """``(n_cached_tokens, blocks)`` — the longest cached chain
        over ``tokens[:-1]``. Full blocks match by chain key; one final
        partial hop may match a cached block whose tokens extend the
        prompt's sub-block tail. Every returned block's refcount is
        bumped; the caller owns (and must eventually free) those refs.
        """
        tokens = tuple(int(t) for t in tokens)
        limit = len(tokens) - 1
        self._clock += 1
        self.lookups += 1
        self.lookup_tokens += max(0, limit)
        bs = self.block_size
        key = None
        blocks: list[int] = []
        n = 0
        while n + bs <= limit:
            k = (key, tokens[n:n + bs])
            e = self._entries.get(k)
            if e is None:
                # Chain miss on device — maybe the block was spilled to
                # the host tier. Re-adoption is full-block only: the
                # partial-hop heuristic below stays device-resident.
                e = self._readopt(k, key)
            if e is None:
                break
            e.last_used = self._clock
            self._alloc.incref(e.block)
            blocks.append(e.block)
            key = k
            n += bs
        if 0 < limit - n < bs:
            rest = tokens[n:limit]
            best = None
            for ck in self._children.get(key, ()):
                e = self._entries[ck]
                if e.tokens[:len(rest)] == rest and (
                        best is None or e.last_used > best.last_used):
                    best = e
            if best is not None:
                best.last_used = self._clock
                self._alloc.incref(best.block)
                blocks.append(best.block)
                n += len(rest)
        if n:
            self.hit_tokens += n
            self.hit_requests += 1
        return n, blocks

    def register(self, tokens, blocks) -> int:
        """Index every FULL block of a just-prefilled prompt
        (``blocks`` = the sequence's BlockTable blocks, which hold
        exactly these tokens' K/V — shared hits included, and
        post-copy-on-write for a partially-matched tail). Newly
        inserted entries gain one cache-owned reference. Returns the
        number of new entries."""
        tokens = tuple(int(t) for t in tokens)
        self._clock += 1
        bs = self.block_size
        key = None
        added = 0
        for i in range(len(tokens) // bs):
            btoks = tokens[i * bs:(i + 1) * bs]
            k = (key, btoks)
            e = self._entries.get(k)
            if e is None:
                self._alloc.incref(blocks[i])
                e = _CacheEntry(k, key, blocks[i], btoks, self._clock)
                self._entries[k] = e
                self._children.setdefault(key, set()).add(k)
                added += 1
            else:
                e.last_used = self._clock
            key = k
        return added

    def evict(self, n_blocks: int) -> int:
        """Free up to ``n_blocks`` pool blocks by dropping
        least-recently-used UNREFERENCED leaf entries (allocator
        refcount 1 — only the cache's own reference — and no cached
        children). Entries referenced by running sequences are never
        evicted. Returns how many blocks actually went back to the
        pool."""
        freed = 0
        while freed < n_blocks:
            victim = None
            for e in self._entries.values():
                if self._children.get(e.key):
                    continue                 # interior of a cached chain
                if self._alloc.refcount(e.block) != 1:
                    continue                 # a sequence still shares it
                if victim is None or e.last_used < victim.last_used:
                    victim = e
            if victim is None:
                break
            if self._spill is not None:
                # Victim selection above already guarantees refcount 1
                # (the cache's own ref): a block any sequence shares is
                # never spilled, only truly cold cache-private blocks.
                self._spill.put(victim.key, victim.parent, victim.tokens,
                                self._spill_extract(victim.block),
                                self._spill_epoch)
            del self._entries[victim.key]
            kids = self._children.get(victim.parent)
            if kids is not None:
                kids.discard(victim.key)
                if not kids:
                    del self._children[victim.parent]
            self._alloc.free([victim.block])
            self.evictions += 1
            freed += 1
        return freed

    def fence(self, epoch) -> int:
        """Invalidate the whole cache in one step and rotate the spill
        epoch — the weights-version fence a hot-swap relies on: a block
        committed under weights N must never match a request served
        under weights N+1 (same tokens, different K/V). Device entries
        are dropped eagerly (the cache's own allocator reference per
        entry returns to the pool; blocks a running sequence still
        shares survive through the sequence's refs). Host-tier spilled
        entries are NOT scanned: the epoch rotation makes
        :meth:`_readopt` drop-and-count each one lazily on its next
        lookup, exactly like a stale entry from a dead engine
        incarnation. Returns the number of device entries dropped."""
        dropped = len(self._entries)
        for e in self._entries.values():
            self._alloc.free([e.block])
        self._entries.clear()
        self._children.clear()
        self._spill_epoch = epoch
        self.fences += 1
        self.fence_dropped += dropped
        return dropped

    def _readopt(self, key, chain_key) -> "_CacheEntry | None":
        """Try to pull a spilled block back into the pool on a chain
        miss. Needs one free block; a stale entry (pool-epoch mismatch
        — the engine restarted since the spill) is dropped and counted
        in ``spill_rejects`` instead of being served."""
        if self._spill is None:
            return None
        se = self._spill.get(key)
        if se is None:
            return None
        if se.epoch != self._spill_epoch:
            self._spill.drop(key)
            self._spill.rejected += 1
            self.spill_rejects += 1
            return None
        if self._alloc.num_free < 1:
            return None
        block = self._alloc.alloc(1)[0]       # cache-owned reference
        self._spill_insert(block, se.arrays)
        self._spill.drop(key)
        self._spill.readopted += 1
        self.spill_hits += 1
        e = _CacheEntry(key, chain_key, block, se.tokens, self._clock)
        self._entries[key] = e
        self._children.setdefault(chain_key, set()).add(key)
        return e

    def stats(self) -> dict:
        return {
            "entries": len(self._entries),
            "lookups": self.lookups,
            "hit_requests": self.hit_requests,
            "hit_tokens": self.hit_tokens,
            "lookup_tokens": self.lookup_tokens,
            "hit_rate": (self.hit_tokens / self.lookup_tokens
                         if self.lookup_tokens else 0.0),
            "evictions": self.evictions,
            "spill_hits": self.spill_hits,
            "spill_rejects": self.spill_rejects,
            "fences": self.fences,
            "fence_dropped": self.fence_dropped,
        }


def init_pool(cache_cfg: CacheConfig, mesh=None):
    """Zero-initialized ``{"k", "v"}`` pools (plus ``k_scale`` /
    ``v_scale`` per-(row, head) f32 scales when the config is int8-
    quantized), placed with :func:`pool_shardings` when a mesh is
    given."""
    rows = cache_cfg.num_blocks * cache_cfg.block_size
    shape = (cache_cfg.n_layers, rows, cache_cfg.n_heads,
             cache_cfg.head_dim)
    pool = {"k": jnp.zeros(shape, cache_cfg.dtype),
            "v": jnp.zeros(shape, cache_cfg.dtype)}
    if cache_cfg.quantized:
        sshape = (cache_cfg.n_layers, rows, cache_cfg.n_heads)
        pool["k_scale"] = jnp.zeros(sshape, jnp.float32)
        pool["v_scale"] = jnp.zeros(sshape, jnp.float32)
    if mesh is not None:
        sh = pool_shardings(mesh, cache_cfg)
        pool = {n: jax.device_put(a, sh[n]) for n, a in pool.items()}
    return pool


def pool_shardings(mesh, cache_cfg: CacheConfig | None = None) -> dict:
    """Cache layout on a serving mesh, one NamedSharding per pool
    array: heads over ``tp`` (matching the training-side head
    sharding), rows replicated — ``dp`` shards the decode batch's
    SLOTS, and any slot's block gather may touch any physical row, so
    the row axis stays unsharded. Quantisation scales follow their
    pool's head axis."""
    head_axis = "tp" if "tp" in mesh.shape else None
    kv = NamedSharding(mesh, P(None, None, head_axis, None))
    out = {"k": kv, "v": kv}
    if cache_cfg is not None and cache_cfg.quantized:
        sc = NamedSharding(mesh, P(None, None, head_axis))
        out["k_scale"] = sc
        out["v_scale"] = sc
    return out
