"""Continuous (in-flight) batching: admission queue + iteration-level
scheduling.

The Orca model (Yu et al., OSDI'22): scheduling decisions happen at
STEP boundaries, not request boundaries. Each engine step the scheduler

1. retires finished sequences — their cache blocks return to the pool
   immediately (the blocks, not the slot count, are the real capacity);
2. admits queued requests into free slots while the *token budget*
   holds: a decode step costs 1 token per running sequence, a prefill
   costs the whole prompt, and the budget caps their sum so one giant
   prompt cannot stall every running sequence's next token;
3. hands the engine the prefill list + the decode batch.

Cache pressure is handled by preemption, newest-first: when a running
sequence cannot grow into a new block (pool exhausted), the
most-recently admitted sequence is pushed back to the FRONT of the
admission queue with its blocks freed (its generated tokens are kept
and replayed as part of the prompt on re-admission), so the oldest
requests always finish first and the engine never deadlocks.

:class:`AdmissionQueue` is bounded; on overflow it either rejects the
new request (``policy="reject"``) or evicts the oldest WAITING request
to make room (``policy="evict_oldest"`` — the evicted request is
returned to the caller so the replica can surface the shed load).
Either way the shed load is *observable*, not just an exception: a
``serving/rejected_total`` / ``serving/evicted_total`` counter ticks
and a ``serve.reject`` event records the request id and queue state,
so the autoscaler (resilience/autoscaler.py) and ``health_report.py``
can tell overload (queue full, latency burning) from failure (workers
dying) when deciding whether to add capacity.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Iterable

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.serving.kv_cache import (
    BlockAllocator, BlockTable, CacheConfig, OutOfBlocksError)


class QueueOverflowError(RuntimeError):
    """The admission queue is full and the policy is ``reject``."""


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``max_new_tokens=0`` is a scoring request
    (prefill only — the BERT-family path): it completes with the
    prompt's last-position logits argmax as its single 'token'.
    ``generated_prefix`` is internal: tokens a PREEMPTED sequence had
    already generated, replayed as prompt suffix on re-admission and
    re-attached to the completion record."""

    id: str
    tokens: tuple
    max_new_tokens: int = 16
    eos_id: int | None = None
    arrival_s: float = 0.0
    generated_prefix: tuple = ()

    def __post_init__(self):
        object.__setattr__(self, "tokens", tuple(int(t)
                                                 for t in self.tokens))
        if not self.tokens:
            raise ValueError(f"request {self.id}: empty prompt")


class Sequence:
    """Runtime state of one admitted request."""

    def __init__(self, request: Request, slot: int,
                 table: BlockTable):
        self.request = request
        self.slot = slot
        self.table = table
        self.generated: list[int] = []
        self.prefilled = False
        self.admitted_s = time.monotonic()
        self.first_token_s: float | None = None
        self.preemptions = 0

    @property
    def prompt_len(self) -> int:
        return len(self.request.tokens)

    @property
    def length(self) -> int:
        """Tokens currently in the cache (prompt + generated so far)."""
        return self.table.length

    @property
    def last_token(self) -> int:
        return (self.generated[-1] if self.generated
                else self.request.tokens[-1])

    @property
    def done(self) -> bool:
        if not self.prefilled:
            return False
        if len(self.generated) >= self.request.max_new_tokens:
            return True
        return (self.request.eos_id is not None and self.generated
                and self.generated[-1] == self.request.eos_id)


class AdmissionQueue:
    """Bounded FIFO of waiting requests."""

    def __init__(self, capacity: int = 256, policy: str = "reject"):
        if policy not in ("reject", "evict_oldest"):
            raise ValueError(f"policy={policy!r}; expected 'reject' or "
                             f"'evict_oldest'")
        self.capacity = capacity
        self.policy = policy
        self._q: collections.deque[Request] = collections.deque()
        self.rejected = 0
        self.evicted = 0
        reg = telemetry.get_registry()
        self._m_rejected = reg.counter(
            "serving/rejected_total",
            "admission-queue overflow rejections (overload shed — "
            "distinct from worker failure)")
        self._m_evicted = reg.counter(
            "serving/evicted_total",
            "oldest-waiting requests evicted on overflow")

    def __len__(self) -> int:
        return len(self._q)

    def submit(self, request: Request) -> "Request | None":
        """Enqueue; on overflow either raise (``reject``) or drop and
        return the oldest waiting request (``evict_oldest``)."""
        evicted = None
        if len(self._q) >= self.capacity:
            if self.policy == "reject":
                self.rejected += 1
                self._m_rejected.increment()
                telemetry.event("serve.reject", id=request.id,
                                queued=len(self._q),
                                capacity=self.capacity,
                                policy=self.policy)
                raise QueueOverflowError(
                    f"admission queue full ({self.capacity})")
            evicted = self._q.popleft()
            self.evicted += 1
            self._m_evicted.increment()
            telemetry.event("serve.reject", id=evicted.id,
                            queued=len(self._q),
                            capacity=self.capacity,
                            policy=self.policy, evicted_for=request.id)
        self._q.append(request)
        return evicted

    def push_front(self, request: Request):
        """Re-queue a preempted sequence's request at the FRONT (it is
        the oldest work in the system; capacity is not enforced here —
        preemption must never lose a request)."""
        self._q.appendleft(request)

    def pop(self) -> "Request | None":
        return self._q.popleft() if self._q else None

    def peek(self) -> "Request | None":
        return self._q[0] if self._q else None


class ContinuousBatchingScheduler:
    """Slot + block + budget bookkeeping for one engine."""

    def __init__(self, cache_cfg: CacheConfig, *, max_slots: int,
                 max_blocks_per_seq: int, token_budget: int,
                 queue: AdmissionQueue | None = None):
        self.cache_cfg = cache_cfg
        self.allocator = BlockAllocator(cache_cfg.num_blocks)
        self.queue = queue if queue is not None else AdmissionQueue()
        self.max_slots = max_slots
        self.max_blocks_per_seq = max_blocks_per_seq
        self.token_budget = token_budget
        self.running: dict[int, Sequence] = {}      # slot -> sequence
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self.preemptions = 0

    # -- admission --------------------------------------------------------
    def admit(self) -> list[Sequence]:
        """Admit queued requests for this step under the token budget:
        budget = token_budget - (1 decode token per running seq); each
        admission consumes its prompt length. Stops at the first request
        that does not fit (FIFO order is preserved — no starvation of
        big prompts behind small ones)."""
        budget = self.token_budget - len(self.running)
        admitted: list[Sequence] = []
        while self._free_slots and self.queue.peek() is not None:
            req = self.queue.peek()
            need = len(req.tokens)
            if need > budget and (admitted or self.running):
                break                       # never starves: alone it runs
            blocks_needed = self.cache_cfg.blocks_for(need + 1)
            if blocks_needed > self.max_blocks_per_seq:
                # can never fit: fail the request rather than wedge FIFO
                self.queue.pop()
                raise OutOfBlocksError(
                    f"request {req.id}: prompt of {need} tokens needs "
                    f"{blocks_needed} blocks > max_blocks_per_seq="
                    f"{self.max_blocks_per_seq}")
            if blocks_needed > self.allocator.num_free:
                break                       # wait for blocks to free up
            self.queue.pop()
            slot = self._free_slots.pop()
            table = BlockTable(self.cache_cfg, self.max_blocks_per_seq)
            table.ensure_room(need + 1, self.allocator)
            seq = Sequence(req, slot, table)
            self.running[slot] = seq
            admitted.append(seq)
            budget -= need
        return admitted

    # -- per-step transitions ---------------------------------------------
    def commit_prefill(self, seq: Sequence):
        seq.table.length = seq.prompt_len
        seq.prefilled = True

    def grow_for_decode(self) -> list[Sequence]:
        """Make room for ONE more token in every running prefilled
        sequence; a sequence that cannot grow triggers newest-first
        preemption until the growth fits. Returns the decode batch."""
        batch = [s for s in self.running.values() if s.prefilled
                 and not s.done]
        batch.sort(key=lambda s: s.slot)
        for seq in list(batch):
            while True:
                try:
                    seq.table.ensure_room(1, self.allocator)
                    break
                except OutOfBlocksError:
                    victim = self._preempt_newest(exclude=seq)
                    if victim is None:
                        raise       # nothing left to preempt: misconfig
                    if victim in batch:
                        batch.remove(victim)
        return batch

    def _preempt_newest(self, exclude: Sequence) -> "Sequence | None":
        cands = [s for s in self.running.values() if s is not exclude]
        if not cands:
            return None
        victim = max(cands, key=lambda s: s.admitted_s)
        del self.running[victim.slot]
        self._free_slots.append(victim.slot)
        self._free_slots.sort(reverse=True)
        victim.table.release(self.allocator)
        # generated tokens become prompt suffix: greedy decode replays
        # them identically on re-admission (deterministic outputs), and
        # generated_prefix re-attaches them to the completion record
        req = victim.request
        new_req = dataclasses.replace(
            req, tokens=req.tokens + tuple(victim.generated),
            max_new_tokens=req.max_new_tokens - len(victim.generated),
            generated_prefix=(req.generated_prefix
                              + tuple(victim.generated)))
        self.queue.push_front(new_req)
        victim.preemptions += 1
        self.preemptions += 1
        return victim

    def append_token(self, seq: Sequence, token: int):
        seq.table.length += 1
        seq.generated.append(int(token))
        if seq.first_token_s is None:
            seq.first_token_s = time.monotonic()

    def finish(self, seq: Sequence):
        """Retire a finished sequence: blocks back to the pool, slot
        freed — both available to the NEXT admission immediately."""
        del self.running[seq.slot]
        self._free_slots.append(seq.slot)
        self._free_slots.sort(reverse=True)
        seq.table.release(self.allocator)

    def finished(self) -> Iterable[Sequence]:
        return [s for s in self.running.values() if s.done]

    @property
    def idle(self) -> bool:
        return not self.running and len(self.queue) == 0
