"""Continuous (in-flight) batching: admission queue + iteration-level
scheduling.

The Orca model (Yu et al., OSDI'22): scheduling decisions happen at
STEP boundaries, not request boundaries. Each engine step the scheduler

1. retires finished sequences — their cache blocks return to the pool
   immediately (the blocks, not the slot count, are the real capacity);
2. admits queued requests into free slots while the *token budget*
   holds: a decode step costs 1 token per running sequence, a prefill
   costs the whole prompt, and the budget caps their sum so one giant
   prompt cannot stall every running sequence's next token;
3. hands the engine the prefill list + the decode batch.

Cache pressure is handled in two stages: first the prefix cache (when
one is attached) evicts unreferenced cached blocks LRU-first, then
preemption kicks in, newest-first: when a running sequence cannot grow
into a new block (pool exhausted), the most-recently admitted sequence
is pushed back to the FRONT of the admission queue with its blocks
freed (its generated tokens are kept and replayed as part of the
prompt on re-admission), so the oldest requests always finish first
and the engine never deadlocks.

**Prefix caching** (``prefix_caching=True``): at admission each
request's prompt is hash-matched against the
:class:`~distributed_tensorflow_tpu.serving.kv_cache.PrefixCache`;
matched blocks are adopted (refcounted — the engine then prefills only
the unmatched suffix), and at prefill commit the prompt's full blocks
are registered for later requests. A preempted sequence's cached
prompt blocks survive its release (the cache keeps its reference), so
replay after preemption usually re-admits straight onto warm blocks.
Correctness never depends on cache state: a cold cache just means full
prefill, and shared blocks are copy-on-written before any divergent
append (kv_cache.BlockTable.ensure_writable).

:class:`AdmissionQueue` is bounded; on overflow it either rejects the
new request (``policy="reject"``) or evicts the oldest WAITING request
to make room (``policy="evict_oldest"`` — the evicted request is
returned to the caller so the replica can surface the shed load).
Either way the shed load is *observable*, not just an exception: a
``serving/rejected_total`` / ``serving/evicted_total`` counter ticks
and a ``serve.reject`` event records the request id and queue state,
so the autoscaler (resilience/autoscaler.py) and ``health_report.py``
can tell overload (queue full, latency burning) from failure (workers
dying) when deciding whether to add capacity.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Iterable

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.serving.kv_cache import (
    BlockAllocator, BlockTable, CacheConfig, OutOfBlocksError,
    PrefixCache)


class QueueOverflowError(RuntimeError):
    """The admission queue is full and the policy is ``reject``."""


@dataclasses.dataclass(frozen=True)
class Request:
    """One generation request. ``max_new_tokens=0`` is a scoring request
    (prefill only — the BERT-family path): it completes with the
    prompt's last-position logits argmax as its single 'token'.
    ``generated_prefix`` is internal: tokens a PREEMPTED sequence had
    already generated, replayed as prompt suffix on re-admission and
    re-attached to the completion record."""

    id: str
    tokens: tuple
    max_new_tokens: int = 16
    eos_id: int | None = None
    arrival_s: float = 0.0
    generated_prefix: tuple = ()
    #: owning tenant (multi-tenant router, serving/tenancy.py); None
    #: for single-tenant workloads — stamped through serve.admit /
    #: serve.request / serve.reject so per-tenant SLOs can partition
    tenant: str | None = None
    #: priority class ("interactive" | "batch")
    pclass: str = "interactive"

    def __post_init__(self):
        object.__setattr__(self, "tokens", tuple(int(t)
                                                 for t in self.tokens))
        if not self.tokens:
            raise ValueError(f"request {self.id}: empty prompt")


class Sequence:
    """Runtime state of one admitted request."""

    def __init__(self, request: Request, slot: int,
                 table: BlockTable, cached_tokens: int = 0):
        self.request = request
        self.slot = slot
        self.table = table
        #: leading prompt tokens adopted from the prefix cache — the
        #: engine prefills only positions cached_tokens..prompt_len-1
        self.cached_tokens = cached_tokens
        self.generated: list[int] = []
        self.prefilled = False
        self.admitted_s = time.monotonic()
        self.first_token_s: float | None = None
        self.preemptions = 0

    @property
    def prompt_len(self) -> int:
        return len(self.request.tokens)

    @property
    def length(self) -> int:
        """Tokens currently in the cache (prompt + generated so far)."""
        return self.table.length

    @property
    def last_token(self) -> int:
        return (self.generated[-1] if self.generated
                else self.request.tokens[-1])

    @property
    def done(self) -> bool:
        if not self.prefilled:
            return False
        if len(self.generated) >= self.request.max_new_tokens:
            return True
        return (self.request.eos_id is not None and self.generated
                and self.generated[-1] == self.request.eos_id)


class AdmissionQueue:
    """Bounded FIFO of waiting requests."""

    def __init__(self, capacity: int = 256, policy: str = "reject"):
        if policy not in ("reject", "evict_oldest"):
            raise ValueError(f"policy={policy!r}; expected 'reject' or "
                             f"'evict_oldest'")
        self.capacity = capacity
        self.policy = policy
        self._q: collections.deque[Request] = collections.deque()
        self.rejected = 0
        self.evicted = 0
        reg = telemetry.get_registry()
        self._m_rejected = reg.counter(
            "serving/rejected_total",
            "admission-queue overflow rejections (overload shed — "
            "distinct from worker failure)")
        self._m_evicted = reg.counter(
            "serving/evicted_total",
            "oldest-waiting requests evicted on overflow")

    def __len__(self) -> int:
        return len(self._q)

    def submit(self, request: Request) -> "Request | None":
        """Enqueue; on overflow either raise (``reject``) or drop and
        return the oldest waiting request (``evict_oldest``)."""
        evicted = None
        if len(self._q) >= self.capacity:
            if self.policy == "reject":
                self.rejected += 1
                self._m_rejected.increment()
                telemetry.event("serve.reject", id=request.id,
                                tenant=request.tenant,
                                pclass=request.pclass,
                                cause="overload",
                                queued=len(self._q),
                                capacity=self.capacity,
                                policy=self.policy)
                raise QueueOverflowError(
                    f"admission queue full ({self.capacity})")
            evicted = self._q.popleft()
            self.evicted += 1
            self._m_evicted.increment()
            telemetry.event("serve.reject", id=evicted.id,
                            tenant=evicted.tenant,
                            pclass=evicted.pclass,
                            cause="overload",
                            queued=len(self._q),
                            capacity=self.capacity,
                            policy=self.policy, evicted_for=request.id)
        self._q.append(request)
        return evicted

    def push_front(self, request: Request):
        """Re-queue a preempted sequence's request at the FRONT (it is
        the oldest work in the system; capacity is not enforced here —
        preemption must never lose a request)."""
        self._q.appendleft(request)

    def pop(self) -> "Request | None":
        return self._q.popleft() if self._q else None

    def peek(self) -> "Request | None":
        return self._q[0] if self._q else None


class ContinuousBatchingScheduler:
    """Slot + block + budget bookkeeping for one engine."""

    def __init__(self, cache_cfg: CacheConfig, *, max_slots: int,
                 max_blocks_per_seq: int, token_budget: int,
                 queue: AdmissionQueue | None = None,
                 prefix_caching: bool = False):
        self.cache_cfg = cache_cfg
        self.allocator = BlockAllocator(cache_cfg.num_blocks)
        self.queue = queue if queue is not None else AdmissionQueue()
        self.max_slots = max_slots
        self.max_blocks_per_seq = max_blocks_per_seq
        self.token_budget = token_budget
        self.running: dict[int, Sequence] = {}      # slot -> sequence
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self.preemptions = 0
        #: admission deferrals split BY CAUSE (satellite of the
        #: disaggregation PR): ``deferred_prefill`` — the head request
        #: didn't fit the step's prefill token budget (compute-bound
        #: prefill interference, the thing disaggregation removes);
        #: ``deferred_blocks`` — the pool had too few free blocks even
        #: after cache eviction (capacity, which disaggregation does
        #: NOT fix). The engine stamps per-step deltas on serve.step.
        self.deferred_prefill = 0
        self.deferred_blocks = 0
        reg = telemetry.get_registry()
        self._m_deferred_prefill = reg.counter(
            "serving/deferred_prefill_total",
            "admissions deferred by the prefill token budget "
            "(prefill/decode interference — disaggregation removes)")
        self._m_deferred_blocks = reg.counter(
            "serving/deferred_blocks_total",
            "admissions deferred by pool exhaustion (KV capacity — "
            "disaggregation does not remove)")
        #: optional callable(victim: Sequence) -> bool installed by the
        #: disaggregated engine: return True to take OWNERSHIP of a
        #: preemption victim (migrate its live KV to another replica)
        #: instead of the replay requeue. See _preempt_newest.
        self.preempt_hook = None
        self.migrated_out = 0
        self.prefix_cache = (PrefixCache(self.allocator,
                                         cache_cfg.block_size)
                             if prefix_caching else None)

    # -- admission --------------------------------------------------------
    def admit(self) -> list[Sequence]:
        """Admit queued requests for this step under the token budget:
        budget = token_budget - (1 decode token per running seq); each
        admission consumes the prompt tokens prefill will actually
        COMPUTE — the unmatched suffix when the prefix cache hits, so
        cache hits also stretch admission throughput. Stops at the
        first request that does not fit (FIFO order is preserved — no
        starvation of big prompts behind small ones)."""
        budget = self.token_budget - len(self.running)
        admitted: list[Sequence] = []
        while self._free_slots and self.queue.peek() is not None:
            req = self.queue.peek()
            cached, cblocks = (self.prefix_cache.match(req.tokens)
                               if self.prefix_cache is not None
                               else (0, []))
            need = len(req.tokens) - cached     # prefill computes this
            if need > budget and (admitted or self.running):
                if cblocks:                 # hand the match refs back
                    self.allocator.free(cblocks)
                self.deferred_prefill += 1
                self._m_deferred_prefill.increment()
                break                       # never starves: alone it runs
            blocks_needed = self.cache_cfg.blocks_for(len(req.tokens) + 1)
            if blocks_needed > self.max_blocks_per_seq:
                # can never fit: fail the request rather than wedge FIFO
                if cblocks:
                    self.allocator.free(cblocks)
                self.queue.pop()
                raise OutOfBlocksError(
                    f"request {req.id}: prompt of {len(req.tokens)} "
                    f"tokens needs {blocks_needed} blocks > "
                    f"max_blocks_per_seq={self.max_blocks_per_seq}")
            grow = blocks_needed - len(cblocks)
            if grow > self.allocator.num_free:
                if self.prefix_cache is not None:
                    self.prefix_cache.evict(grow - self.allocator.num_free)
                if grow > self.allocator.num_free:
                    if cblocks:
                        self.allocator.free(cblocks)
                    self.deferred_blocks += 1
                    self._m_deferred_blocks.increment()
                    break                   # wait for blocks to free up
            self.queue.pop()
            slot = self._free_slots.pop()
            table = BlockTable(self.cache_cfg, self.max_blocks_per_seq)
            table.blocks = list(cblocks)    # match()'s refs transfer here
            table.ensure_room(len(req.tokens) + 1, self.allocator)
            seq = Sequence(req, slot, table, cached_tokens=cached)
            self.running[slot] = seq
            admitted.append(seq)
            budget -= need
        return admitted

    # -- per-step transitions ---------------------------------------------
    def commit_prefill(self, seq: Sequence):
        seq.table.length = seq.prompt_len
        seq.prefilled = True
        if self.prefix_cache is not None:
            # index the prompt's full blocks for later requests; the
            # table holds post-copy-on-write private blocks, so every
            # registered block really contains these tokens' K/V
            self.prefix_cache.register(seq.request.tokens,
                                       seq.table.blocks)

    def _ensure_room(self, table: BlockTable, n_tokens: int):
        """``table.ensure_room`` with prefix-cache pressure relief:
        when the pool is short, evict unreferenced cached blocks before
        giving up (the caller then falls back to preemption)."""
        need = self.cache_cfg.blocks_for(table.length + n_tokens)
        while True:
            try:
                table.ensure_room(n_tokens, self.allocator)
                return
            except OutOfBlocksError:
                grow = need - len(table.blocks)
                if (need <= table.max_blocks
                        and self.prefix_cache is not None
                        and grow > self.allocator.num_free
                        and self.prefix_cache.evict(
                            grow - self.allocator.num_free) > 0):
                    continue
                raise

    def grow_for_decode(self, n_tokens=1) -> list[Sequence]:
        """Make room for ``n_tokens`` more tokens (an int, or a
        callable(seq) -> int — speculative decode reserves k+1 per
        sequence) in every running prefilled sequence; a sequence that
        cannot grow evicts unreferenced cached blocks first, then
        triggers newest-first preemption until the growth fits. Returns
        the decode batch."""
        batch = [s for s in self.running.values() if s.prefilled
                 and not s.done]
        batch.sort(key=lambda s: s.slot)
        for seq in list(batch):
            if seq not in batch:
                # preempted by an EARLIER grower this very step: its
                # table is released — growing it would leak blocks
                # into a zombie table (regression-tested)
                continue
            n = n_tokens(seq) if callable(n_tokens) else n_tokens
            while True:
                try:
                    self._ensure_room(seq.table, n)
                    break
                except OutOfBlocksError:
                    victim = self._preempt_newest(exclude=seq)
                    if victim is None:
                        raise       # nothing left to preempt: misconfig
                    if victim in batch:
                        batch.remove(victim)
        return batch

    def _preempt_newest(self, exclude: Sequence) -> "Sequence | None":
        cands = [s for s in self.running.values() if s is not exclude]
        if not cands:
            return None
        victim = max(cands, key=lambda s: s.admitted_s)
        del self.running[victim.slot]
        self._free_slots.append(victim.slot)
        self._free_slots.sort(reverse=True)
        if self.preempt_hook is not None and victim.prefilled \
                and self.preempt_hook(victim):
            # The hook took ownership: the victim's live KV migrated to
            # another replica (blocks + bookkeeping released there), so
            # there is nothing to replay — the request is NOT requeued
            # and this is not a replay preemption.
            self.migrated_out += 1
            return victim
        victim.table.release(self.allocator)
        # generated tokens become prompt suffix: greedy decode replays
        # them identically on re-admission (deterministic outputs), and
        # generated_prefix re-attaches them to the completion record
        req = victim.request
        new_req = dataclasses.replace(
            req, tokens=req.tokens + tuple(victim.generated),
            max_new_tokens=req.max_new_tokens - len(victim.generated),
            generated_prefix=(req.generated_prefix
                              + tuple(victim.generated)))
        self.queue.push_front(new_req)
        victim.preemptions += 1
        self.preemptions += 1
        return victim

    @staticmethod
    def _pristine(req: Request) -> Request:
        """Undo preemption-replay rewriting: the ORIGINAL request, with
        any previously generated tokens stripped back out of the prompt
        and the generation budget restored."""
        n = len(req.generated_prefix)
        if n == 0:
            return req
        return dataclasses.replace(
            req, tokens=req.tokens[:len(req.tokens) - n],
            max_new_tokens=req.max_new_tokens + n,
            generated_prefix=())

    def requeue_running(self) -> int:
        """Release every running sequence and re-queue its PRISTINE
        request at the front of the admission queue — the weights
        hot-swap primitive. Tokens generated so far are discarded, not
        replayed: replaying them as prompt (the preemption path) would
        splice version-N tokens into a version-N+1 stream. Re-admission
        re-prefills from scratch under the new weights, so every
        completed output is wholly one version's. Queued requests that
        carry a preemption-replay ``generated_prefix`` (version-N
        tokens waiting to be replayed) are sanitized the same way.
        Returns the number of running sequences requeued. FIFO age
        order is preserved: the oldest request ends up at the front."""
        seqs = sorted(self.running.values(),
                      key=lambda s: s.admitted_s, reverse=True)
        for seq in seqs:
            del self.running[seq.slot]
            self._free_slots.append(seq.slot)
            seq.table.release(self.allocator)
            self.queue.push_front(self._pristine(seq.request))
        self._free_slots.sort(reverse=True)
        for i, req in enumerate(self.queue._q):
            if req.generated_prefix:
                self.queue._q[i] = self._pristine(req)
        return len(seqs)

    def adopt(self, request: Request, blocks: list[int], length: int,
              generated) -> Sequence:
        """Install an ALREADY-PREFILLED sequence (KV migrated in from
        another replica — see serving/migrate.py). ``blocks`` are
        freshly allocated on THIS scheduler's allocator and hold the
        sequence's first ``length`` cache rows; ``generated`` are
        tokens produced elsewhere, kept as live generation state (NOT
        ``generated_prefix``) so the handoff replays nothing. Raises
        when no slot is free — the migration source must check capacity
        before shipping."""
        if not self._free_slots:
            raise OutOfBlocksError(
                f"adopt({request.id}): no free slot "
                f"(max_slots={self.max_slots})")
        if len(blocks) > self.max_blocks_per_seq:
            raise OutOfBlocksError(
                f"adopt({request.id}): {len(blocks)} blocks > "
                f"max_blocks_per_seq={self.max_blocks_per_seq}")
        slot = self._free_slots.pop()
        table = BlockTable(self.cache_cfg, self.max_blocks_per_seq)
        table.blocks = list(blocks)         # caller's refs transfer here
        table.length = length
        seq = Sequence(request, slot, table)
        seq.generated = [int(t) for t in generated]
        seq.prefilled = True
        self.running[slot] = seq
        return seq

    def append_token(self, seq: Sequence, token: int):
        seq.table.length += 1
        seq.generated.append(int(token))
        if seq.first_token_s is None:
            seq.first_token_s = time.monotonic()

    def finish(self, seq: Sequence):
        """Retire a finished sequence: blocks back to the pool, slot
        freed — both available to the NEXT admission immediately."""
        del self.running[seq.slot]
        self._free_slots.append(seq.slot)
        self._free_slots.sort(reverse=True)
        seq.table.release(self.allocator)

    def finished(self) -> Iterable[Sequence]:
        return [s for s in self.running.values() if s.done]

    @property
    def idle(self) -> bool:
        return not self.running and len(self.queue) == 0
