"""Compiled incremental decode for the transformer/BERT family.

Two pure, jittable programs over a trained ``TransformerLM`` parameter
tree (stacked-layers layout) and the block-allocated KV pool
(serving/kv_cache.py):

- :func:`make_prefill_fn` — one right-padded mixed-length batch of
  prompts through the FULL forward (the exact math of
  ``models/transformer.TransformerLM``, masked by the factored
  ``ops.attention.length_valid_mask`` rule), writing every position's
  rotary-embedded K and V into the sequences' cache blocks and
  returning each prompt's last-position logits.
- :func:`make_decode_fn` — ONE token per running slot: project q/k/v
  for the new token, scatter k/v into the slot's current block, gather
  the slot's block window, and attend the single query against it.
  Because prefill wrote the same K/V the full forward computes and the
  mask is the same factored rule, greedy decode through the cache
  matches argmax over full-sequence recompute — the correctness
  contract tests/test_serving.py pins on 1 device and on dp×tp meshes.

Everything here is plain jnp (no Pallas custom calls), so on a serving
mesh GSPMD partitions the programs directly: slots over ``dp``,
heads/mlp/vocab over ``tp`` (:func:`param_shardings`), the pool laid
out by ``kv_cache.pool_shardings``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM, mesh_axis_rules, rotary_embedding)
from distributed_tensorflow_tpu.ops.attention import mha_reference


def _plain(tree):
    """Deep-convert FrozenDict/Mapping nodes to plain dicts so the
    parameter tree's pytree STRUCTURE matches the shardings tree the
    engine passes as jit in_shardings."""
    if hasattr(tree, "items"):
        return {k: _plain(v) for k, v in tree.items()}
    return tree


def canonical_params(cfg: TransformerConfig, params):
    """Parameter tree in the stacked-layers layout the decode programs
    index (``params["layers"]`` leaves shaped ``(L, ...)``, plain-dict
    nodes): unstacked ``layer_<i>`` trees (scan_layers=False training)
    are stacked."""
    params = _plain(params)
    if "layers" in params:
        return params
    names = [f"layer_{i}" for i in range(cfg.n_layers)]
    missing = [n for n in names if n not in params]
    if missing:
        raise ValueError(f"params have neither 'layers' nor {missing}")
    layers = [params.pop(n) for n in names]
    params["layers"] = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *layers)
    return params


def _layer(params, l: int):
    return jax.tree_util.tree_map(lambda a: a[l], dict(params["layers"]))


def _rms_norm(x, scale, dtype, eps: float = 1e-6):
    """models/transformer.RMSNorm math, parameter passed explicitly."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale).astype(dtype)


def rotary_at(x, positions, *, base: float = 10000.0):
    """RoPE at explicit absolute positions: ``x`` is ``(B, H, Q, hd)``,
    ``positions`` ``(B, Q)``. Same angle formula as
    ``models/transformer.rotary_embedding`` so a token's K is bitwise
    the same whether computed in prefill (positions ``0..S-1``) or one
    at a time during decode."""
    d = x.shape[-1]
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # (B, Q, d/2)
    sin = jnp.sin(ang)[:, None]                                # (B,1,Q,d/2)
    cos = jnp.cos(ang)[:, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


def model_forward(cfg: TransformerConfig, params, tokens, lengths=None,
                  *, return_kv: bool = False):
    """Full-sequence forward over the canonical parameter tree — the
    serving-side twin of ``TransformerLM.__call__`` (same einsums, same
    order, no sharding-constraint machinery; GSPMD lays it out from the
    caller's in_shardings). ``lengths`` masks a right-padded batch via
    the factored rule. ``return_kv`` additionally returns the per-layer
    post-RoPE K and V stacks ``(L, B, H, S, hd)`` — exactly what prefill
    writes into the cache blocks."""
    dt = cfg.dtype
    embed = params["embed"]
    x = embed.astype(dt)[tokens]                       # (B, S, D)
    ks, vs = [], []
    for l in range(cfg.n_layers):
        p = _layer(params, l)
        h = _rms_norm(x, p["RMSNorm_0"]["scale"], dt)
        att = p["attn"]
        q = jnp.einsum("bsd,dhk->bhsk", h, att["query"].astype(dt))
        k = jnp.einsum("bsd,dhk->bhsk", h, att["key"].astype(dt))
        v = jnp.einsum("bsd,dhk->bhsk", h, att["value"].astype(dt))
        q = rotary_embedding(q, seq_axis=-2)
        k = rotary_embedding(k, seq_axis=-2)
        o = mha_reference(q, k, v, causal=cfg.causal, lengths=lengths)
        o = jnp.einsum("bhsk,hkd->bsd", o, att["out"].astype(dt))
        x = x + o
        h = _rms_norm(x, p["RMSNorm_1"]["scale"], dt)
        mlp = p["mlp"]
        hh = jnp.einsum("bsd,df->bsf", h, mlp["wi"].astype(dt))
        gate, up = jnp.split(hh, 2, axis=-1)
        hh = jax.nn.silu(gate) * up
        x = x + jnp.einsum("bsf,fd->bsd", hh, mlp["wo"].astype(dt))
        if return_kv:
            ks.append(k)
            vs.append(v)
    x = _rms_norm(x, params["final_norm"]["scale"], dt)
    logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(dt))
    logits = logits.astype(jnp.float32)
    if return_kv:
        return logits, (jnp.stack(ks), jnp.stack(vs))
    return logits


def make_prefill_fn(cfg: TransformerConfig):
    """``prefill(params, pool_k, pool_v, tokens, lengths, write_rows)``
    → ``(last_logits, pool_k, pool_v)``.

    ``tokens`` (B, S) right-padded prompts, ``lengths`` (B,) true
    lengths, ``write_rows`` (B, S) flat pool rows per position (padded
    positions point at the trash block). ``last_logits`` (B, vocab) are
    the logits at each prompt's final REAL position — the first
    generated token's distribution."""

    def prefill(params, pool_k, pool_v, tokens, lengths, write_rows):
        B, S = tokens.shape
        logits, (ks, vs) = model_forward(cfg, params, tokens,
                                         lengths=lengths, return_kv=True)
        L, _, H, _, hd = ks.shape
        rows = write_rows.reshape(-1)                       # (B*S,)
        flat_k = ks.transpose(0, 1, 3, 2, 4).reshape(L, B * S, H, hd)
        flat_v = vs.transpose(0, 1, 3, 2, 4).reshape(L, B * S, H, hd)
        pool_k = pool_k.at[:, rows].set(flat_k.astype(pool_k.dtype))
        pool_v = pool_v.at[:, rows].set(flat_v.astype(pool_v.dtype))
        last = logits[jnp.arange(B), jnp.maximum(lengths, 1) - 1]
        return last, pool_k, pool_v

    return prefill


def make_decode_fn(cfg: TransformerConfig):
    """``decode(params, pool_k, pool_v, tokens, positions, lengths,
    write_rows, window_rows)`` → ``(logits, pool_k, pool_v)``.

    One incremental step for a batch of running slots: ``tokens`` (B,)
    the token being fed, ``positions`` (B,) its absolute position,
    ``lengths`` (B,) the post-append visible length (``positions + 1``
    for active slots, 0 for idle ones — an idle slot attends nothing
    and its logits row is garbage the scheduler never reads),
    ``write_rows`` (B,) the flat pool row this token's K/V lands in,
    ``window_rows`` (B, W) each slot's full block-window gather index.
    """
    if not cfg.causal:
        raise ValueError("incremental decode requires a causal model; "
                         "serve bidirectional (BERT) configs through the "
                         "prefill/scoring path")

    def decode(params, pool_k, pool_v, tokens, positions, lengths,
               write_rows, window_rows):
        dt = cfg.dtype
        embed = params["embed"]
        x = embed.astype(dt)[tokens]                    # (B, D)
        pos_q = positions[:, None]                      # (B, 1)
        for l in range(cfg.n_layers):
            p = _layer(params, l)
            h = _rms_norm(x, p["RMSNorm_0"]["scale"], dt)
            att = p["attn"]
            q = jnp.einsum("bd,dhk->bhk", h, att["query"].astype(dt))
            k = jnp.einsum("bd,dhk->bhk", h, att["key"].astype(dt))
            v = jnp.einsum("bd,dhk->bhk", h, att["value"].astype(dt))
            q = rotary_at(q[:, :, None], pos_q)          # (B, H, 1, hd)
            k = rotary_at(k[:, :, None], pos_q)[:, :, 0]  # (B, H, hd)
            # write THEN gather: the query must see its own position
            pool_k = pool_k.at[l, write_rows].set(k.astype(pool_k.dtype))
            pool_v = pool_v.at[l, write_rows].set(v.astype(pool_v.dtype))
            kw = pool_k[l][window_rows]                  # (B, W, H, hd)
            vw = pool_v[l][window_rows]
            kw = kw.transpose(0, 2, 1, 3).astype(dt)     # (B, H, W, hd)
            vw = vw.transpose(0, 2, 1, 3).astype(dt)
            o = mha_reference(q, kw, vw, causal=True, lengths=lengths,
                              q_positions=positions)     # (B, H, 1, hd)
            o = jnp.einsum("bhk,hkd->bd", o[:, :, 0],
                           att["out"].astype(dt))
            x = x + o
            h = _rms_norm(x, p["RMSNorm_1"]["scale"], dt)
            mlp = p["mlp"]
            hh = jnp.einsum("bd,df->bf", h, mlp["wi"].astype(dt))
            gate, up = jnp.split(hh, 2, axis=-1)
            hh = jax.nn.silu(gate) * up
            x = x + jnp.einsum("bf,fd->bd", hh, mlp["wo"].astype(dt))
        x = _rms_norm(x, params["final_norm"]["scale"], dt)
        logits = jnp.einsum("bd,vd->bv", x, embed.astype(dt))
        return logits.astype(jnp.float32), pool_k, pool_v

    return decode


def param_shardings(cfg: TransformerConfig, mesh):
    """NamedShardings for the canonical (stacked-layers) serving
    parameter tree from the SAME logical-axis metadata training uses
    (``LOGICAL_AXIS_RULES`` restricted to the serving mesh):
    heads/mlp/vocab over ``tp``, everything else replicated (a dp×tp
    serving mesh has no fsdp axis, so ``embed``'s fsdp rule maps to
    None)."""
    import dataclasses

    from flax.linen import partitioning as nn_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    # scan_layers=True yields the stacked "layers" tree directly — the
    # canonical layout — with the leading layer axis already unsharded
    # (the "layers" logical axis maps to None).
    shape_cfg = dataclasses.replace(cfg, scan_layers=True, mesh=None)
    model = TransformerLM(shape_cfg)
    rules = mesh_axis_rules(mesh)
    tokens = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
    with nn_partitioning.axis_rules(list(rules)):
        var_shapes = jax.eval_shape(
            lambda r: model.init(r, tokens), jax.random.PRNGKey(0))
        logical = nn_partitioning.get_axis_names(var_shapes["params_axes"])
        mesh_specs = nn_partitioning.logical_to_mesh(logical)
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), mesh_specs,
        is_leaf=lambda x: isinstance(x, P))
    return _plain(shardings)
