"""Compiled incremental decode for the transformer/BERT family.

Pure, jittable programs over a trained ``TransformerLM`` parameter
tree (stacked-layers layout) and the block-allocated KV pool
(serving/kv_cache.py):

- :func:`make_prefill_fn` — one right-padded mixed-length batch of
  prompts through the FULL forward (the exact math of
  ``models/transformer.TransformerLM``, masked by the factored
  ``ops.attention.length_valid_mask`` rule), writing every position's
  rotary-embedded K and V into the sequences' cache blocks and
  returning each prompt's last-position logits.
- :func:`make_decode_fn` — ONE token per running slot: project q/k/v
  for the new token, scatter k/v into the slot's current block, gather
  the slot's block window, and attend the single query against it.
  Because prefill wrote the same K/V the full forward computes and the
  mask is the same factored rule, greedy decode through the cache
  matches argmax over full-sequence recompute — the correctness
  contract tests/test_serving.py pins on 1 device and on dp×tp meshes.
- :func:`make_extend_fn` — the MULTI-token cache-aware forward: E new
  tokens per slot at explicit absolute positions, written then attended
  against each slot's block window. This is both the prefix-cache
  *start-offset prefill* (a prompt whose first C tokens hash-matched
  cached blocks runs only the suffix through it) and the speculative-
  decoding *verify* step (the target model scores the draft's k tokens
  plus the bonus position in one forward). At E=1 it is exactly
  :func:`make_decode_fn`.

Every program takes and returns the pool as ONE dict (``{"k", "v"}``
plus ``{"k_scale", "v_scale"}`` when the cache config is int8): writes
quantize on the way in, gathers dequantize on the way out, so the whole
quantisation story lives in :func:`_pool_write` / :func:`_pool_window`
and the attention math never sees anything but the compute dtype.

Everything here is plain jnp (no Pallas custom calls), so on a serving
mesh GSPMD partitions the programs directly: slots over ``dp``,
heads/mlp/vocab over ``tp`` (:func:`param_shardings`), the pool laid
out by ``kv_cache.pool_shardings``.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM, mesh_axis_rules, rotary_embedding)
from distributed_tensorflow_tpu.ops.attention import mha_reference


def _plain(tree):
    """Deep-convert FrozenDict/Mapping nodes to plain dicts so the
    parameter tree's pytree STRUCTURE matches the shardings tree the
    engine passes as jit in_shardings."""
    if hasattr(tree, "items"):
        return {k: _plain(v) for k, v in tree.items()}
    return tree


def canonical_params(cfg: TransformerConfig, params):
    """Parameter tree in the stacked-layers layout the decode programs
    index (``params["layers"]`` leaves shaped ``(L, ...)``, plain-dict
    nodes): unstacked ``layer_<i>`` trees (scan_layers=False training)
    are stacked."""
    params = _plain(params)
    if "layers" in params:
        return params
    names = [f"layer_{i}" for i in range(cfg.n_layers)]
    missing = [n for n in names if n not in params]
    if missing:
        raise ValueError(f"params have neither 'layers' nor {missing}")
    layers = [params.pop(n) for n in names]
    params["layers"] = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *layers)
    return params


def truncated_draft(cfg: TransformerConfig, params, n_layers=None):
    """Self-speculation draft: the target's FIRST ``n_layers`` layers
    plus the shared embeddings and final norm — a draft model that
    costs nothing to obtain (LayerSkip / Draft&Verify style) and is the
    engine's default when ``speculative_k > 0`` with no explicit draft.
    Returns ``(draft_cfg, draft_params)`` in the canonical layout."""
    n = n_layers if n_layers is not None else max(1, cfg.n_layers // 2)
    if not 1 <= n <= cfg.n_layers:
        raise ValueError(f"truncated_draft: n_layers={n} outside "
                         f"[1, {cfg.n_layers}]")
    p = canonical_params(cfg, params)
    dp = dict(p)
    dp["layers"] = jax.tree_util.tree_map(lambda a: a[:n],
                                          dict(p["layers"]))
    dcfg = dataclasses.replace(cfg, n_layers=n, mesh=None)
    return dcfg, dp


def _layer(params, l: int):
    return jax.tree_util.tree_map(lambda a: a[l], dict(params["layers"]))


def _rms_norm(x, scale, dtype, eps: float = 1e-6):
    """models/transformer.RMSNorm math, parameter passed explicitly."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps) * scale).astype(dtype)


def rotary_at(x, positions, *, base: float = 10000.0):
    """RoPE at explicit absolute positions: ``x`` is ``(B, H, Q, hd)``,
    ``positions`` ``(B, Q)``. Same angle formula as
    ``models/transformer.rotary_embedding`` so a token's K is bitwise
    the same whether computed in prefill (positions ``0..S-1``) or one
    at a time during decode."""
    d = x.shape[-1]
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    ang = positions.astype(jnp.float32)[..., None] * inv_freq  # (B, Q, d/2)
    sin = jnp.sin(ang)[:, None]                                # (B,1,Q,d/2)
    cos = jnp.cos(ang)[:, None]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                          axis=-1)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# pool write / gather (the quantisation seam)
# ---------------------------------------------------------------------------

def _quantize_rows(x):
    """``(..., H, hd)`` float → int8 codes + per-(row, head) f32 scale.
    The quantisation block is one head's ``hd``-vector of one pool row:
    symmetric absmax scaling, so dequantisation is one multiply."""
    x32 = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -127, 127)
    return q.astype(jnp.int8), scale


def _pool_write(pool: dict, l, rows, k, v, quantized: bool) -> dict:
    """Scatter new K/V rows (``(N, H, hd)`` compute-dtype) into layer
    ``l`` of the pool at flat ``rows``; int8 pools quantize on write
    and store the scales alongside."""
    pool = dict(pool)
    if quantized:
        qk, sk = _quantize_rows(k)
        qv, sv = _quantize_rows(v)
        pool["k"] = pool["k"].at[l, rows].set(qk)
        pool["v"] = pool["v"].at[l, rows].set(qv)
        pool["k_scale"] = pool["k_scale"].at[l, rows].set(sk)
        pool["v_scale"] = pool["v_scale"].at[l, rows].set(sv)
    else:
        pool["k"] = pool["k"].at[l, rows].set(k.astype(pool["k"].dtype))
        pool["v"] = pool["v"].at[l, rows].set(v.astype(pool["v"].dtype))
    return pool


def _pool_window(pool: dict, l, window_rows, dt, quantized: bool):
    """Gather each slot's block window from layer ``l``:
    ``(B, W, H, hd)`` → ``(B, H, W, hd)`` compute-dtype, dequantized
    for int8 pools."""
    kw = pool["k"][l][window_rows]
    vw = pool["v"][l][window_rows]
    if quantized:
        kw = kw.astype(jnp.float32) * pool["k_scale"][l][window_rows][..., None]
        vw = vw.astype(jnp.float32) * pool["v_scale"][l][window_rows][..., None]
    return (kw.transpose(0, 2, 1, 3).astype(dt),
            vw.transpose(0, 2, 1, 3).astype(dt))


def make_copy_fn():
    """``copy(pool, src_rows, dst_rows)`` → pool with rows ``src_rows``
    duplicated into ``dst_rows`` across every layer and every pool
    array (values AND scales) — the device side of copy-on-write: the
    engine applies it before the first divergent write into a shared
    block."""

    def copy(pool, src_rows, dst_rows):
        return {n: a.at[:, dst_rows].set(a[:, src_rows])
                for n, a in pool.items()}

    return copy


def model_forward(cfg: TransformerConfig, params, tokens, lengths=None,
                  *, return_kv: bool = False):
    """Full-sequence forward over the canonical parameter tree — the
    serving-side twin of ``TransformerLM.__call__`` (same einsums, same
    order, no sharding-constraint machinery; GSPMD lays it out from the
    caller's in_shardings). ``lengths`` masks a right-padded batch via
    the factored rule. ``return_kv`` additionally returns the per-layer
    post-RoPE K and V stacks ``(L, B, H, S, hd)`` — exactly what prefill
    writes into the cache blocks."""
    dt = cfg.dtype
    embed = params["embed"]
    x = embed.astype(dt)[tokens]                       # (B, S, D)
    ks, vs = [], []
    for l in range(cfg.n_layers):
        p = _layer(params, l)
        h = _rms_norm(x, p["RMSNorm_0"]["scale"], dt)
        att = p["attn"]
        q = jnp.einsum("bsd,dhk->bhsk", h, att["query"].astype(dt))
        k = jnp.einsum("bsd,dhk->bhsk", h, att["key"].astype(dt))
        v = jnp.einsum("bsd,dhk->bhsk", h, att["value"].astype(dt))
        q = rotary_embedding(q, seq_axis=-2)
        k = rotary_embedding(k, seq_axis=-2)
        o = mha_reference(q, k, v, causal=cfg.causal, lengths=lengths)
        o = jnp.einsum("bhsk,hkd->bsd", o, att["out"].astype(dt))
        x = x + o
        h = _rms_norm(x, p["RMSNorm_1"]["scale"], dt)
        mlp = p["mlp"]
        hh = jnp.einsum("bsd,df->bsf", h, mlp["wi"].astype(dt))
        gate, up = jnp.split(hh, 2, axis=-1)
        hh = jax.nn.silu(gate) * up
        x = x + jnp.einsum("bsf,fd->bsd", hh, mlp["wo"].astype(dt))
        if return_kv:
            ks.append(k)
            vs.append(v)
    x = _rms_norm(x, params["final_norm"]["scale"], dt)
    logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(dt))
    logits = logits.astype(jnp.float32)
    if return_kv:
        return logits, (jnp.stack(ks), jnp.stack(vs))
    return logits


def make_prefill_fn(cfg: TransformerConfig, cache_cfg=None):
    """``prefill(params, pool, tokens, lengths, write_rows)``
    → ``(last_logits, pool)``.

    ``tokens`` (B, S) right-padded prompts, ``lengths`` (B,) true
    lengths, ``write_rows`` (B, S) flat pool rows per position (padded
    positions point at the trash block). ``last_logits`` (B, vocab) are
    the logits at each prompt's final REAL position — the first
    generated token's distribution."""
    quantized = cache_cfg.quantized if cache_cfg is not None else False

    def prefill(params, pool, tokens, lengths, write_rows):
        B, S = tokens.shape
        logits, (ks, vs) = model_forward(cfg, params, tokens,
                                         lengths=lengths, return_kv=True)
        L, _, H, _, hd = ks.shape
        rows = write_rows.reshape(-1)                       # (B*S,)
        flat_k = ks.transpose(0, 1, 3, 2, 4).reshape(L, B * S, H, hd)
        flat_v = vs.transpose(0, 1, 3, 2, 4).reshape(L, B * S, H, hd)
        for l in range(L):
            pool = _pool_write(pool, l, rows, flat_k[l], flat_v[l],
                               quantized)
        last = logits[jnp.arange(B), jnp.maximum(lengths, 1) - 1]
        return last, pool

    return prefill


def make_decode_fn(cfg: TransformerConfig, cache_cfg=None):
    """``decode(params, pool, tokens, positions, lengths, write_rows,
    window_rows)`` → ``(logits, pool)``.

    One incremental step for a batch of running slots: ``tokens`` (B,)
    the token being fed, ``positions`` (B,) its absolute position,
    ``lengths`` (B,) the post-append visible length (``positions + 1``
    for active slots, 0 for idle ones — an idle slot attends nothing
    and its logits row is garbage the scheduler never reads),
    ``write_rows`` (B,) the flat pool row this token's K/V lands in,
    ``window_rows`` (B, W) each slot's full block-window gather index.
    """
    if not cfg.causal:
        raise ValueError("incremental decode requires a causal model; "
                         "serve bidirectional (BERT) configs through the "
                         "prefill/scoring path")
    quantized = cache_cfg.quantized if cache_cfg is not None else False

    def decode(params, pool, tokens, positions, lengths, write_rows,
               window_rows):
        dt = cfg.dtype
        embed = params["embed"]
        x = embed.astype(dt)[tokens]                    # (B, D)
        pos_q = positions[:, None]                      # (B, 1)
        for l in range(cfg.n_layers):
            p = _layer(params, l)
            h = _rms_norm(x, p["RMSNorm_0"]["scale"], dt)
            att = p["attn"]
            q = jnp.einsum("bd,dhk->bhk", h, att["query"].astype(dt))
            k = jnp.einsum("bd,dhk->bhk", h, att["key"].astype(dt))
            v = jnp.einsum("bd,dhk->bhk", h, att["value"].astype(dt))
            q = rotary_at(q[:, :, None], pos_q)          # (B, H, 1, hd)
            k = rotary_at(k[:, :, None], pos_q)[:, :, 0]  # (B, H, hd)
            # write THEN gather: the query must see its own position
            pool = _pool_write(pool, l, write_rows, k, v, quantized)
            kw, vw = _pool_window(pool, l, window_rows, dt, quantized)
            o = mha_reference(q, kw, vw, causal=True, lengths=lengths,
                              q_positions=positions)     # (B, H, 1, hd)
            o = jnp.einsum("bhk,hkd->bd", o[:, :, 0],
                           att["out"].astype(dt))
            x = x + o
            h = _rms_norm(x, p["RMSNorm_1"]["scale"], dt)
            mlp = p["mlp"]
            hh = jnp.einsum("bd,df->bf", h, mlp["wi"].astype(dt))
            gate, up = jnp.split(hh, 2, axis=-1)
            hh = jax.nn.silu(gate) * up
            x = x + jnp.einsum("bf,fd->bd", hh, mlp["wo"].astype(dt))
        x = _rms_norm(x, params["final_norm"]["scale"], dt)
        logits = jnp.einsum("bd,vd->bv", x, embed.astype(dt))
        return logits.astype(jnp.float32), pool

    return decode


def make_extend_fn(cfg: TransformerConfig, cache_cfg=None):
    """``extend(params, pool, tokens, positions, lengths, write_rows,
    window_rows)`` → ``(logits, pool)`` — E tokens per slot in one
    cache-aware forward.

    ``tokens`` (B, E) the new tokens (right-padded), ``positions``
    (B, E) their ABSOLUTE cache positions (padded entries must point at
    or past ``lengths`` so the factored mask zeroes them), ``lengths``
    (B,) the post-write visible length, ``write_rows`` (B, E) flat pool
    rows (padded entries at the trash block), ``window_rows`` (B, W)
    the block-window gather index. Returns logits for ALL E positions
    — row ``i`` is the next-token distribution after the token fed at
    ``positions[:, i]``.

    Two callers, one program: prefix-cache suffix prefill (positions
    ``C..L-1`` of a prompt whose first C tokens hash-matched) and
    speculative-decode verification (positions ``L-1..L+k-1``: the
    banked token plus k draft proposals, scored in one step). Per-query
    math is position-independent, so row 0 of a (B, E) extend is
    bitwise the row a (B,) decode at the same position produces — the
    greedy-parity contract extends to both callers."""
    if not cfg.causal:
        raise ValueError("extend requires a causal model; serve "
                         "bidirectional (BERT) configs through the "
                         "prefill/scoring path")
    quantized = cache_cfg.quantized if cache_cfg is not None else False

    def extend(params, pool, tokens, positions, lengths, write_rows,
               window_rows):
        dt = cfg.dtype
        B, E = tokens.shape
        embed = params["embed"]
        x = embed.astype(dt)[tokens]                    # (B, E, D)
        rows = write_rows.reshape(-1)                   # (B*E,)
        for l in range(cfg.n_layers):
            p = _layer(params, l)
            h = _rms_norm(x, p["RMSNorm_0"]["scale"], dt)
            att = p["attn"]
            q = jnp.einsum("bsd,dhk->bhsk", h, att["query"].astype(dt))
            k = jnp.einsum("bsd,dhk->bhsk", h, att["key"].astype(dt))
            v = jnp.einsum("bsd,dhk->bhsk", h, att["value"].astype(dt))
            q = rotary_at(q, positions)                  # (B, H, E, hd)
            k = rotary_at(k, positions)
            # write THEN gather: query i must see keys 0..i of the span
            flat_k = k.transpose(0, 2, 1, 3).reshape(B * E, k.shape[1],
                                                     k.shape[3])
            flat_v = v.transpose(0, 2, 1, 3).reshape(B * E, v.shape[1],
                                                     v.shape[3])
            pool = _pool_write(pool, l, rows, flat_k, flat_v, quantized)
            kw, vw = _pool_window(pool, l, window_rows, dt, quantized)
            o = mha_reference(q, kw, vw, causal=True, lengths=lengths,
                              q_positions=positions)     # (B, H, E, hd)
            o = jnp.einsum("bhsk,hkd->bsd", o, att["out"].astype(dt))
            x = x + o
            h = _rms_norm(x, p["RMSNorm_1"]["scale"], dt)
            mlp = p["mlp"]
            hh = jnp.einsum("bsd,df->bsf", h, mlp["wi"].astype(dt))
            gate, up = jnp.split(hh, 2, axis=-1)
            hh = jax.nn.silu(gate) * up
            x = x + jnp.einsum("bsf,fd->bsd", hh, mlp["wo"].astype(dt))
        x = _rms_norm(x, params["final_norm"]["scale"], dt)
        logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(dt))
        return logits.astype(jnp.float32), pool

    return extend


def make_draft_fn(cfg: TransformerConfig):
    """``draft(params, tokens, lengths)`` → (B,) greedy next token at
    each sequence's end — the speculative-decoding proposal step,
    batched over the decode slots. Full recompute (the draft model is
    small by construction; it keeps no cache state to invalidate on
    preemption or restart)."""

    def draft(params, tokens, lengths):
        logits = model_forward(cfg, params, tokens, lengths=lengths)
        last = logits[jnp.arange(tokens.shape[0]),
                      jnp.maximum(lengths, 1) - 1]
        return jnp.argmax(last, axis=-1).astype(jnp.int32)

    return jax.jit(draft)


def kv_quantization_probe(cfg: TransformerConfig, params, prompt,
                          kv_dtype: str = "int8", *,
                          n_steps: int = 8, num_blocks: int = 16,
                          block_size: int = 8) -> dict:
    """Measured logit-error bound of a quantized KV pool vs the f32
    reference: run the SAME prompt + greedy continuation through two
    pools (f32 and ``kv_dtype``), feeding the f32 path's tokens to both
    so the trajectories stay aligned, and track the worst absolute
    logit difference and whether any argmax flipped. This is the
    number the README's KV-dtype table documents and ``bench.py
    --serving --kv-dtype int8`` stamps into its row."""
    from distributed_tensorflow_tpu.serving.kv_cache import (
        BlockAllocator, BlockTable, CacheConfig, init_pool)

    prompt = [int(t) for t in prompt]
    params = canonical_params(cfg, params)
    params = jax.tree_util.tree_map(jnp.asarray, dict(params))
    max_err = 0.0
    argmax_flips = 0
    cfgs = {
        "ref": CacheConfig.for_model(cfg, num_blocks=num_blocks,
                                     block_size=block_size,
                                     kv_dtype="f32"),
        "q": CacheConfig.for_model(cfg, num_blocks=num_blocks,
                                   block_size=block_size,
                                   kv_dtype=kv_dtype),
    }
    state = {}
    for name, cc in cfgs.items():
        alloc = BlockAllocator(cc.num_blocks)
        table = BlockTable(cc, max_blocks=cc.usable_blocks)
        table.ensure_room(len(prompt) + n_steps + 1, alloc)
        pool = init_pool(cc)
        prefill = jax.jit(make_prefill_fn(cfg, cc))
        decode = jax.jit(make_decode_fn(cfg, cc))
        toks = np.asarray([prompt], np.int32)
        rows = table.rows(np.arange(len(prompt)))[None]
        last, pool = prefill(params, pool, jnp.asarray(toks),
                             jnp.asarray([len(prompt)], np.int32),
                             jnp.asarray(rows))
        table.length = len(prompt)
        state[name] = (table, pool, decode, np.asarray(last[0]))
    ref_logits = state["ref"][3]
    q_logits = state["q"][3]
    max_err = float(np.max(np.abs(ref_logits - q_logits)))
    argmax_flips += int(np.argmax(ref_logits) != np.argmax(q_logits))
    token = int(np.argmax(ref_logits))       # f32 path drives both
    for _ in range(n_steps):
        outs = {}
        for name in ("ref", "q"):
            table, pool, decode, _ = state[name]
            pos = table.length
            table.length += 1
            logits, pool = decode(
                params, pool, jnp.asarray([token], np.int32),
                jnp.asarray([pos], np.int32),
                jnp.asarray([pos + 1], np.int32),
                jnp.asarray([table.row_of(pos)], np.int32),
                jnp.asarray(table.window_rows()[None]))
            outs[name] = np.asarray(logits[0])
            state[name] = (table, pool, decode, outs[name])
        max_err = max(max_err,
                      float(np.max(np.abs(outs["ref"] - outs["q"]))))
        argmax_flips += int(np.argmax(outs["ref"])
                            != np.argmax(outs["q"]))
        token = int(np.argmax(outs["ref"]))
    return {"kv_dtype": kv_dtype, "max_abs_logit_err": max_err,
            "argmax_flips": argmax_flips,
            "positions_checked": n_steps + 1}


def param_shardings(cfg: TransformerConfig, mesh):
    """NamedShardings for the canonical (stacked-layers) serving
    parameter tree from the SAME logical-axis metadata training uses
    (``LOGICAL_AXIS_RULES`` restricted to the serving mesh):
    heads/mlp/vocab over ``tp``, everything else replicated (a dp×tp
    serving mesh has no fsdp axis, so ``embed``'s fsdp rule maps to
    None)."""
    import dataclasses

    from flax.linen import partitioning as nn_partitioning
    from jax.sharding import NamedSharding, PartitionSpec as P

    # scan_layers=True yields the stacked "layers" tree directly — the
    # canonical layout — with the leading layer axis already unsharded
    # (the "layers" logical axis maps to None).
    shape_cfg = dataclasses.replace(cfg, scan_layers=True, mesh=None)
    model = TransformerLM(shape_cfg)
    rules = mesh_axis_rules(mesh)
    tokens = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
    with nn_partitioning.axis_rules(list(rules)):
        var_shapes = jax.eval_shape(
            lambda r: model.init(r, tokens), jax.random.PRNGKey(0))
        logical = nn_partitioning.get_axis_names(var_shapes["params_axes"])
        mesh_specs = nn_partitioning.logical_to_mesh(logical)
    shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), mesh_specs,
        is_leaf=lambda x: isinstance(x, P))
    return _plain(shardings)
