"""Multi-host sharded inference: KV-cache decode, continuous batching,
supervised serving replicas.

The serving stack over the trained sharded models (ROADMAP item 1 —
"serves heavy traffic from millions of users"):

- :mod:`kv_cache`  — block-allocated KV pool (PagedAttention-style
  fixed-size blocks; mixed-length requests share one batch; finished
  sequences free blocks immediately), head axis sharded over ``tp``.
- :mod:`decode`    — compiled prefill (full forward over a right-padded
  mixed-length batch, masked by the factored
  ``ops.attention.length_valid_mask`` rule) and one-token incremental
  decode over the block windows; greedy decode through the cache
  matches argmax over full-sequence recompute (tests/test_serving.py).
- :mod:`scheduler` — Orca-style continuous batching: admission queue,
  step-boundary admission under a token budget, newest-first preemption
  back to the queue when the pool runs dry.
- :mod:`engine`    — :class:`~distributed_tensorflow_tpu.serving.engine.
  InferenceEngine`: weights restored down the checkpoint recovery
  ladder, ``serve.step``/``serve.request`` telemetry, the ``serve.step``
  chaos site.
- :mod:`replica`   — the supervised replica worker function: heartbeats
  like a trainer (the recovery supervisor restarts a dead serving
  replica exactly like a dead trainer) and re-queues in-flight requests
  across restarts via its completion log (zero dropped requests).

Serving-speed optimisations (ISSUE 14, all output-invariant, all off
by default): copy-on-write **prefix caching**
(``InferenceEngine(prefix_caching=True)`` — committed prompt prefixes
shared across requests, refcounted in :class:`BlockAllocator`, indexed
by :class:`PrefixCache`), **speculative decoding**
(``speculative_k=k`` — draft-then-verify in one cache-aware forward,
greedy outputs exactly equal non-speculative), and a **quantized KV
pool** (``kv_dtype="bf16"|"int8"`` — 2-3.8x the servable slots per
chip; ``kv_quantization_probe`` measures the logit-error bound).

Disaggregated serving (ISSUE 16): :mod:`migrate` ships a sequence's
live KV blocks between replicas over the write-once chunked blob
transport — :class:`DisaggregatedEngine` splits prefill from decode
(a prefill burst stops blowing decode p99), drain mode ``migrate``
hands live sequences to a successor with zero replay, and cold
prefix-cache blocks spill to a :class:`HostTier` and re-adopt on hit
(``InferenceEngine(spill_tier=...)``). Greedy outputs stay
byte-identical to the monolithic engine throughout.

Multi-tenant frontend (ISSUE 20): :mod:`router` + :mod:`tenancy` put a
crash-tolerant, tenant-aware router in front of the replica fleet —
prefix-cache-affinity routing (falling back to least-loaded by scraped
queue depth, measured against random), weighted-fair admission with
priority classes (interactive ahead of batch, batch aged past its
starvation deadline promoted), per-tenant token-bucket quotas + SLO
burn windows, and a line-buffered decision journal that re-routes a
killed replica's in-flight work and survives a router kill without
double-serving. Chaos: ``python tools/chaos_sweep.py --router``.

Quick start::

    from distributed_tensorflow_tpu import serving

    engine = serving.InferenceEngine(cfg, params, mesh=mesh)
    engine.submit(serving.Request(id="a", tokens=prompt, max_new_tokens=32))
    while not engine.scheduler.idle:
        for done in engine.step():
            print(done["id"], done["tokens"])

Bench: ``python bench.py --serving`` (p50/p99 latency + tokens/s at a
target QPS); chaos: ``python tools/chaos_sweep.py --serve``.
"""

from distributed_tensorflow_tpu.serving.engine import InferenceEngine
from distributed_tensorflow_tpu.serving.kv_cache import (
    BlockAllocator,
    BlockTable,
    CacheConfig,
    HostTier,
    OutOfBlocksError,
    PrefixCache,
    init_pool,
    pool_shardings,
)
from distributed_tensorflow_tpu.serving.migrate import (
    DisaggregatedEngine,
    FileKV,
    MigrationPayload,
    fetch_payload,
    pack_payload,
    publish_payload,
    unpack_payload,
)
from distributed_tensorflow_tpu.serving.scheduler import (
    AdmissionQueue,
    ContinuousBatchingScheduler,
    QueueOverflowError,
    Request,
    Sequence,
)
from distributed_tensorflow_tpu.serving.decode import (
    canonical_params,
    kv_quantization_probe,
    make_decode_fn,
    make_draft_fn,
    make_extend_fn,
    make_prefill_fn,
    model_forward,
    param_shardings,
    truncated_draft,
)
from distributed_tensorflow_tpu.serving.replica import (
    completed_ids,
    seeded_requests,
    serving_replica,
)
from distributed_tensorflow_tpu.serving.router import (
    Router,
    RouterJournal,
    RoutingPolicy,
    prefix_chain_keys,
    seeded_tenant_workload,
)
from distributed_tensorflow_tpu.serving.tenancy import (
    TenancyController,
    TenantConfig,
    TokenBucket,
    default_tenants,
    evaluate_tenants,
    fair_shares,
)

__all__ = [
    "InferenceEngine",
    "BlockAllocator", "BlockTable", "CacheConfig", "HostTier",
    "OutOfBlocksError", "PrefixCache", "init_pool", "pool_shardings",
    "DisaggregatedEngine", "FileKV", "MigrationPayload",
    "fetch_payload", "pack_payload", "publish_payload", "unpack_payload",
    "AdmissionQueue", "ContinuousBatchingScheduler", "QueueOverflowError",
    "Request", "Sequence",
    "canonical_params", "kv_quantization_probe", "make_decode_fn",
    "make_draft_fn", "make_extend_fn", "make_prefill_fn",
    "model_forward", "param_shardings", "truncated_draft",
    "completed_ids", "seeded_requests", "serving_replica",
    "Router", "RouterJournal", "RoutingPolicy", "prefix_chain_keys",
    "seeded_tenant_workload",
    "TenancyController", "TenantConfig", "TokenBucket",
    "default_tenants", "evaluate_tenants", "fair_shares",
]
