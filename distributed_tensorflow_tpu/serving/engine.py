"""The inference engine: sharded model + KV cache + continuous batching.

One :class:`InferenceEngine` is one serving replica's model runtime:

- **Weights** — passed in directly, or restored down the checkpoint
  recovery ladder via :meth:`from_checkpoint`
  (``CheckpointManager.restore_latest``: host snapshot > peer replica >
  local disk > durable disk — a restarted serving replica warm-starts
  from the same tiers a restarted trainer does).
- **Placement** — on a ``dp×tp`` mesh the decode batch's slots shard
  over ``dp`` and heads/mlp/vocab shard over ``tp`` using the SAME
  logical-axis rules as training (serving/decode.param_shardings); the
  KV pool's head axis follows (kv_cache.pool_shardings). Single-device
  when ``mesh=None``.
- **Stepping** — :meth:`step` is one continuous-batching iteration:
  retire finished sequences (free their blocks), admit from the queue
  under the token budget, prefill the newly admitted, decode one token
  for every running sequence. Greedy (argmax) sampling — the decode
  path's output is exactly comparable to full-sequence recompute.
- **Telemetry** — every step is a ``serve.step`` span; every completed
  request emits a ``serve.request`` event whose ``dur_s`` is the
  queue→completion latency (both render in tools/obs_report.py and as
  spans in tools/trace_report.py). Instruments live under the shared
  ``inference/`` namespace (the one ``Model.predict`` also reports
  into) plus ``serving/`` for engine-specific gauges.
- **Per-request tracing** — every request's lifecycle events
  (``serve.admit`` → ``serve.prefill`` → per-token ``serve.token`` →
  ``serve.request``) share a deterministic ``request_span_id`` derived
  from the request id, so the trace assembler links them with flow
  arrows — ACROSS preemption replays and replica restarts (a restarted
  incarnation re-serving the same id emits the same span id, so one
  request's whole story threads through both generations' tracks).
  Serving steps also feed the live goodput ledger
  (telemetry/goodput.py) when one is active, with replayed tokens
  priced as ``preempt_replay`` badput.
- **Chaos** — each step fires the ``serve.step`` injection site
  (resilience/faults.py) BEFORE mutating any scheduler state, so an
  injected failure is retryable: the replica runtime catches it and
  re-runs the step; no request is lost.
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.telemetry import goodput as _goodput
from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM)
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.serving import decode as decode_lib
from distributed_tensorflow_tpu.serving.kv_cache import (
    CacheConfig, init_pool, pool_shardings)
from distributed_tensorflow_tpu.serving.scheduler import (
    AdmissionQueue, ContinuousBatchingScheduler, Request, Sequence)
from distributed_tensorflow_tpu.utils.jax_compat import (
    safe_donate_argnums)


def request_span_id(request_id: str) -> str:
    """Deterministic per-request trace span id. Derived from the
    request id alone so every lifecycle event of one request — across
    preemption replays, across replica generations — carries the SAME
    id and the trace assembler threads them with flow arrows."""
    return f"req/{request_id}"


class InferenceEngine:
    """Continuous-batching inference over a sharded transformer.

    ``max_slots`` is the decode batch width (compiled shape; on a mesh
    it must divide the dp shard count), ``max_prompt_len`` the compiled
    prefill width, ``num_blocks``/``block_size`` size the KV pool, and
    ``token_budget`` caps prefill+decode tokens per step (Orca-style
    iteration-level fairness). ``max_seq_len`` bounds prompt+generation
    per sequence (default: the model's ``max_seq_len``)."""

    def __init__(self, cfg: TransformerConfig, params, *, mesh=None,
                 num_blocks: int = 64, block_size: int = 16,
                 max_slots: int = 8, max_prompt_len: int | None = None,
                 token_budget: int | None = None,
                 max_seq_len: int | None = None,
                 queue_capacity: int = 256,
                 queue_policy: str = "reject",
                 cache_dtype=None):
        if cfg.mesh is not None:
            import dataclasses
            cfg = dataclasses.replace(cfg, mesh=None)
        self.cfg = cfg
        self.mesh = mesh
        self.max_slots = max_slots
        self.max_seq_len = min(max_seq_len or cfg.max_seq_len,
                               cfg.max_seq_len)
        self.max_prompt_len = min(max_prompt_len or self.max_seq_len,
                                  self.max_seq_len)
        self.token_budget = token_budget or (max_slots
                                             + self.max_prompt_len)
        cache_cfg = CacheConfig.for_model(cfg, num_blocks=num_blocks,
                                          block_size=block_size,
                                          dtype=cache_dtype)
        max_blocks_per_seq = cache_cfg.blocks_for(self.max_seq_len)
        self.cache_cfg = cache_cfg
        self.window = max_blocks_per_seq * block_size
        self.scheduler = ContinuousBatchingScheduler(
            cache_cfg, max_slots=max_slots,
            max_blocks_per_seq=max_blocks_per_seq,
            token_budget=self.token_budget,
            queue=AdmissionQueue(queue_capacity, queue_policy))

        params = decode_lib.canonical_params(cfg, params)
        if mesh is not None:
            shardings = decode_lib.param_shardings(cfg, mesh)
            params = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(jnp.asarray(a), s),
                dict(params), shardings)
        else:
            params = jax.tree_util.tree_map(jnp.asarray, dict(params))
        self.params = params
        self.pool = init_pool(cache_cfg, mesh)

        prefill = decode_lib.make_prefill_fn(cfg)
        decode = decode_lib.make_decode_fn(cfg) if cfg.causal else None
        if mesh is not None:
            # jit under the mesh context so GSPMD partitions over it;
            # inputs arrive host-side and get sharded by in_shardings
            from jax.sharding import NamedSharding, PartitionSpec as P
            dp = "dp" if "dp" in mesh.shape else None
            pool_sh = pool_shardings(mesh)
            rep = NamedSharding(mesh, P())
            slotv = NamedSharding(mesh, P(dp))
            self._prefill = jax.jit(
                prefill,
                in_shardings=(shardings, pool_sh, pool_sh, rep, rep, rep),
                out_shardings=(rep, pool_sh, pool_sh),
                donate_argnums=safe_donate_argnums((1, 2)))
            self._decode = jax.jit(
                decode,
                in_shardings=(shardings, pool_sh, pool_sh, slotv, slotv,
                              slotv, slotv,
                              NamedSharding(mesh, P(dp, None))),
                out_shardings=(NamedSharding(mesh, P(dp, None)),
                               pool_sh, pool_sh),
                donate_argnums=safe_donate_argnums((1, 2))) if decode is not None else None
        else:
            self._prefill = jax.jit(prefill, donate_argnums=safe_donate_argnums((1, 2)))
            self._decode = (jax.jit(decode, donate_argnums=safe_donate_argnums((1, 2)))
                            if decode is not None else None)

        # shared inference namespace (Model.predict reports here too)
        reg = telemetry.get_registry()
        self._m_req_latency = reg.histogram(
            "inference/request_latency",
            "admission -> completion seconds per serving request")
        self._m_ttft = reg.histogram(
            "inference/time_to_first_token",
            "admission -> first generated token seconds")
        self._m_completed = reg.counter("inference/requests_completed")
        self._m_tokens = reg.counter("inference/tokens_generated")
        self._m_replayed = reg.counter(
            "inference/tokens_replayed",
            "tokens re-generated after preemption/restart (badput)")
        self._m_step = reg.histogram("serving/step_time",
                                     "one continuous-batching iteration")
        self._m_running = reg.gauge("serving/sequences_running")
        self._m_queued = reg.gauge("serving/requests_queued")
        self._m_blocks_free = reg.gauge("serving/blocks_free")
        self._m_preempt = reg.counter("serving/preemptions")

        self._step_idx = 0
        self._submitted: dict[str, float] = {}      # id -> wall arrival
        self._submit_mono: dict[str, float] = {}    # id -> mono arrival

    # -- weights -----------------------------------------------------------
    @classmethod
    def from_checkpoint(cls, cfg: TransformerConfig, directory: str, *,
                        checkpoint_name: str = "ckpt",
                        local_dir: str | None = None,
                        snapshot_store=None, seed: int = 0,
                        **engine_kwargs) -> "InferenceEngine":
        """Restore serving weights down the recovery ladder. The
        checkpoint must have been written as ``Checkpoint(params=...)``
        over a ``TransformerLM(cfg)`` parameter tree; ``local_dir`` /
        ``snapshot_store`` enable the warm tiers exactly as they do for
        trainers (CheckpointManager.restore_latest walks host > peer >
        local > durable and emits ``recovery.restore_tier``). With
        nothing restorable anywhere, falls back to seed-deterministic
        fresh init (cold start)."""
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint, CheckpointManager)
        from distributed_tensorflow_tpu.training.model import (
            _unflatten_like)

        model = TransformerLM(cfg)
        tokens = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
        params = model.init(jax.random.PRNGKey(seed), tokens)["params"]
        params = (params.unfreeze() if hasattr(params, "unfreeze")
                  else dict(params))
        ckpt = Checkpoint(params=params)
        mgr = CheckpointManager(ckpt, directory,
                                checkpoint_name=checkpoint_name,
                                local_dir=local_dir,
                                snapshot_store=snapshot_store)
        res = mgr.restore_latest()
        if res is not None:
            _tier, _step, flat = res
            params = _unflatten_like(params, flat, "params")
        return cls(cfg, params, **engine_kwargs)

    # -- request lifecycle -------------------------------------------------
    def submit(self, request: Request, *,
               arrival_wall: "float | None" = None) -> "Request | None":
        """Queue a request; returns the request the queue evicted to
        make room (policy ``evict_oldest``), if any. Raises
        ``QueueOverflowError`` under the ``reject`` policy.

        ``arrival_wall`` backdates the latency clock to the request's
        TRUE arrival (an open-loop timed workload — or a restarted
        replica re-serving backlog whose original arrival predates this
        incarnation): the ``serve.request`` latency then honestly
        includes the queueing the client experienced, so SLO burn can
        not be reset by a restart."""
        if len(request.tokens) > self.max_prompt_len:
            raise ValueError(
                f"request {request.id}: prompt {len(request.tokens)} > "
                f"max_prompt_len {self.max_prompt_len}")
        if not self.cfg.causal and request.max_new_tokens > 0:
            raise ValueError(
                f"request {request.id}: bidirectional (non-causal) "
                f"configs serve scoring requests only "
                f"(max_new_tokens=0)")
        if (len(request.tokens) + request.max_new_tokens
                > self.max_seq_len):
            raise ValueError(
                f"request {request.id}: prompt + max_new_tokens "
                f"exceeds max_seq_len {self.max_seq_len}")
        evicted = self.scheduler.queue.submit(request)
        self._submitted[request.id] = (arrival_wall
                                       if arrival_wall is not None
                                       else time.time())
        self._submit_mono[request.id] = time.monotonic()
        if evicted is not None:
            self._submitted.pop(evicted.id, None)
            self._submit_mono.pop(evicted.id, None)
        self._m_queued.set(len(self.scheduler.queue))
        telemetry.event("serve.admit", id=request.id,
                        span_id=request_span_id(request.id),
                        prompt_tokens=len(request.tokens),
                        queued=len(self.scheduler.queue))
        return evicted

    def _prefill_one(self, seq: Sequence):
        """Run one admitted sequence's prompt through the compiled
        prefill (fixed (1, max_seq_len) shape — wider than
        max_prompt_len so a PREEMPTED sequence's replayed prompt, which
        includes its already-generated tokens, always fits) and bank its
        first greedy token."""
        rid = seq.request.id
        submit_mono = self._submit_mono.get(rid)
        queue_wait = (seq.admitted_s - submit_mono
                      if submit_mono is not None else None)
        with telemetry.span(
                "serve.prefill", id=rid, span_id=request_span_id(rid),
                prompt_tokens=seq.prompt_len,
                queue_wait_s=(round(queue_wait, 6)
                              if queue_wait is not None else None),
                replayed=len(seq.request.generated_prefix) or None):
            P = self.max_seq_len
            toks = np.zeros((1, P), np.int32)
            toks[0, :seq.prompt_len] = seq.request.tokens
            rows = seq.table.rows(np.arange(P))[None]       # (1, P)
            lengths = np.asarray([seq.prompt_len], np.int32)
            last, self.pool["k"], self.pool["v"] = self._prefill(
                self.params, self.pool["k"], self.pool["v"],
                jnp.asarray(toks), jnp.asarray(lengths),
                jnp.asarray(rows))
            self.scheduler.commit_prefill(seq)
            first = int(np.asarray(jnp.argmax(last[0])))
        if seq.request.max_new_tokens > 0:
            self.scheduler.append_token(seq, first)
        else:
            seq.first_token_s = time.monotonic()
            seq.score_token = first                    # scoring request

    def _decode_batch(self, batch: list[Sequence]):
        """One incremental token for every running sequence. The decode
        program has a fixed (max_slots,) batch; idle slots feed trash
        rows with length 0 and their logits are never read."""
        B, W = self.max_slots, self.window
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        lengths = np.zeros(B, np.int32)
        write_rows = np.zeros(B, np.int32)     # trash block row 0
        window_rows = np.zeros((B, W), np.int32)
        for seq in batch:
            s = seq.slot
            # feed the last banked token at position length-1 (it was
            # appended by the previous prefill/decode step)
            tokens[s] = seq.last_token
            positions[s] = seq.length - 1
            lengths[s] = seq.length
            write_rows[s] = seq.table.row_of(seq.length - 1)
            window_rows[s] = seq.table.window_rows()
        logits, self.pool["k"], self.pool["v"] = self._decode(
            self.params, self.pool["k"], self.pool["v"],
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(lengths), jnp.asarray(write_rows),
            jnp.asarray(window_rows))
        nxt = np.asarray(jnp.argmax(logits, axis=-1))
        emit = telemetry.enabled()
        for seq in batch:
            self.scheduler.append_token(seq, int(nxt[seq.slot]))
            if emit:
                # per-token decode breadcrumb on the request's span:
                # index counts generated tokens ACROSS preemptions (the
                # replayed prefix included), so a re-served request's
                # token trail lines up generation-to-generation
                rid = seq.request.id
                telemetry.event(
                    "serve.token", id=rid,
                    span_id=request_span_id(rid),
                    index=(len(seq.request.generated_prefix)
                           + len(seq.generated)),
                    step=self._step_idx)

    def step(self) -> list[dict]:
        """One continuous-batching iteration; returns completion records
        for every request finished this step."""
        t0 = time.monotonic()
        # chaos site FIRST: an injected raise leaves scheduler/cache
        # state untouched, so the caller can simply retry the step
        faults.fire("serve.step", tag=self._step_idx)
        sched = self.scheduler
        finished: list[dict] = []
        with telemetry.span("serve.step", step=self._step_idx) as sp:
            # 1. retire finished sequences -> blocks free immediately
            for seq in list(sched.finished()):
                finished.append(self._complete(seq))
            admitted = sched.admit()
            for seq in admitted:
                self._prefill_one(seq)
            # scoring requests (max_new_tokens=0) finish at prefill
            for seq in list(sched.finished()):
                finished.append(self._complete(seq))
            batch = sched.grow_for_decode() if self._decode else []
            if batch:
                self._decode_batch(batch)
            sp["admitted"] = len(admitted)
            sp["decoded"] = len(batch)
            sp["finished"] = len(finished)
            sp["queued"] = len(sched.queue)
            sp["blocks_free"] = sched.allocator.num_free
        self._step_idx += 1
        step_s = time.monotonic() - t0
        self._m_step.record(step_s)
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.serve_step(step_s)
        self._m_running.set(len(sched.running))
        self._m_queued.set(len(sched.queue))
        self._m_blocks_free.set(sched.allocator.num_free)
        if sched.preemptions > self._m_preempt.value:
            self._m_preempt.increment(
                sched.preemptions - self._m_preempt.value)
        return finished

    def _complete(self, seq: Sequence) -> dict:
        self.scheduler.finish(seq)
        req = seq.request
        now = time.time()
        arrival = self._submitted.pop(req.id, now)
        self._submit_mono.pop(req.id, None)
        latency = max(0.0, now - arrival)
        ttft = ((seq.first_token_s - seq.admitted_s)
                if seq.first_token_s is not None else None)
        generated = list(req.generated_prefix) + list(seq.generated)
        tokens = (generated if (req.max_new_tokens > 0
                                or req.generated_prefix)
                  else [getattr(seq, "score_token", -1)])
        prompt_tokens = len(req.tokens) - len(req.generated_prefix)
        replayed = len(req.generated_prefix)
        self._m_req_latency.record(latency)
        if ttft is not None:
            self._m_ttft.record(ttft)
        self._m_completed.increment()
        self._m_tokens.increment(len(seq.generated))
        if replayed:
            self._m_replayed.increment(replayed)
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.tokens(fresh=len(seq.generated), replayed=replayed)
        telemetry.event(
            "serve.request", id=req.id, dur_s=round(latency, 6),
            span_id=request_span_id(req.id),
            prompt_tokens=prompt_tokens, new_tokens=len(generated),
            replayed_tokens=replayed,
            ttft_s=round(ttft, 6) if ttft is not None else None,
            preemptions=seq.preemptions)
        return {"id": req.id, "tokens": tokens,
                "prompt_tokens": prompt_tokens,
                "latency_s": latency, "ttft_s": ttft,
                "replayed_tokens": replayed,
                "preemptions": seq.preemptions}

    # -- convenience -------------------------------------------------------
    def run_until_idle(self, *, max_steps: int = 100000,
                       retry_faults: bool = False) -> dict:
        """Drive :meth:`step` until queue and slots drain; returns
        ``{request_id: completion record}``. ``retry_faults=True``
        re-runs a step whose ``serve.step`` chaos site raised (the
        replica runtime's behavior)."""
        from distributed_tensorflow_tpu.resilience.faults import (
            FaultInjected)
        out: dict[str, dict] = {}
        for _ in range(max_steps):
            if self.scheduler.idle:
                break
            try:
                for rec in self.step():
                    out[rec["id"]] = rec
            except FaultInjected:
                if not retry_faults:
                    raise
        return out

    def generate(self, prompts, *, max_new_tokens: int = 16,
                 eos_id: int | None = None) -> list[list[int]]:
        """Batch convenience: greedy-decode ``prompts`` (lists of token
        ids) through the continuous-batching path; returns the generated
        token lists in prompt order."""
        for i, p in enumerate(prompts):
            self.submit(Request(id=f"g{i}", tokens=tuple(p),
                                max_new_tokens=max_new_tokens,
                                eos_id=eos_id))
        done = self.run_until_idle()
        return [done[f"g{i}"]["tokens"] for i in range(len(prompts))]

    def stats(self) -> dict:
        sched = self.scheduler
        return {
            "steps": self._step_idx,
            "running": len(sched.running),
            "queued": len(sched.queue),
            "blocks_free": sched.allocator.num_free,
            "blocks_total": self.cache_cfg.usable_blocks,
            "preemptions": sched.preemptions,
            "queue_rejected": sched.queue.rejected,
            "queue_evicted": sched.queue.evicted,
            "requests_completed": self._m_completed.value,
            "tokens_generated": self._m_tokens.value,
            "tokens_replayed": self._m_replayed.value,
            "serve_time_s": self._m_step.export().get("sum", 0.0),
        }
