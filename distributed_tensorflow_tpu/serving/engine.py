"""The inference engine: sharded model + KV cache + continuous batching.

One :class:`InferenceEngine` is one serving replica's model runtime:

- **Weights** — passed in directly, or restored down the checkpoint
  recovery ladder via :meth:`from_checkpoint`
  (``CheckpointManager.restore_latest``: host snapshot > peer replica >
  local disk > durable disk — a restarted serving replica warm-starts
  from the same tiers a restarted trainer does).
- **Placement** — on a ``dp×tp`` mesh the decode batch's slots shard
  over ``dp`` and heads/mlp/vocab shard over ``tp`` using the SAME
  logical-axis rules as training (serving/decode.param_shardings); the
  KV pool's head axis follows (kv_cache.pool_shardings). Single-device
  when ``mesh=None``.
- **Stepping** — :meth:`step` is one continuous-batching iteration:
  retire finished sequences (free their blocks), admit from the queue
  under the token budget, prefill the newly admitted, decode one token
  for every running sequence. Greedy (argmax) sampling — the decode
  path's output is exactly comparable to full-sequence recompute.
- **Telemetry** — every step is a ``serve.step`` span; every completed
  request emits a ``serve.request`` event whose ``dur_s`` is the
  queue→completion latency (both render in tools/obs_report.py and as
  spans in tools/trace_report.py). Instruments live under the shared
  ``inference/`` namespace (the one ``Model.predict`` also reports
  into) plus ``serving/`` for engine-specific gauges.
- **Per-request tracing** — every request's lifecycle events
  (``serve.admit`` → ``serve.prefill`` → per-token ``serve.token`` →
  ``serve.request``) share a deterministic ``request_span_id`` derived
  from the request id, so the trace assembler links them with flow
  arrows — ACROSS preemption replays and replica restarts (a restarted
  incarnation re-serving the same id emits the same span id, so one
  request's whole story threads through both generations' tracks).
  Serving steps also feed the live goodput ledger
  (telemetry/goodput.py) when one is active, with replayed tokens
  priced as ``preempt_replay`` badput.
- **Chaos** — each step fires the ``serve.step`` injection site
  (resilience/faults.py) BEFORE mutating any scheduler state, so an
  injected failure is retryable: the replica runtime catches it and
  re-runs the step; no request is lost.

Three serving-speed optimisations stack on the same step loop, each
off by default and each OUTPUT-INVARIANT (greedy tokens are identical
with the feature on or off — the regression contract
tests/test_serving_speed.py pins):

- **Prefix caching** (``prefix_caching=True``) — committed prompt
  prefixes are content-indexed in the scheduler's
  :class:`~distributed_tensorflow_tpu.serving.kv_cache.PrefixCache`;
  a later request whose prompt hash-matches adopts the cached blocks
  (refcounted) and prefill runs ONLY over the unmatched suffix through
  the multi-token ``extend`` program. Shared blocks are copied-on-write
  before any divergent append; eviction is LRU over cached blocks no
  sequence references. Cache hits shrink the serve-step share of the
  goodput ledger automatically (smaller prefill = less serve time for
  the same tokens).
- **Speculative decoding** (``speculative_k=k`` with a small draft
  model, default the target's own first half of layers —
  ``decode.truncated_draft``) — the draft proposes k greedy tokens per
  slot, the target verifies all k+1 positions in ONE cache-aware
  ``extend`` forward, the longest agreeing prefix commits (plus the
  target's own next token), and the first rejection truncates. Greedy
  outputs equal non-speculative decode exactly; ``accepted_draft_rate``
  in :meth:`stats` says how much of the draft's work survived.
- **Quantized KV cache** (``kv_dtype="bf16"``/``"int8"``) — the pool
  stores quantized K/V (int8 with per-(row, head) f32 scales),
  quantize-on-write/dequantize-on-gather inside the compiled programs,
  multiplying servable slots per chip
  (``CacheConfig.bytes_per_token``); greedy parity holds on short
  sequences, with a measured logit-error bound
  (``decode.kv_quantization_probe``) documented in the README.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.telemetry import goodput as _goodput
from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM)
from distributed_tensorflow_tpu.resilience import faults
from distributed_tensorflow_tpu.serving import decode as decode_lib
from distributed_tensorflow_tpu.serving.kv_cache import (
    CacheConfig, HostTier, init_pool, pool_shardings)
from distributed_tensorflow_tpu.serving.scheduler import (
    AdmissionQueue, ContinuousBatchingScheduler, OutOfBlocksError,
    Request, Sequence)
from distributed_tensorflow_tpu.utils.jax_compat import (
    safe_donate_argnums)

_pool_epochs = itertools.count()


def request_span_id(request_id: str) -> str:
    """Deterministic per-request trace span id. Derived from the
    request id alone so every lifecycle event of one request — across
    preemption replays, across replica generations — carries the SAME
    id and the trace assembler threads them with flow arrows."""
    return f"req/{request_id}"


def migrate_span_id(request_id: str) -> str:
    """Span id shared by BOTH halves of one KV migration — the source's
    export and the destination's adopt — so the merged trace renders a
    flow arrow prefill→decode (or victim→survivor for drain/rescue)."""
    return f"kvmig/{request_id}"


def params_digest(params) -> str:
    """Content digest of a parameter tree: crc32 over the tree
    structure plus every leaf's raw bytes, host-fetched. Two engines
    serving byte-identical weights get the same digest regardless of
    how the weights arrived (fresh init, restore tier, hot-swap) — the
    content half of the ``weights_version`` identity stamped on every
    serving event."""
    leaves, treedef = jax.tree_util.tree_flatten(params)
    crc = zlib.crc32(repr(treedef).encode())
    for leaf in leaves:
        a = np.ascontiguousarray(np.asarray(jax.device_get(leaf)))
        crc = zlib.crc32(a.tobytes(), crc)
    return f"{crc & 0xFFFFFFFF:08x}"


class InferenceEngine:
    """Continuous-batching inference over a sharded transformer.

    ``max_slots`` is the decode batch width (compiled shape; on a mesh
    it must divide the dp shard count), ``max_prompt_len`` the compiled
    prefill width, ``num_blocks``/``block_size`` size the KV pool, and
    ``token_budget`` caps prefill+decode tokens per step (Orca-style
    iteration-level fairness). ``max_seq_len`` bounds prompt+generation
    per sequence (default: the model's ``max_seq_len``).

    Serving-speed knobs (module docstring has the semantics; all
    output-invariant): ``prefix_caching=True`` shares committed prompt
    prefixes across requests; ``speculative_k=k`` drafts k tokens per
    slot and verifies them in one forward (``draft_params``/
    ``draft_cfg`` override the default truncated-target draft);
    ``kv_dtype`` in {"f32", "bf16", "int8"} picks the pool's storage
    dtype (``cache_dtype`` remains the raw-dtype spelling)."""

    def __init__(self, cfg: TransformerConfig, params, *, mesh=None,
                 num_blocks: int = 64, block_size: int = 16,
                 max_slots: int = 8, max_prompt_len: int | None = None,
                 token_budget: int | None = None,
                 max_seq_len: int | None = None,
                 queue_capacity: int = 256,
                 queue_policy: str = "reject",
                 cache_dtype=None, kv_dtype: str | None = None,
                 prefix_caching: bool = False,
                 speculative_k: int = 0,
                 draft_params=None, draft_cfg=None,
                 role: str = "both",
                 spill_tier: "HostTier | int | None" = None,
                 snapshot_step: int | None = None):
        if cfg.mesh is not None:
            import dataclasses
            cfg = dataclasses.replace(cfg, mesh=None)
        if role not in ("both", "prefill"):
            raise ValueError(f"role={role!r}; expected 'both' or "
                             f"'prefill'")
        self.cfg = cfg
        self.mesh = mesh
        #: "prefill" compiles no decode program: step() admits +
        #: prefills only, and the disaggregated runtime EXPORTS each
        #: prefilled sequence's KV to a decode replica (migrate.py).
        self.role = role
        #: fences host-tier spills and stale drain handoffs: unique per
        #: engine incarnation, never equal across restarts
        self.pool_epoch = f"{os.getpid()}-{next(_pool_epochs)}"
        self.max_slots = max_slots
        self.max_seq_len = min(max_seq_len or cfg.max_seq_len,
                               cfg.max_seq_len)
        self.max_prompt_len = min(max_prompt_len or self.max_seq_len,
                                  self.max_seq_len)
        self.token_budget = token_budget or (max_slots
                                             + self.max_prompt_len)
        cache_cfg = CacheConfig.for_model(cfg, num_blocks=num_blocks,
                                          block_size=block_size,
                                          dtype=cache_dtype,
                                          kv_dtype=kv_dtype)
        max_blocks_per_seq = cache_cfg.blocks_for(self.max_seq_len)
        self.cache_cfg = cache_cfg
        self.window = max_blocks_per_seq * block_size
        self.prefix_caching = bool(prefix_caching)
        self.scheduler = ContinuousBatchingScheduler(
            cache_cfg, max_slots=max_slots,
            max_blocks_per_seq=max_blocks_per_seq,
            token_budget=self.token_budget,
            queue=AdmissionQueue(queue_capacity, queue_policy),
            prefix_caching=self.prefix_caching)

        if speculative_k and not cfg.causal:
            raise ValueError("speculative decoding requires a causal "
                             "model")
        self.spec_k = int(speculative_k)
        self._draft_default = False
        if self.spec_k:
            if draft_params is None:
                # default draft: the target's own first half of layers
                # (free self-speculation; pass an explicit small model
                # for a real distilled draft) — re-derived from the new
                # weights on every hot-swap (install_version)
                self._draft_default = True
                draft_cfg, draft_params = decode_lib.truncated_draft(
                    cfg, params)
            elif draft_cfg is None:
                raise ValueError("draft_params requires draft_cfg")
            self._draft_cfg = draft_cfg
            self._draft_params = jax.tree_util.tree_map(
                jnp.asarray,
                dict(decode_lib.canonical_params(draft_cfg,
                                                 draft_params)))
            self._draft = decode_lib.make_draft_fn(draft_cfg)

        params = decode_lib.canonical_params(cfg, params)
        if mesh is not None:
            shardings = decode_lib.param_shardings(cfg, mesh)
            params = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(jnp.asarray(a), s),
                dict(params), shardings)
        else:
            params = jax.tree_util.tree_map(jnp.asarray, dict(params))
        self.params = params
        #: model-version identity: snapshot step (0 = direct params, no
        #: checkpoint provenance) + content digest. Stamped on every
        #: serve.prefill/serve.request event and rotated by
        #: install_version — the fence the prefix cache and the rollout
        #: controller both key on.
        self.weights_step = (int(snapshot_step)
                             if snapshot_step is not None else 0)
        self.weights_digest = params_digest(self.params)
        self.swaps = 0
        self.swap_error: BaseException | None = None
        # hot-swap provenance (set by from_checkpoint; load_version
        # needs both to rebuild a pinned CheckpointManager)
        self._version_source: dict | None = None
        self._params_template = None
        self._swap_thread: threading.Thread | None = None
        self._pending_swap = None
        self.pool = init_pool(cache_cfg, mesh)

        prefill = decode_lib.make_prefill_fn(cfg, cache_cfg)
        decode = (decode_lib.make_decode_fn(cfg, cache_cfg)
                  if cfg.causal and role != "prefill" else None)
        extend = (decode_lib.make_extend_fn(cfg, cache_cfg)
                  if cfg.causal else None)
        copy_fn = decode_lib.make_copy_fn()

        def gather_fn(pool, rows):
            return {n: pool[n][:, rows] for n in pool}

        def insert_fn(pool, rows, vals):
            return {n: pool[n].at[:, rows].set(vals[n]) for n in pool}
        if mesh is not None:
            # jit under the mesh context so GSPMD partitions over it;
            # inputs arrive host-side and get sharded by in_shardings
            from jax.sharding import NamedSharding, PartitionSpec as P
            dp = "dp" if "dp" in mesh.shape else None
            pool_sh = pool_shardings(mesh, cache_cfg)
            rep = NamedSharding(mesh, P())
            slotv = NamedSharding(mesh, P(dp))
            slotm = NamedSharding(mesh, P(dp, None))
            self._prefill = jax.jit(
                prefill,
                in_shardings=(shardings, pool_sh, rep, rep, rep),
                out_shardings=(rep, pool_sh),
                donate_argnums=safe_donate_argnums((1,)))
            self._decode = jax.jit(
                decode,
                in_shardings=(shardings, pool_sh, slotv, slotv,
                              slotv, slotv, slotm),
                out_shardings=(slotm, pool_sh),
                donate_argnums=safe_donate_argnums((1,))) \
                if decode is not None else None
            # the extend program serves two batch shapes: suffix
            # prefill is (1, E) — too narrow to shard over dp, so it
            # runs replicated like prefill — and speculative verify is
            # (max_slots, k+1), sharded over dp like decode
            self._extend_prefill = jax.jit(
                extend,
                in_shardings=(shardings, pool_sh, rep, rep, rep, rep,
                              rep),
                out_shardings=(rep, pool_sh),
                donate_argnums=safe_donate_argnums((1,))) \
                if extend is not None else None
            self._extend_spec = jax.jit(
                extend,
                in_shardings=(shardings, pool_sh, slotm, slotm, slotv,
                              slotm, slotm),
                out_shardings=(NamedSharding(mesh, P(dp, None, None)),
                               pool_sh),
                donate_argnums=safe_donate_argnums((1,))) \
                if extend is not None else None
            self._copy = jax.jit(
                copy_fn, in_shardings=(pool_sh, rep, rep),
                out_shardings=pool_sh,
                donate_argnums=safe_donate_argnums((0,)))
            # migration/spill row movers: gather block rows to a
            # replicated (host-fetchable) array, insert host rows into
            # the sharded pool. No donation on gather — the pool
            # survives an export.
            self._gather = jax.jit(gather_fn,
                                   in_shardings=(pool_sh, rep),
                                   out_shardings=rep)
            self._insert = jax.jit(
                insert_fn, in_shardings=(pool_sh, rep, rep),
                out_shardings=pool_sh,
                donate_argnums=safe_donate_argnums((0,)))
        else:
            self._prefill = jax.jit(
                prefill, donate_argnums=safe_donate_argnums((1,)))
            self._decode = (jax.jit(
                decode, donate_argnums=safe_donate_argnums((1,)))
                if decode is not None else None)
            self._extend_prefill = (jax.jit(
                extend, donate_argnums=safe_donate_argnums((1,)))
                if extend is not None else None)
            self._extend_spec = self._extend_prefill
            self._copy = jax.jit(
                copy_fn, donate_argnums=safe_donate_argnums((0,)))
            self._gather = jax.jit(gather_fn)
            self._insert = jax.jit(
                insert_fn, donate_argnums=safe_donate_argnums((0,)))

        # shared inference namespace (Model.predict reports here too)
        reg = telemetry.get_registry()
        self._m_req_latency = reg.histogram(
            "inference/request_latency",
            "admission -> completion seconds per serving request")
        self._m_ttft = reg.histogram(
            "inference/time_to_first_token",
            "admission -> first generated token seconds")
        self._m_completed = reg.counter("inference/requests_completed")
        self._m_tokens = reg.counter("inference/tokens_generated")
        self._m_replayed = reg.counter(
            "inference/tokens_replayed",
            "tokens re-generated after preemption/restart (badput)")
        self._m_step = reg.histogram("serving/step_time",
                                     "one continuous-batching iteration")
        self._m_running = reg.gauge("serving/sequences_running")
        self._m_queued = reg.gauge("serving/requests_queued")
        self._m_blocks_free = reg.gauge("serving/blocks_free")
        self._m_preempt = reg.counter("serving/preemptions")
        self._m_cached_tokens = reg.counter(
            "serving/prefix_cached_tokens",
            "prompt tokens served from the prefix cache (prefill "
            "skipped)")
        self._m_prompt_tokens = reg.counter(
            "serving/prefix_prompt_tokens",
            "prompt tokens submitted to prefix-cache lookup")
        self._m_cache_blocks = reg.gauge("serving/prefix_cache_blocks")
        self._m_spec_proposed = reg.counter(
            "serving/draft_tokens_proposed")
        self._m_spec_accepted = reg.counter(
            "serving/draft_tokens_accepted")
        self._m_model_version = reg.gauge(
            "serving/model_version",
            "snapshot step of the weights currently serving")
        self._m_model_version.set(self.weights_step)
        self._m_swaps = reg.counter(
            "serving/weight_swaps",
            "in-place weight hot-swaps completed")

        self._step_idx = 0
        self._submitted: dict[str, float] = {}      # id -> wall arrival
        self._submit_mono: dict[str, float] = {}    # id -> mono arrival
        # instance-local speculation tallies (the registry counters
        # above are process-wide and shared across engines)
        self._spec_proposed_n = 0
        self._spec_accepted_n = 0
        # instance-local migration tallies
        self.migrations_out = 0
        self.migrations_in = 0
        self.migrated_bytes = 0

        self.spill_tier: HostTier | None = None
        if spill_tier is not None and spill_tier is not False:
            if not self.prefix_caching:
                raise ValueError("spill_tier requires "
                                 "prefix_caching=True (the tier backs "
                                 "prefix-cache eviction)")
            tier = (spill_tier if isinstance(spill_tier, HostTier)
                    else HostTier(int(spill_tier)))
            bs = self.cache_cfg.block_size

            def _extract(block: int) -> dict:
                rows = jnp.arange(block * bs, (block + 1) * bs,
                                  dtype=jnp.int32)
                g = self._gather(self.pool, rows)
                return {n: np.asarray(jax.device_get(a))
                        for n, a in g.items()}

            def _insert_block(block: int, arrays: dict):
                rows = jnp.arange(block * bs, (block + 1) * bs,
                                  dtype=jnp.int32)
                vals = {n: jnp.asarray(a) for n, a in arrays.items()}
                self.pool = self._insert(self.pool, rows, vals)

            self.scheduler.prefix_cache.attach_spill(
                tier, extract=_extract, insert=_insert_block,
                epoch=self._cache_epoch())
            self.spill_tier = tier

    # -- weights -----------------------------------------------------------
    @property
    def weights_version(self) -> str:
        """``<step>@<digest>`` — the identity stamped on serving
        events; also the weights half of the prefix-cache epoch."""
        return f"{self.weights_step}@{self.weights_digest}"

    def _cache_epoch(self) -> str:
        """Spill/fence epoch = incarnation × weights version. A
        host-tier block survives ONLY while both halves match: a
        restart rotates pool_epoch (PR 16's fence), a hot-swap rotates
        weights_version — either way a stale block is dropped-and-
        counted at re-adoption, never served."""
        return f"{self.pool_epoch}/{self.weights_version}"

    @classmethod
    def from_checkpoint(cls, cfg: TransformerConfig, directory: str, *,
                        checkpoint_name: str = "ckpt",
                        local_dir: str | None = None,
                        snapshot_store=None, seed: int = 0,
                        at_step: int | None = None,
                        **engine_kwargs) -> "InferenceEngine":
        """Restore serving weights down the recovery ladder. The
        checkpoint must have been written as ``Checkpoint(params=...)``
        over a ``TransformerLM(cfg)`` parameter tree; ``local_dir`` /
        ``snapshot_store`` enable the warm tiers exactly as they do for
        trainers (CheckpointManager.restore_latest walks host > peer >
        local > durable and emits ``recovery.restore_tier``). With
        nothing restorable anywhere, falls back to seed-deterministic
        fresh init (cold start). ``at_step`` pin-restores an exact
        snapshot (rollback; raises loudly when that step is torn or
        pruned). The engine remembers its checkpoint source, so
        :meth:`load_version` can later hot-swap to any other step.

        A successful restore emits ``serve.swap`` with
        ``mode="restart"`` — the restart-adoption datapoint the
        update→servable freshness SLO closes on, so the respawn gap
        hot-swap removes is measured, not assumed."""
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint, CheckpointManager)
        from distributed_tensorflow_tpu.training.model import (
            _unflatten_like)

        t0 = time.monotonic()
        model = TransformerLM(cfg)
        tokens = jnp.zeros((1, min(8, cfg.max_seq_len)), jnp.int32)
        params = model.init(jax.random.PRNGKey(seed), tokens)["params"]
        params = (params.unfreeze() if hasattr(params, "unfreeze")
                  else dict(params))
        template = params
        ckpt = Checkpoint(params=params)
        mgr = CheckpointManager(ckpt, directory,
                                checkpoint_name=checkpoint_name,
                                local_dir=local_dir,
                                snapshot_store=snapshot_store)
        res = mgr.restore_latest(at_step=at_step)
        step = None
        if res is not None:
            _tier, step, flat = res
            params = _unflatten_like(template, flat, "params")
        eng = cls(cfg, params, snapshot_step=step, **engine_kwargs)
        eng._version_source = dict(directory=directory,
                                   checkpoint_name=checkpoint_name,
                                   local_dir=local_dir,
                                   snapshot_store=snapshot_store)
        eng._params_template = template
        if step is not None:
            telemetry.event(
                "serve.swap", step=step, version=eng.weights_version,
                previous=None, mode="restart", requeued=0,
                dur_s=round(time.monotonic() - t0, 6))
        return eng

    def install_version(self, params, *, step: int | None = None,
                        published_wall: "float | None" = None,
                        mode: str = "swap",
                        started_mono: "float | None" = None) -> dict:
        """Flip the serving weights IN PLACE at a step boundary. The
        parameter tree must match the current one exactly (structure,
        shapes, dtypes) — the compiled programs take params as a plain
        argument, so an identical-shape flip costs zero recompiles.

        The swap rule, in order: (1) every running sequence is
        released and its PRISTINE request re-queued at the front
        (tokens generated under the old weights are discarded, so no
        completed output ever mixes versions — the preemption-replay
        path is sanitized too); (2) the params pointer flips (and the
        default truncated-target draft is re-derived when speculative
        decoding uses it); (3) the prefix cache is fenced by the new
        ``weights_version`` — device entries dropped, host-tier spills
        epoch-fenced; (4) a ``serve.swap`` event is emitted and the
        whole transition is priced into the ``rollout`` badput bucket.
        Zero requests are dropped: the latency clock keys on request
        id and survives the requeue, so SLO burn stays honest."""
        t0 = started_mono if started_mono is not None \
            else time.monotonic()
        raw = params
        params = decode_lib.canonical_params(self.cfg, params)
        if self.mesh is not None:
            shardings = decode_lib.param_shardings(self.cfg, self.mesh)
            params = jax.tree_util.tree_map(
                lambda a, s: jax.device_put(jnp.asarray(a), s),
                dict(params), shardings)
        else:
            params = jax.tree_util.tree_map(jnp.asarray, dict(params))
        old_l, old_t = jax.tree_util.tree_flatten(self.params)
        new_l, new_t = jax.tree_util.tree_flatten(params)
        if old_t != new_t or any(
                a.shape != b.shape or a.dtype != b.dtype
                for a, b in zip(old_l, new_l)):
            raise ValueError(
                "install_version: parameter tree mismatch — hot-swap "
                "requires the same TransformerConfig (identical "
                "structure/shapes/dtypes); rebuild the engine for an "
                "architecture change")
        previous = self.weights_version
        requeued = self.scheduler.requeue_running()
        self.params = params
        if self.spec_k and self._draft_default:
            dcfg, dparams = decode_lib.truncated_draft(self.cfg, raw)
            self._draft_cfg = dcfg
            self._draft_params = jax.tree_util.tree_map(
                jnp.asarray,
                dict(decode_lib.canonical_params(dcfg, dparams)))
        self.weights_step = (int(step) if step is not None
                             else self.weights_step + 1)
        self.weights_digest = params_digest(self.params)
        dropped = 0
        if self.scheduler.prefix_cache is not None:
            dropped = self.scheduler.prefix_cache.fence(
                self._cache_epoch())
        self.swaps += 1
        self.swap_error = None
        self._m_swaps.increment()
        self._m_model_version.set(self.weights_step)
        dur = time.monotonic() - t0
        now = time.time()
        freshness = (max(0.0, now - published_wall)
                     if published_wall is not None else None)
        telemetry.event(
            "serve.swap", step=self.weights_step,
            version=self.weights_version, previous=previous,
            mode=mode, requeued=requeued, cache_dropped=dropped,
            dur_s=round(dur, 6),
            freshness_s=(round(freshness, 6)
                         if freshness is not None else None))
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.record("rollout", dur)
        return {"step": self.weights_step,
                "version": self.weights_version,
                "previous": previous, "requeued": requeued,
                "cache_dropped": dropped, "dur_s": dur}

    def _restore_pinned(self, step: int):
        """Fetch snapshot ``step``'s flat state via the restore-tier
        ladder, pinned (torn/pruned ⇒ loud raise, never a silent
        different version)."""
        if self._version_source is None:
            raise RuntimeError(
                "load_version: engine has no checkpoint provenance — "
                "build it with InferenceEngine.from_checkpoint")
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint, CheckpointManager)
        src = self._version_source
        ckpt = Checkpoint(params=self._params_template)
        mgr = CheckpointManager(
            ckpt, src["directory"],
            checkpoint_name=src["checkpoint_name"],
            local_dir=src["local_dir"],
            snapshot_store=src["snapshot_store"])
        res = mgr.restore_latest(at_step=int(step))
        if res is None:
            raise FileNotFoundError(
                f"load_version: pinned step {step} not restorable")
        return res[2]

    def load_version(self, step: int, *,
                     published_wall: "float | None" = None) -> dict:
        """Synchronous hot-swap to snapshot ``step``: restore down the
        tier ladder (pinned), rebuild the parameter tree, and
        :meth:`install_version` it. Restore time is part of the priced
        transition. Prefer :meth:`begin_load_version` on a live step
        loop — it keeps the restore off the serving thread."""
        from distributed_tensorflow_tpu.training.model import (
            _unflatten_like)
        t0 = time.monotonic()
        flat = self._restore_pinned(step)
        params = _unflatten_like(self._params_template, flat, "params")
        return self.install_version(params, step=step,
                                    published_wall=published_wall,
                                    started_mono=t0)

    def begin_load_version(self, step: int, *,
                           published_wall: "float | None" = None
                           ) -> bool:
        """Start restoring snapshot ``step`` on a background thread;
        :meth:`step` installs it at the next step boundary once the
        restore lands (the flip itself stays on the serving thread, so
        no request ever sees a half-written tree). Returns False when a
        load is already in flight. A failed restore surfaces as a
        ``serve.swap_error`` event + :attr:`swap_error` — the replica
        keeps serving the current version."""
        if self._swap_thread is not None and \
                self._swap_thread.is_alive():
            return False

        def _work():
            t0 = time.monotonic()
            try:
                flat = self._restore_pinned(step)
            except BaseException as e:       # surfaced at the boundary
                self._pending_swap = ("error", int(step), e)
                return
            self._pending_swap = ("ready", int(step), flat,
                                  published_wall, t0)

        self._pending_swap = None
        self._swap_thread = threading.Thread(
            target=_work, name=f"swap-load-{step}", daemon=True)
        self._swap_thread.start()
        return True

    def _poll_pending_swap(self):
        """Install a background-loaded version at the step boundary."""
        if self._swap_thread is None or self._swap_thread.is_alive():
            return
        self._swap_thread = None
        pending, self._pending_swap = self._pending_swap, None
        if pending is None:
            return
        if pending[0] == "error":
            _kind, step, err = pending
            self.swap_error = err
            telemetry.event("serve.swap_error", step=step,
                            error=repr(err))
            return
        from distributed_tensorflow_tpu.training.model import (
            _unflatten_like)
        _kind, step, flat, published_wall, t0 = pending
        params = _unflatten_like(self._params_template, flat, "params")
        self.install_version(params, step=step,
                             published_wall=published_wall,
                             started_mono=t0)

    # -- request lifecycle -------------------------------------------------
    def submit(self, request: Request, *,
               arrival_wall: "float | None" = None) -> "Request | None":
        """Queue a request; returns the request the queue evicted to
        make room (policy ``evict_oldest``), if any. Raises
        ``QueueOverflowError`` under the ``reject`` policy.

        ``arrival_wall`` backdates the latency clock to the request's
        TRUE arrival (an open-loop timed workload — or a restarted
        replica re-serving backlog whose original arrival predates this
        incarnation): the ``serve.request`` latency then honestly
        includes the queueing the client experienced, so SLO burn can
        not be reset by a restart."""
        if len(request.tokens) > self.max_prompt_len:
            raise ValueError(
                f"request {request.id}: prompt {len(request.tokens)} > "
                f"max_prompt_len {self.max_prompt_len}")
        if not self.cfg.causal and request.max_new_tokens > 0:
            raise ValueError(
                f"request {request.id}: bidirectional (non-causal) "
                f"configs serve scoring requests only "
                f"(max_new_tokens=0)")
        if (len(request.tokens) + request.max_new_tokens
                > self.max_seq_len):
            raise ValueError(
                f"request {request.id}: prompt + max_new_tokens "
                f"exceeds max_seq_len {self.max_seq_len}")
        evicted = self.scheduler.queue.submit(request)
        self._submitted[request.id] = (arrival_wall
                                       if arrival_wall is not None
                                       else time.time())
        self._submit_mono[request.id] = time.monotonic()
        if evicted is not None:
            self._submitted.pop(evicted.id, None)
            self._submit_mono.pop(evicted.id, None)
        self._m_queued.set(len(self.scheduler.queue))
        telemetry.event("serve.admit", id=request.id,
                        span_id=request_span_id(request.id),
                        tenant=request.tenant, pclass=request.pclass,
                        prompt_tokens=len(request.tokens),
                        queued=len(self.scheduler.queue))
        return evicted

    def _apply_copies(self, copies):
        """Execute BlockTable.ensure_writable's copy-on-write
        instructions on the device pool (values AND quantisation
        scales) BEFORE the divergent write they protect."""
        if not copies:
            return
        src = np.concatenate([np.arange(s, s + n, dtype=np.int32)
                              for s, _, n in copies])
        dst = np.concatenate([np.arange(d, d + n, dtype=np.int32)
                              for _, d, n in copies])
        self.pool = self._copy(self.pool, jnp.asarray(src),
                               jnp.asarray(dst))

    def _prefill_one(self, seq: Sequence):
        """Run one admitted sequence's prompt through the compiled
        prefill and bank its first greedy token.

        Cold path: the full prompt through ``prefill`` (fixed
        (1, max_seq_len) shape — wider than max_prompt_len so a
        PREEMPTED sequence's replayed prompt, which includes its
        already-generated tokens, always fits). Prefix-cache hit: only
        the unmatched suffix runs, through the multi-token ``extend``
        program at a power-of-two bucket width (bounded recompiles),
        attending the cached blocks through the normal block-window
        gather — the start-offset path that turns repeated-prefix
        traffic into O(suffix) prefill."""
        rid = seq.request.id
        submit_mono = self._submit_mono.get(rid)
        queue_wait = (seq.admitted_s - submit_mono
                      if submit_mono is not None else None)
        C = seq.cached_tokens
        with telemetry.span(
                "serve.prefill", id=rid, span_id=request_span_id(rid),
                model_version=self.weights_version,
                prompt_tokens=seq.prompt_len,
                cached_tokens=C or None,
                queue_wait_s=(round(queue_wait, 6)
                              if queue_wait is not None else None),
                replayed=len(seq.request.generated_prefix) or None):
            if C:
                S = seq.prompt_len - C              # suffix to compute
                E = min(self.max_seq_len,
                        1 << max(3, (S - 1).bit_length()))
                # a partially-matched tail block is SHARED: copy it
                # before the suffix writes into it (and before the row
                # indices below are derived from the table)
                self._apply_copies(seq.table.ensure_writable(
                    C, seq.prompt_len, self.scheduler.allocator))
                toks = np.zeros((1, E), np.int32)
                toks[0, :S] = seq.request.tokens[C:]
                pos = np.full((1, E), self.window, np.int32)
                pos[0, :S] = np.arange(C, seq.prompt_len)
                rows = np.zeros((1, E), np.int32)   # pad -> trash row
                rows[0, :S] = seq.table.rows(np.arange(C,
                                                       seq.prompt_len))
                win = seq.table.window_rows()[None]
                lengths = np.asarray([seq.prompt_len], np.int32)
                logits, self.pool = self._extend_prefill(
                    self.params, self.pool, jnp.asarray(toks),
                    jnp.asarray(pos), jnp.asarray(lengths),
                    jnp.asarray(rows), jnp.asarray(win))
                last = logits[0, S - 1]
            else:
                P = self.max_seq_len
                toks = np.zeros((1, P), np.int32)
                toks[0, :seq.prompt_len] = seq.request.tokens
                rows = seq.table.rows(np.arange(P))[None]   # (1, P)
                lengths = np.asarray([seq.prompt_len], np.int32)
                last, self.pool = self._prefill(
                    self.params, self.pool, jnp.asarray(toks),
                    jnp.asarray(lengths), jnp.asarray(rows))
                last = last[0]
            self.scheduler.commit_prefill(seq)
            first = int(np.asarray(jnp.argmax(last)))
        self._m_prompt_tokens.increment(seq.prompt_len)
        if C:
            self._m_cached_tokens.increment(C)
        if seq.request.max_new_tokens > 0:
            self.scheduler.append_token(seq, first)
        else:
            seq.first_token_s = time.monotonic()
            seq.score_token = first                    # scoring request

    def _emit_token(self, seq: Sequence):
        # per-token decode breadcrumb on the request's span: index
        # counts generated tokens ACROSS preemptions (the replayed
        # prefix included), so a re-served request's token trail lines
        # up generation-to-generation
        rid = seq.request.id
        telemetry.event(
            "serve.token", id=rid, span_id=request_span_id(rid),
            index=(len(seq.request.generated_prefix)
                   + len(seq.generated)),
            step=self._step_idx)

    def _decode_batch(self, batch: list[Sequence]):
        """One incremental token for every running sequence. The decode
        program has a fixed (max_slots,) batch; idle slots feed trash
        rows with length 0 and their logits are never read."""
        B, W = self.max_slots, self.window
        tokens = np.zeros(B, np.int32)
        positions = np.zeros(B, np.int32)
        lengths = np.zeros(B, np.int32)
        write_rows = np.zeros(B, np.int32)     # trash block row 0
        window_rows = np.zeros((B, W), np.int32)
        for seq in batch:
            s = seq.slot
            if self.prefix_caching:
                # the write at position length-1 must not land in a
                # block a prefix-cache sibling shares: copy-on-write
                # first (without a cache no block is ever shared)
                self._apply_copies(seq.table.ensure_writable(
                    seq.length - 1, seq.length,
                    self.scheduler.allocator))
            # feed the last banked token at position length-1 (it was
            # appended by the previous prefill/decode step)
            tokens[s] = seq.last_token
            positions[s] = seq.length - 1
            lengths[s] = seq.length
            write_rows[s] = seq.table.row_of(seq.length - 1)
            window_rows[s] = seq.table.window_rows()
        logits, self.pool = self._decode(
            self.params, self.pool,
            jnp.asarray(tokens), jnp.asarray(positions),
            jnp.asarray(lengths), jnp.asarray(write_rows),
            jnp.asarray(window_rows))
        nxt = np.asarray(jnp.argmax(logits, axis=-1))
        emit = telemetry.enabled()
        for seq in batch:
            self.scheduler.append_token(seq, int(nxt[seq.slot]))
            if emit:
                self._emit_token(seq)

    # -- speculative decoding ---------------------------------------------
    def _spec_span(self, seq: Sequence) -> int:
        """How many draft tokens speculating on ``seq`` can possibly
        commit this step: capped by k, by the request's remaining
        output budget (committing j drafts + 1 target token needs
        remaining >= j + 1), and by the sequence-length ceiling."""
        remaining = seq.request.max_new_tokens - len(seq.generated)
        return max(0, min(self.spec_k, remaining - 1,
                          self.max_seq_len - seq.length))

    def _speculative_batch(self, batch: list[Sequence]) -> int:
        """Draft-then-verify for the whole decode batch (Leviathan et
        al.): the draft proposes up to k greedy tokens per slot, the
        target scores all k+1 positions in ONE cache-aware extend
        forward, and each slot commits the longest prefix on which the
        draft agreed with the target — plus the target's own next
        token (the bonus on full acceptance, the correction on the
        first rejection). Every committed token is the target's argmax
        in its true greedy context, so outputs are EXACTLY the
        non-speculative ones. Returns tokens committed."""
        k, B, W = self.spec_k, self.max_slots, self.window
        E, S = k + 1, self.max_seq_len
        spans = {seq.slot: self._spec_span(seq) for seq in batch}

        # 1. draft proposals: k batched greedy steps, full recompute
        toks = np.zeros((B, S), np.int32)
        lens = np.zeros(B, np.int32)
        for seq in batch:
            hist = list(seq.request.tokens) + seq.generated
            toks[seq.slot, :len(hist)] = hist
            lens[seq.slot] = len(hist)
        proposals = np.zeros((B, k), np.int32)
        for i in range(k):
            nxt = np.asarray(self._draft(self._draft_params,
                                         jnp.asarray(toks),
                                         jnp.asarray(lens)))
            proposals[:, i] = nxt
            can = lens < S
            toks[np.arange(B)[can], lens[can]] = nxt[can]
            lens[can] += 1

        # 2. verify all k+1 positions in one extend forward
        tokens = np.zeros((B, E), np.int32)
        positions = np.full((B, E), W, np.int32)   # pad -> masked query
        lengths = np.zeros(B, np.int32)
        write_rows = np.zeros((B, E), np.int32)    # pad -> trash row
        window_rows = np.zeros((B, W), np.int32)
        for seq in batch:
            s, L, ke = seq.slot, seq.length, spans[seq.slot]
            if self.prefix_caching:
                self._apply_copies(seq.table.ensure_writable(
                    L - 1, L + ke, self.scheduler.allocator))
            tokens[s, 0] = seq.last_token
            tokens[s, 1:ke + 1] = proposals[s, :ke]
            positions[s, :ke + 1] = np.arange(L - 1, L + ke)
            lengths[s] = L + ke
            write_rows[s, :ke + 1] = [seq.table.row_of(p)
                                      for p in range(L - 1, L + ke)]
            window_rows[s] = seq.table.window_rows()
        logits, self.pool = self._extend_spec(
            self.params, self.pool, jnp.asarray(tokens),
            jnp.asarray(positions), jnp.asarray(lengths),
            jnp.asarray(write_rows), jnp.asarray(window_rows))
        target_next = np.asarray(jnp.argmax(logits, axis=-1))  # (B, E)

        # 3. commit the agreeing prefix + the target's next token
        emit = telemetry.enabled()
        committed_total = 0
        for seq in batch:
            s, ke = seq.slot, spans[seq.slot]
            j = 0
            while j < ke and proposals[s, j] == target_next[s, j]:
                j += 1
            self._m_spec_proposed.increment(ke)
            self._m_spec_accepted.increment(j)
            self._spec_proposed_n += ke
            self._spec_accepted_n += j
            for t in target_next[s, :j + 1]:
                self.scheduler.append_token(seq, int(t))
                committed_total += 1
                if emit:
                    self._emit_token(seq)
                if seq.done:
                    break
        return committed_total

    def step(self) -> list[dict]:
        """One continuous-batching iteration; returns completion records
        for every request finished this step."""
        t0 = time.monotonic()
        # chaos site FIRST: an injected raise leaves scheduler/cache
        # state untouched, so the caller can simply retry the step
        faults.fire("serve.step", tag=self._step_idx)
        # a background-loaded version installs HERE — the step
        # boundary: after the fault site (an injected raise just
        # defers the flip to the retry), before any admission/decode
        # touches the old weights
        self._poll_pending_swap()
        sched = self.scheduler
        finished: list[dict] = []
        with telemetry.span("serve.step", step=self._step_idx) as sp:
            # 1. retire finished sequences -> blocks free immediately
            for seq in list(sched.finished()):
                finished.append(self._complete(seq))
            defer_p0 = sched.deferred_prefill
            defer_b0 = sched.deferred_blocks
            admitted = sched.admit()
            for seq in admitted:
                self._prefill_one(seq)
            # scoring requests (max_new_tokens=0) finish at prefill
            for seq in list(sched.finished()):
                finished.append(self._complete(seq))
            if self._decode is None:
                batch = []
            elif self.spec_k:
                spec_before = self._spec_proposed_n
                acc_before = self._spec_accepted_n
                batch = sched.grow_for_decode(
                    lambda s: self._spec_span(s) + 1)
                if batch:
                    self._speculative_batch(batch)
                sp["proposed_drafts"] = (self._spec_proposed_n
                                         - spec_before)
                sp["accepted_drafts"] = (self._spec_accepted_n
                                         - acc_before)
            else:
                batch = sched.grow_for_decode()
                if batch:
                    self._decode_batch(batch)
            sp["admitted"] = len(admitted)
            sp["decoded"] = len(batch)
            sp["finished"] = len(finished)
            sp["queued"] = len(sched.queue)
            sp["blocks_free"] = sched.allocator.num_free
            # deferral split BY CAUSE (this step's deltas): prefill
            # budget pressure vs pool exhaustion — the bench reads
            # these off serve.step to attribute p99 to interference
            if sched.deferred_prefill > defer_p0:
                sp["deferred_prefill"] = (sched.deferred_prefill
                                          - defer_p0)
            if sched.deferred_blocks > defer_b0:
                sp["deferred_blocks"] = (sched.deferred_blocks
                                         - defer_b0)
            if admitted:
                cached = sum(s.cached_tokens for s in admitted)
                if cached:
                    sp["cached_tokens"] = cached
            if sched.prefix_cache is not None:
                self._m_cache_blocks.set(len(sched.prefix_cache))
        self._step_idx += 1
        step_s = time.monotonic() - t0
        self._m_step.record(step_s)
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.serve_step(step_s)
        self._m_running.set(len(sched.running))
        self._m_queued.set(len(sched.queue))
        self._m_blocks_free.set(sched.allocator.num_free)
        if sched.preemptions > self._m_preempt.value:
            self._m_preempt.increment(
                sched.preemptions - self._m_preempt.value)
        return finished

    def _complete(self, seq: Sequence) -> dict:
        self.scheduler.finish(seq)
        req = seq.request
        now = time.time()
        arrival = self._submitted.pop(req.id, now)
        self._submit_mono.pop(req.id, None)
        latency = max(0.0, now - arrival)
        ttft = ((seq.first_token_s - seq.admitted_s)
                if seq.first_token_s is not None else None)
        generated = list(req.generated_prefix) + list(seq.generated)
        tokens = (generated if (req.max_new_tokens > 0
                                or req.generated_prefix)
                  else [getattr(seq, "score_token", -1)])
        prompt_tokens = len(req.tokens) - len(req.generated_prefix)
        replayed = len(req.generated_prefix)
        self._m_req_latency.record(latency)
        if ttft is not None:
            self._m_ttft.record(ttft)
        self._m_completed.increment()
        self._m_tokens.increment(len(seq.generated))
        if replayed:
            self._m_replayed.increment(replayed)
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.tokens(fresh=len(seq.generated), replayed=replayed)
        telemetry.event(
            "serve.request", id=req.id, dur_s=round(latency, 6),
            span_id=request_span_id(req.id),
            model_version=self.weights_version,
            tenant=req.tenant, pclass=req.pclass,
            prompt_tokens=prompt_tokens, new_tokens=len(generated),
            replayed_tokens=replayed,
            ttft_s=round(ttft, 6) if ttft is not None else None,
            preemptions=seq.preemptions)
        return {"id": req.id, "tokens": tokens,
                "prompt_tokens": prompt_tokens,
                "model_version": self.weights_version,
                "tenant": req.tenant, "pclass": req.pclass,
                "latency_s": latency, "ttft_s": ttft,
                "replayed_tokens": replayed,
                "preemptions": seq.preemptions}

    # -- KV-block migration ------------------------------------------------
    def pool_fingerprint(self) -> dict:
        """Pool-compatibility fingerprint a migration payload carries:
        adoption REQUIRES equality — same storage dtype, same block
        geometry, same per-row shape — or the raw exported rows would
        be reinterpreted wrongly. Weights equality is the caller's
        contract (replicas of one serving deployment share a
        checkpoint)."""
        c = self.cache_cfg
        return {"kv_dtype": str(jnp.dtype(c.dtype).name),
                "block_size": c.block_size, "n_layers": c.n_layers,
                "n_heads": c.n_heads, "head_dim": c.head_dim}

    def _block_rows(self, blocks) -> np.ndarray:
        bs = self.cache_cfg.block_size
        return np.concatenate(
            [np.arange(b * bs, (b + 1) * bs, dtype=np.int32)
             for b in blocks])

    def export_sequence(self, seq: Sequence, *,
                        reason: str = "migrate"):
        """Gather a PREFILLED sequence's KV blocks off the pool and
        return a :class:`~distributed_tensorflow_tpu.serving.migrate.
        MigrationPayload` holding everything another replica needs to
        continue it — raw block rows (scales included for int8), the
        request, tokens generated so far (carried as LIVE state, so the
        adopter replays nothing), and latency provenance. The
        sequence's slot and blocks are released HERE: after export the
        payload is the only copy, and publishing it is the caller's
        job (write-once blob commit makes that crash-safe).

        The exported rows include position ``length-1``'s not-yet-
        written row — the engine's KV timing invariant (the last banked
        token's KV is written by the NEXT decode step before any read),
        so shipping one stale row is byte-correct exactly like the
        monolithic step."""
        rid = seq.request.id
        sched = self.scheduler
        if not seq.prefilled:
            raise ValueError(f"export {rid}: sequence not prefilled "
                             f"(nothing in the cache to migrate)")
        from distributed_tensorflow_tpu.serving import (
            migrate as _migrate)
        blocks = list(seq.table.blocks)
        t0 = time.monotonic()
        with telemetry.span("kv.migrate", id=rid,
                            span_id=migrate_span_id(rid),
                            direction="export", reason=reason,
                            blocks=len(blocks)) as sp:
            g = self._gather(self.pool,
                             jnp.asarray(self._block_rows(blocks)))
            arrays = {n: np.asarray(jax.device_get(a))
                      for n, a in g.items()}
            ttft = ((seq.first_token_s - seq.admitted_s)
                    if seq.first_token_s is not None else None)
            payload = _migrate.MigrationPayload(
                request_id=rid, tokens=tuple(seq.request.tokens),
                max_new_tokens=seq.request.max_new_tokens,
                eos_id=seq.request.eos_id,
                generated_prefix=tuple(seq.request.generated_prefix),
                generated=tuple(seq.generated), length=seq.length,
                fingerprint=self.pool_fingerprint(),
                pool_epoch=self.pool_epoch,
                arrival_wall=self._submitted.get(rid),
                ttft_s=ttft, preemptions=seq.preemptions,
                arrays=arrays)
            sp["bytes"] = payload.nbytes
            # source-side release: the slot (unless the scheduler's
            # preemption path already freed it) and the block refs
            if sched.running.get(seq.slot) is seq:
                del sched.running[seq.slot]
                sched._free_slots.append(seq.slot)
                sched._free_slots.sort(reverse=True)
            seq.table.release(sched.allocator)
            self._submitted.pop(rid, None)
            self._submit_mono.pop(rid, None)
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.record("kv_migrate", time.monotonic() - t0)
        self.migrations_out += 1
        self.migrated_bytes += payload.nbytes
        return payload

    def can_adopt(self, payload) -> bool:
        """Non-destructive capacity probe: a free slot AND enough free
        blocks for the payload. The migration source MUST check before
        shipping — adoption never preempts to make room."""
        n_blocks = payload.arrays["k"].shape[1] \
            // self.cache_cfg.block_size
        return (bool(self.scheduler._free_slots)
                and self.scheduler.allocator.num_free >= n_blocks)

    def adopt_sequence(self, payload, *,
                       arrival_wall: "float | None" = None) -> Sequence:
        """Install a migrated-in sequence: allocate blocks, scatter the
        payload's rows into this pool, and register the sequence as
        prefilled-and-running. Greedy decode continues exactly where
        the source stopped — prior tokens are live generation state,
        so ``replayed_tokens`` stays 0 and the completion record is
        byte-identical to the monolithic run. Raises ``ValueError`` on
        a pool-fingerprint mismatch (never serves through an
        incompatible pool) and ``OutOfBlocksError`` when capacity is
        short (see :meth:`can_adopt`)."""
        rid = payload.request_id
        fp = self.pool_fingerprint()
        if payload.fingerprint != fp:
            raise ValueError(
                f"adopt {rid}: pool fingerprint mismatch "
                f"(payload {payload.fingerprint} vs engine {fp})")
        sched = self.scheduler
        bs = self.cache_cfg.block_size
        n_blocks = payload.arrays["k"].shape[1] // bs
        t0 = time.monotonic()
        with telemetry.span("kv.migrate", id=rid,
                            span_id=migrate_span_id(rid),
                            direction="adopt", blocks=n_blocks,
                            bytes=payload.nbytes):
            blocks = sched.allocator.alloc(n_blocks)
            try:
                req = Request(id=rid, tokens=payload.tokens,
                              max_new_tokens=payload.max_new_tokens,
                              eos_id=payload.eos_id,
                              generated_prefix=tuple(
                                  payload.generated_prefix))
                seq = sched.adopt(req, blocks, payload.length,
                                  payload.generated)
            except Exception:
                sched.allocator.free(blocks)
                raise
            vals = {n: jnp.asarray(a)
                    for n, a in payload.arrays.items()}
            self.pool = self._insert(
                self.pool, jnp.asarray(self._block_rows(blocks)), vals)
            seq.preemptions = payload.preemptions
            if payload.ttft_s is not None:
                # preserve the SOURCE-measured time-to-first-token
                # (_complete reports first_token_s - admitted_s)
                seq.first_token_s = seq.admitted_s + payload.ttft_s
            self._submitted[rid] = (
                arrival_wall if arrival_wall is not None
                else payload.arrival_wall
                if payload.arrival_wall is not None else time.time())
            self._submit_mono[rid] = time.monotonic()
        ledger = _goodput.active_ledger()
        if ledger is not None:
            ledger.record("kv_migrate", time.monotonic() - t0)
        self.migrations_in += 1
        self.migrated_bytes += payload.nbytes
        return seq

    def block_accounting(self) -> dict:
        """Allocator conservation audit (the chaos --disagg gate):
        every live reference must be owned by a running sequence's
        table or a prefix-cache entry, and free + allocated must equal
        the usable pool. ``leaked_refs != 0`` or ``conserved: False``
        means a migration path dropped or duplicated block ownership."""
        sched = self.scheduler
        alloc = sched.allocator
        seq_refs = sum(len(s.table.blocks)
                       for s in sched.running.values())
        cache_refs = (len(sched.prefix_cache)
                      if sched.prefix_cache is not None else 0)
        return {
            "free": alloc.num_free,
            "allocated": alloc.num_allocated,
            "usable": self.cache_cfg.usable_blocks,
            "total_refs": alloc.total_refs,
            "seq_refs": seq_refs,
            "cache_refs": cache_refs,
            "leaked_refs": alloc.total_refs - seq_refs - cache_refs,
            "conserved": (alloc.num_free + alloc.num_allocated
                          == self.cache_cfg.usable_blocks),
        }

    # -- convenience -------------------------------------------------------
    def run_until_idle(self, *, max_steps: int = 100000,
                       retry_faults: bool = False) -> dict:
        """Drive :meth:`step` until queue and slots drain; returns
        ``{request_id: completion record}``. ``retry_faults=True``
        re-runs a step whose ``serve.step`` chaos site raised (the
        replica runtime's behavior)."""
        from distributed_tensorflow_tpu.resilience.faults import (
            FaultInjected)
        out: dict[str, dict] = {}
        for _ in range(max_steps):
            if self.scheduler.idle:
                break
            try:
                for rec in self.step():
                    out[rec["id"]] = rec
            except FaultInjected:
                if not retry_faults:
                    raise
        return out

    def generate(self, prompts, *, max_new_tokens: int = 16,
                 eos_id: int | None = None) -> list[list[int]]:
        """Batch convenience: greedy-decode ``prompts`` (lists of token
        ids) through the continuous-batching path; returns the generated
        token lists in prompt order."""
        for i, p in enumerate(prompts):
            self.submit(Request(id=f"g{i}", tokens=tuple(p),
                                max_new_tokens=max_new_tokens,
                                eos_id=eos_id))
        done = self.run_until_idle()
        return [done[f"g{i}"]["tokens"] for i in range(len(prompts))]

    def stats(self) -> dict:
        sched = self.scheduler
        out = {
            "steps": self._step_idx,
            "running": len(sched.running),
            "queued": len(sched.queue),
            "blocks_free": sched.allocator.num_free,
            "blocks_total": self.cache_cfg.usable_blocks,
            "preemptions": sched.preemptions,
            "deferred_prefill": sched.deferred_prefill,
            "deferred_blocks": sched.deferred_blocks,
            "migrated_out": sched.migrated_out,
            "migrations_out": self.migrations_out,
            "migrations_in": self.migrations_in,
            "migrated_bytes": self.migrated_bytes,
            "queue_rejected": sched.queue.rejected,
            "queue_evicted": sched.queue.evicted,
            "requests_completed": self._m_completed.value,
            "tokens_generated": self._m_tokens.value,
            "tokens_replayed": self._m_replayed.value,
            "serve_time_s": self._m_step.export().get("sum", 0.0),
            "kv_dtype": str(jnp.dtype(self.cache_cfg.dtype).name),
            "weights_step": self.weights_step,
            "weights_version": self.weights_version,
            "swaps": self.swaps,
        }
        if sched.prefix_cache is not None:
            out["prefix_cache"] = sched.prefix_cache.stats()
        if self.spill_tier is not None:
            out["spill_tier"] = self.spill_tier.stats()
        if self.spec_k:
            prop = self._spec_proposed_n
            out["speculative"] = {
                "k": self.spec_k,
                "proposed": prop,
                "accepted": self._spec_accepted_n,
                "accepted_rate": (self._spec_accepted_n / prop
                                  if prop else 0.0),
            }
        return out
