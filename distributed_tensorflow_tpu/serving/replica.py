"""Supervised serving replicas: the control-plane side of serving.

A *serving replica* is one :class:`~distributed_tensorflow_tpu.serving.
engine.InferenceEngine` driven by :func:`serving_replica` — a worker
function shaped exactly like the elastic trainer the recovery
supervisor already manages (examples/train_mnist.py elastic_worker):
module-level (picklable by reference), heartbeats once per engine step,
restartable from scratch at any instant. Run it under
``resilience.RecoverySupervisor`` and a SIGKILLed replica is detected,
its generation reformed, and the process respawned exactly like a dead
trainer — no supervisor changes needed.

**Zero dropped requests.** The replica appends one JSONL record per
COMPLETED request to ``served-<task>.jsonl`` (line-buffered, so a
SIGKILL loses at most the line in flight). On (re)start it reads that
file back, treats every recorded id as done, and re-queues the rest —
in-flight requests at kill time are simply re-served by the next
incarnation. Greedy decode over fixed weights is deterministic, so a
request that was half-decoded (or torn mid-write) re-generates the SAME
tokens; ``tools/chaos_sweep.py --serve`` gates both the completeness of
the union and the cross-generation consistency of any duplicates.

**Chaos.** Besides process-level SIGKILLs, the engine's ``serve.step``
fault site can raise mid-load; the replica retries the step under a
RetryPolicy (the site fires before any state mutation, so a retry is
always safe).

**Timed (open-loop) workloads & autoscaling.** ``spike=`` switches the
replica from serve-everything-ASAP to an open-loop arrival schedule
(:func:`seeded_spike_schedule`: base rate + a traffic spike window, a
pure function of the seed). All replicas and all incarnations share one
wall-clock anchor (:func:`run_epoch`, first-writer-wins in the run
dir), so request arrivals — and therefore SLO latency, measured from
the TRUE arrival via the engine's ``arrival_wall`` — are consistent
across restarts and resharding. The replica also polls the
supervisor's drain flag (cluster/elastic.drain_requested) every step:
on drain it stops admitting, finishes its RUNNING sequences, logs
them, and exits cleanly — the drain-before-stop contract a scale-down
relies on for zero dropped requests (unfinished work re-shards onto
the next generation via the completion-log union).
"""

from __future__ import annotations

import glob as _glob
import json
import os
import random

from distributed_tensorflow_tpu.serving.scheduler import Request


def seeded_requests(seed: int, n: int, vocab_size: int, *,
                    prompt_range: tuple = (4, 12),
                    new_tokens_range: tuple = (2, 10)) -> list[Request]:
    """Deterministic synthetic workload (the resilience/faults.py
    seeding discipline: a string-seeded stream, stable across
    processes/runs) — every replica incarnation regenerates the SAME
    request set from the seed."""
    rng = random.Random(f"dtx-serve:{seed}")
    out = []
    for i in range(n):
        plen = rng.randrange(*prompt_range)
        out.append(Request(
            id=f"r{i:04d}",
            tokens=tuple(rng.randrange(vocab_size) for _ in range(plen)),
            max_new_tokens=rng.randrange(*new_tokens_range)))
    return out


def seeded_spike_schedule(seed: int, *, duration_s: float = 40.0,
                          base_qps: float = 2.0, spike_qps: float = 8.0,
                          spike_start_s: float = 8.0,
                          spike_end_s: float = 22.0,
                          vocab_size: int = 256,
                          prompt_range: tuple = (4, 12),
                          new_tokens_range: tuple = (2, 6)
                          ) -> list[Request]:
    """Open-loop Poisson arrivals at ``base_qps`` with a spike window
    at ``spike_qps`` — the seeded traffic shape ``chaos_sweep --spike``
    and ``bench --autoscale`` drive at the autoscaler. A pure function
    of the seed (the resilience/faults.py discipline), arrival times in
    ``Request.arrival_s`` relative to the shared :func:`run_epoch`."""
    rng = random.Random(f"dtx-spike:{seed}")
    out: list[Request] = []
    t = 0.0
    i = 0
    while True:
        rate = (spike_qps if spike_start_s <= t < spike_end_s
                else base_qps)
        t += rng.expovariate(rate)
        if t >= duration_s:
            break
        plen = rng.randrange(*prompt_range)
        out.append(Request(
            id=f"s{i:05d}",
            tokens=tuple(rng.randrange(vocab_size)
                         for _ in range(plen)),
            max_new_tokens=rng.randrange(*new_tokens_range),
            arrival_s=round(t, 6)))
        i += 1
    return out


def run_epoch(run_dir: str) -> float:
    """The run's shared t=0 wall clock: first writer wins (O_EXCL), so
    every replica and every incarnation — including ones respawned by
    a scale reform — anchors the same arrival schedule to the same
    instant."""
    import time as _time
    path = os.path.join(run_dir, "run-epoch.json")
    try:
        fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        with os.fdopen(fd, "w") as f:
            json.dump({"epoch": _time.time()}, f)
    except FileExistsError:
        pass
    for _ in range(100):
        try:
            with open(path) as f:
                return float(json.load(f)["epoch"])
        except (OSError, ValueError):
            _time.sleep(0.01)            # racing writer: not yet flushed
    raise RuntimeError(f"unreadable run epoch at {path}")


def completed_ids_all(run_dir: str) -> dict[str, list]:
    """The UNION of every replica's completion log — what a (re)started
    replica treats as already done. Reading the union (not just its own
    task's log) matters under autoscaling: a scale reform re-shards the
    workload, so requests another replica finished may now map to this
    one."""
    out: dict[str, list] = {}
    for path in sorted(_glob.glob(os.path.join(run_dir,
                                               "served-*.jsonl"))):
        out.update(completed_ids(path))
    return out


def completed_ids(path: str) -> dict[str, list]:
    """``{request_id: tokens}`` from a replica's completion log;
    torn trailing lines (SIGKILL mid-write) are skipped."""
    out: dict[str, list] = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                  # torn line: not completed
                if "id" in rec:
                    out[rec["id"]] = rec.get("tokens", [])
    except OSError:
        pass
    return out


def _handoff_index(kv) -> dict:
    """``{request_id: blob_prefix}`` of committed drain-by-migration
    handoffs, HIGHEST generation winning (a request drained twice has
    one blob per draining generation; later = more decode progress).
    Keys are ``handoff/g<gen>/t<task>/<rid>`` — per-generation
    namespaces, so a republish never rewrites chunks under a committed
    count key."""
    best: dict = {}
    for key in kv.list("handoff/"):
        if not key.endswith("/n"):
            continue
        parts = key.split("/")      # handoff, g<gen>, t<task>, rid, n
        if len(parts) != 5:
            continue
        try:
            gen = int(parts[1][1:])
        except ValueError:
            continue
        rid = parts[3]
        if rid not in best or gen > best[rid][0]:
            best[rid] = (gen, key[:-len("/n")])
    return {rid: pfx for rid, (g, pfx) in best.items()}


def _try_adopt(engine, kv, prefix: str, timeout_s: float = 2.0) -> str:
    """Adopt one committed migration blob. Returns ``"adopted"``,
    ``"full"`` (fits but no capacity RIGHT NOW — retry later) or
    ``"bad"`` (fingerprint mismatch / corrupt blob — the caller must
    re-serve from the prompt, which is always correct)."""
    from distributed_tensorflow_tpu.serving import migrate as _mig
    try:
        payload = _mig.fetch_payload(kv, prefix, timeout_s=timeout_s)
        if payload.fingerprint != engine.pool_fingerprint():
            return "bad"
        if not engine.can_adopt(payload):
            return "full"
        engine.adopt_sequence(payload)
        return "adopted"
    except Exception:
        return "bad"


def serving_replica(run_dir: str, n_requests: int, seed: int,
                    vocab_size: int = 256, *, max_retries: int = 50,
                    engine_kwargs: dict | None = None,
                    ckpt_dir: str | None = None,
                    step_delay_s: float = 0.0,
                    spike: dict | None = None,
                    prefix_caching: bool = False,
                    speculative_k: int = 0,
                    kv_dtype: str | None = None,
                    disagg: bool = False):
    """One generation of one supervised serving replica.

    Serves the seeded workload to completion, heartbeating every engine
    step; restartable at any point via the completion log.
    ``step_delay_s`` paces the step loop (models network/request-bound
    serving; the chaos sweep uses it so a step-targeted SIGKILL has a
    real window to land in). ``spike`` (kwargs for
    :func:`seeded_spike_schedule`, minus seed/vocab) switches to the
    open-loop timed workload: requests are submitted when their arrival
    time passes (relative to the shared :func:`run_epoch`), latency is
    measured from the true arrival, and the supervisor's drain flag is
    honored every step (drain-before-stop).

    ``prefix_caching`` / ``speculative_k`` / ``kv_dtype`` switch on the
    engine's serving-speed optimisations. All three are OUTPUT-
    invariant for greedy decode (speculation exactly, prefix caching
    byte-identically, int8 within the probed bound — see the README
    KV-dtype table), so the cross-generation byte-identical-duplicates
    gate holds with them enabled, and a restarted incarnation simply
    rebuilds its prefix cache cold: correctness never depends on cache
    state.

    ``disagg=True`` (needs >= 2 replicas) splits the fleet by ROLE:
    task 0 is the prefill replica — it owns ALL admission, prefills
    every prompt, and publishes each sequence's KV blocks as a
    write-once migration blob (serving/migrate.py) keyed to its decode
    owner; tasks >= 1 are decode replicas that adopt their blobs and
    run the token loop. A SIGKILL on either side is safe by the blob
    commit protocol: an uncommitted blob is re-published by the next
    prefill incarnation, a committed one is re-adopted by the next
    decode incarnation, and greedy determinism keeps any duplicate
    completions byte-identical. Drain mode ``migrate`` exports live
    sequences to per-generation handoff blobs the next incarnation
    adopts — progress moves, nothing replays.

    Returns ``(task_index, n_served_this_generation,
    n_total_completed)``."""
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic

    # join the distributed runtime exactly like an elastic trainer:
    # the coordination control plane (and, on the CPU test backend, the
    # gloo-configured runtime the spawn harness expects) needs the
    # client BEFORE the first jax computation
    runtime = bootstrap.initialize()
    import contextlib

    import jax
    if runtime.num_processes <= 1:
        # a single-replica supervised run never joins a distributed
        # world, but the spawn harness pre-configures gloo collectives
        # (which this jaxlib rejects without a distributed client) —
        # reset before the first computation initializes the backend
        with contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation",
                              "none")

    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig, TransformerLM)
    from distributed_tensorflow_tpu.resilience.faults import FaultInjected
    from distributed_tensorflow_tpu.serving.engine import InferenceEngine
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    task = runtime.process_id
    n_replicas = max(1, runtime.num_processes)
    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=task)
    # live goodput ledger: serve steps are goodput, replayed tokens are
    # preempt_replay badput; everything before the first step is
    # startup. Exported through the registry (goodput/* gauges) and, in
    # the event files, re-derivable fleet-wide by the supervisor's
    # export tick.
    from distributed_tensorflow_tpu.telemetry import goodput
    goodput.activate(goodput.GoodputLedger())

    linger_s = 0.0
    epoch = None
    if spike is not None:
        spike = dict(spike)
        # keep serving (idle) past the schedule's end so the burn-clear
        # window — and the autoscaler's reclaim — happen while replicas
        # are still alive to be drained and resharded
        linger_s = float(spike.pop("linger_s", 0.0))
        workload = seeded_spike_schedule(seed, vocab_size=vocab_size,
                                         **spike)
        # the union across replicas AND generations: a scale reform
        # re-shards the workload, so another replica's completions are
        # this one's "already done"
        done = completed_ids_all(run_dir)
    else:
        workload = seeded_requests(seed, n_requests, vocab_size)
        # disagg reads the UNION: completions land in the decode
        # replicas' logs, and the prefill replica must not re-admit them
        done = (completed_ids_all(run_dir) if disagg
                else completed_ids(os.path.join(run_dir,
                                                f"served-{task}.jsonl")))
    disagg = bool(disagg) and n_replicas >= 2 and spike is None

    cfg = TransformerConfig.tiny(max_seq_len=64)
    kwargs = dict(num_blocks=48, block_size=8, max_slots=4,
                  max_prompt_len=16,
                  queue_capacity=len(workload) + 1,
                  prefix_caching=prefix_caching,
                  speculative_k=speculative_k,
                  kv_dtype=kv_dtype)
    kwargs.update(engine_kwargs or {})
    if disagg and task == 0:
        kwargs["role"] = "prefill"      # no decode program compiled
    if ckpt_dir:
        engine = InferenceEngine.from_checkpoint(cfg, ckpt_dir, **kwargs)
    else:
        # seed-deterministic weights: every incarnation serves the same
        # model, so re-served requests generate identical tokens
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0),
            jax.numpy.zeros((1, 8), jax.numpy.int32))["params"]
        engine = InferenceEngine(cfg, params, **kwargs)

    if spike is not None:
        # warm the compiled prefill/decode BEFORE anchoring (or
        # reading) the run epoch: compile time is replica startup, not
        # client-visible queueing — it must not poison the latency SLO
        # stream that drives the autoscaler. Every incarnation warms
        # (a respawn is nearly free once the persistent compile cache
        # is populated).
        gen0 = elastic.generation()
        from distributed_tensorflow_tpu.serving.scheduler import (
            Request as _Req)
        engine.submit(_Req(id=f"warmup-{task}-g{gen0}",
                           tokens=(1, 2, 3), max_new_tokens=2))
        engine.run_until_idle(retry_faults=True)
        epoch = run_epoch(run_dir)

    from distributed_tensorflow_tpu.serving import migrate as _mig
    kv = _mig.FileKV(os.path.join(run_dir, "kvwire"))
    n_dec = max(1, n_replicas - 1)

    def _dtask(rid: str) -> int:
        """Deterministic request -> decode-replica owner (disagg): the
        same id maps to the same decode task in every incarnation, so
        a respawned decoder knows exactly which blobs are its."""
        return 1 + int(rid.lstrip("rs")) % n_dec

    log_path = os.path.join(run_dir, f"served-{task}.jsonl")
    if disagg:
        # role sharding: prefill (task 0) owns every admission, decode
        # task d owns the requests _dtask maps to it
        mine = (list(workload) if task == 0
                else [r for r in workload if _dtask(r.id) == task])
    else:
        # replicas statically shard the workload (request i -> replica
        # i mod N); the union of all replicas' completion logs must
        # cover the full request set — the chaos zero-dropped gate
        mine = [r for i, r in enumerate(workload)
                if i % n_replicas == task]
    todo = [r for r in mine if r.id not in done]
    gen = elastic.generation()
    print(f"[gen {gen} serve-{task}] {len(mine) - len(todo)} already "
          f"served, {len(todo)} of {len(mine)} to go", flush=True)

    served = 0
    step = 0
    retries = 0
    drained = False
    import collections as _collections
    import time as _time
    pending = _collections.deque(todo)   # arrival order == index order
    finished_ids: set = set()
    if spike is None and not disagg:
        # drain-by-migration handoffs from a previous generation: adopt
        # the live KV (decode continues, zero replay) instead of
        # re-serving from the prompt; anything that does not fit or
        # match simply submits — correctness never depends on a blob
        handoffs = _handoff_index(kv)
        adopted_n = 0
        for r in todo:
            pfx = handoffs.get(r.id)
            if pfx is not None and _try_adopt(engine, kv,
                                              pfx) == "adopted":
                adopted_n += 1
            else:
                engine.submit(r)
        if adopted_n:
            print(f"[gen {gen} serve-{task}] adopted {adopted_n} "
                  f"drained sequence(s) by KV migration", flush=True)
        pending.clear()

    def _log_finished(log, finished):
        nonlocal served
        for rec in finished:
            log.write(json.dumps({
                "id": rec["id"], "tokens": rec["tokens"],
                "prompt_tokens": rec["prompt_tokens"],
                "latency_s": round(rec["latency_s"], 6),
                "gen": gen}) + "\n")
            finished_ids.add(rec["id"])
            served += 1

    def _step(log) -> bool:
        """One retried engine step; False when the retry budget blew."""
        nonlocal retries
        try:
            _log_finished(log, engine.step())
        except FaultInjected:
            retries += 1
            if retries > max_retries:
                raise
        return True

    def _drain(log, mode: str):
        """Drain-before-stop. ``fast`` (scale-up: capacity is wanted
        NOW): finish only the RUNNING sequences, the queue re-shards.
        ``full`` (scale-down: load is low by definition): finish
        everything already admitted, so no accepted request pays the
        respawn gap's latency tail. ``migrate`` (fastest, zero wasted
        work): EXPORT every running sequence's live KV to a
        per-generation handoff blob a survivor/successor adopts —
        decode continues where it stopped instead of finishing here or
        replaying there. Either way nothing is dropped — whatever is
        left re-shards onto the next generation via the completion-log
        union."""
        nonlocal drained
        held = 0
        migrated = 0
        if mode == "full":
            while not engine.scheduler.idle:
                elastic.heartbeat(step)
                _step(log)
        else:
            while engine.scheduler.queue.pop() is not None:
                held += 1
            if mode == "migrate":
                for seq in sorted(engine.scheduler.running.values(),
                                  key=lambda s: s.slot):
                    if not seq.prefilled or seq.done:
                        continue
                    rid = seq.request.id
                    payload = engine.export_sequence(seq,
                                                     reason="drain")
                    _mig.publish_payload(
                        kv, f"handoff/g{gen}/t{task}/{rid}", payload)
                    migrated += 1
            while engine.scheduler.running:
                elastic.heartbeat(step)
                _step(log)
        tv_events.event("serve.drain", task=task, mode=mode,
                        completed=served,
                        requeued=held + len(pending),
                        migrated=migrated or None)
        drained = True

    def _alloc_check():
        """Allocator conservation audit at generation end — the chaos
        --disagg gate asserts zero leaked refs on EVERY one of these,
        so a migration path that drops or duplicates block ownership
        fails loudly, not silently."""
        tv_events.event("serve.alloc_check", task=task,
                        **engine.block_accounting())

    def _finish(msg: str):
        elastic.heartbeat(step)
        _alloc_check()
        print(f"[gen {gen} serve-{task}] {msg}", flush=True)
        goodput.activate(None)
        if tdir:
            tv_events.shutdown()
        bootstrap.shutdown()

    if disagg and task == 0:
        # ---- prefill replica: admit everything, prefill, publish ----
        pending.clear()
        todo = [r for r in todo
                if not _mig.payload_committed(
                    kv, f"mig/d{_dtask(r.id)}/{r.id}")]
        with open(log_path, "a", buffering=1) as log:
            for r in todo:
                engine.submit(r)
            while not engine.scheduler.idle:
                elastic.heartbeat(step)
                if elastic.drain_mode() is not None:
                    # nothing decodes here: every prefilled sequence is
                    # exported the step it commits, so drain just stops
                    # admitting — the queue re-shards next generation
                    drained = True
                    break
                if step_delay_s:
                    _time.sleep(step_delay_s)
                _step(log)        # admit + prefill (+ scoring finishes)
                step += 1
                for seq in sorted(engine.scheduler.running.values(),
                                  key=lambda s: s.slot):
                    if seq.prefilled and not seq.done:
                        rid = seq.request.id
                        payload = engine.export_sequence(
                            seq, reason="prefill")
                        _mig.publish_payload(
                            kv, f"mig/d{_dtask(rid)}/{rid}", payload)
        _finish(f"prefilled+shipped, {served} completed at prefill "
                f"({'drained' if drained else 'complete'}), "
                f"{retries} injected-fault retries")
        return task, served, served

    if disagg:
        # ---- decode replica: adopt my blobs, run the token loop -----
        pending.clear()
        todo_by_id = {r.id: r for r in todo}
        todo_ids = set(todo_by_id)
        shipped: set = set()
        # a previous incarnation (possibly of ANOTHER task, before a
        # reshard) may have drained by migration: its handoff blobs
        # carry more decode progress than the original prefill blob —
        # prefer them
        handoffs = _handoff_index(kv)
        with open(log_path, "a", buffering=1) as log:
            while todo_ids - finished_ids:
                elastic.heartbeat(step)
                mode = elastic.drain_mode()
                if mode is not None:
                    _drain(log, mode if mode == "full" else "migrate")
                    break
                for rid in sorted(todo_ids - finished_ids - shipped):
                    pfx = handoffs.get(rid, f"mig/d{task}/{rid}")
                    if not _mig.payload_committed(kv, pfx):
                        continue
                    got = _try_adopt(engine, kv, pfx)
                    if got == "full":
                        break           # capacity frees as seqs finish
                    if got == "bad":
                        # stale/incompatible blob: serve from the
                        # prompt — greedy determinism keeps the output
                        # identical, only the KV shortcut is lost
                        engine.submit(todo_by_id[rid])
                    shipped.add(rid)
                if engine.scheduler.running:
                    if step_delay_s:
                        _time.sleep(step_delay_s)
                    _step(log)
                    step += 1
                else:
                    _time.sleep(0.01)   # blobs still in flight
        _finish(f"served {served} this generation "
                f"({'drained' if drained else 'complete'}), "
                f"{retries} injected-fault retries")
        return task, served, len(mine) - len(todo) + served

    end_rel = (float(spike.get("duration_s", 40.0)) + linger_s
               if spike is not None else 0.0)

    def _more_to_do() -> bool:
        if pending or not engine.scheduler.idle:
            return True
        return (spike is not None
                and _time.time() - epoch < end_rel)

    # line-buffered like the event log: a SIGKILL loses at most one line
    with open(log_path, "a", buffering=1) as log:
        while _more_to_do():
            elastic.heartbeat(step)
            mode = elastic.drain_mode()
            if mode is not None:
                _drain(log, mode)
                break
            if spike is not None:
                now_rel = _time.time() - epoch
                while pending and pending[0].arrival_s <= now_rel:
                    r = pending.popleft()
                    # backdate the latency clock to the TRUE arrival:
                    # a request re-served after a reform still carries
                    # the queueing its client actually experienced
                    engine.submit(r, arrival_wall=epoch + r.arrival_s)
                if engine.scheduler.idle:
                    # nothing running, nothing due: doze until the next
                    # arrival (still heartbeating)
                    _time.sleep(min(0.05, max(
                        0.001, (pending[0].arrival_s - now_rel)
                        if pending else 0.05)))
                    continue
            if step_delay_s:
                _time.sleep(step_delay_s)
            _step(log)
            step += 1
    _finish(f"served {served} this generation "
            f"({'drained' if drained else 'complete'}), "
            f"{retries} injected-fault retries")
    return task, served, len(mine) - len(todo) + served


# ---------------------------------------------------------------------------
# Routed replicas (multi-tenant frontend, serving/router.py)
# ---------------------------------------------------------------------------

def inbox_path(run_dir: str, task: int) -> str:
    """The line-buffered per-replica inbox the router appends routed
    requests to and :func:`routed_replica` tails."""
    return os.path.join(run_dir, f"inbox-{task}.jsonl")


def replica_metrics_dir(run_dir: str, task: int) -> str:
    """Where replica ``task`` exports its live metrics
    (``metrics-live.prom``) — one directory per replica so the router
    can scrape each one's queue depth (and judge liveness by mtime)."""
    return os.path.join(run_dir, f"metrics-{task}")


def request_to_wire(request: Request, meta: "dict | None" = None
                    ) -> dict:
    """One inbox line: the request's full content (the inbox IS the
    handoff — a respawned replica re-reads it from the top) plus the
    router's routing metadata (``reroute`` marks re-dispatch after a
    replica death; the server prices those completions
    ``reroute_replay``)."""
    return {"id": request.id, "tokens": list(request.tokens),
            "max_new_tokens": request.max_new_tokens,
            "eos_id": request.eos_id,
            "arrival_s": request.arrival_s,
            "tenant": request.tenant, "pclass": request.pclass,
            **(meta or {})}


def request_from_wire(rec: dict) -> Request:
    return Request(id=rec["id"], tokens=tuple(rec["tokens"]),
                   max_new_tokens=int(rec.get("max_new_tokens", 16)),
                   eos_id=rec.get("eos_id"),
                   arrival_s=float(rec.get("arrival_s", 0.0)),
                   tenant=rec.get("tenant"),
                   pclass=rec.get("pclass") or "interactive")


def _read_complete_lines(f) -> "list[str]":
    """New COMPLETE lines since the last call; a partial trailing line
    (the router mid-append) rewinds and is retried next poll."""
    lines = []
    while True:
        pos = f.tell()
        line = f.readline()
        if not line:
            break
        if not line.endswith("\n"):
            f.seek(pos)         # torn tail: the router is mid-write
            break
        lines.append(line)
    return lines


def routed_replica(run_dir: str, seed: int, *,
                   max_retries: int = 50,
                   engine_kwargs: "dict | None" = None,
                   step_delay_s: float = 0.0,
                   export_interval_s: float = 0.5):
    """One generation of one ROUTER-FED serving replica.

    Unlike :func:`serving_replica` (static workload shard), this worker
    owns no workload: it tails its inbox file (:func:`inbox_path` —
    line-buffered appends from the router), serves whatever lands
    there, and logs completions to the same ``served-<task>.jsonl``
    contract. Restart safety is the same union argument extended by the
    inbox: a respawned incarnation re-reads the inbox from the top,
    skips every id in the fleet-wide completion union, and re-serves
    the rest — plus whatever the router RE-ROUTES here from a replica
    that died (``reroute``-flagged lines; their completions emit
    ``serve.rerouted`` so the goodput ledger prices the duplicate work
    into the ``reroute_replay`` bucket).

    The replica runs its own :class:`~distributed_tensorflow_tpu.
    telemetry.exporter.MetricsExporter` into
    :func:`replica_metrics_dir` — the scrape the router's least-loaded
    fallback and liveness detection read. Prefix caching is ON by
    default (affinity routing is pointless without it).

    Exits when the router's ``eof`` sentinel has been read AND the
    engine is idle. Returns ``(task, served_this_gen, total_done)``."""
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic

    runtime = bootstrap.initialize()
    import contextlib

    import jax
    if runtime.num_processes <= 1:
        with contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation",
                              "none")

    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig, TransformerLM)
    from distributed_tensorflow_tpu.resilience.faults import FaultInjected
    from distributed_tensorflow_tpu.serving.engine import InferenceEngine
    from distributed_tensorflow_tpu.telemetry import events as tv_events
    from distributed_tensorflow_tpu.telemetry import exporter as tv_exp
    from distributed_tensorflow_tpu.telemetry import goodput

    task = runtime.process_id
    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=task)
    goodput.activate(goodput.GoodputLedger())

    cfg = TransformerConfig.tiny(max_seq_len=64)
    kwargs = dict(num_blocks=96, block_size=8, max_slots=4,
                  max_prompt_len=40, queue_capacity=4096,
                  prefix_caching=True)
    kwargs.update(engine_kwargs or {})
    params = TransformerLM(cfg).init(
        jax.random.PRNGKey(0),
        jax.numpy.zeros((1, 8), jax.numpy.int32))["params"]
    engine = InferenceEngine(cfg, params, **kwargs)

    # warm the compiled programs BEFORE anchoring the epoch (compile is
    # startup, not client-visible queueing), exactly like the spike path
    gen = elastic.generation()
    engine.submit(Request(id=f"warmup-{task}-g{gen}", tokens=(1, 2, 3),
                          max_new_tokens=2))
    engine.run_until_idle(retry_faults=True)

    mdir = replica_metrics_dir(run_dir, task)
    os.makedirs(mdir, exist_ok=True)
    exp = tv_exp.MetricsExporter(interval_s=export_interval_s, dir=mdir)

    # the ROUTER anchors the run epoch once it sees the whole fleet's
    # exporters up (arrivals must not start during compile warmup);
    # wait for its anchor, with a standalone-use fallback
    import time as _time
    epoch_path = os.path.join(run_dir, "run-epoch.json")
    wait_until = _time.time() + 60.0
    while not os.path.exists(epoch_path) and _time.time() < wait_until:
        elastic.heartbeat(0)
        _time.sleep(0.05)
    epoch = run_epoch(run_dir)

    done = completed_ids_all(run_dir)
    inbox = inbox_path(run_dir, task)
    open(inbox, "a").close()         # the router may not have written yet
    log_path = os.path.join(run_dir, f"served-{task}.jsonl")

    served = 0
    step = 0
    retries = 0
    eof = False
    submitted: set = set()
    reroute_ids: set = set()
    import time as _time
    print(f"[gen {gen} route-serve-{task}] up, {len(done)} in "
          f"completion union", flush=True)

    def _log_finished(log, finished):
        nonlocal served
        ledger = goodput.active_ledger()
        for rec in finished:
            if rec["id"].startswith("warmup-"):
                continue
            log.write(json.dumps({
                "id": rec["id"], "tokens": rec["tokens"],
                "prompt_tokens": rec["prompt_tokens"],
                "latency_s": round(rec["latency_s"], 6),
                "tenant": rec.get("tenant"),
                "pclass": rec.get("pclass"),
                "reroute": rec["id"] in reroute_ids,
                "gen": gen}) + "\n")
            served += 1
            if rec["id"] in reroute_ids:
                # duplicate/recovery work: the whole re-served request
                # prices into the reroute_replay badput bucket
                nt = len(rec["tokens"])
                tv_events.event("serve.rerouted", id=rec["id"],
                                tenant=rec.get("tenant"),
                                new_tokens=nt)
                if ledger is not None:
                    ledger.tokens(0, rerouted=nt)

    with open(log_path, "a", buffering=1) as log, open(inbox) as inb:
        while True:
            elastic.heartbeat(step)
            progressed = False
            for line in _read_complete_lines(inb):
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if rec.get("eof"):
                    eof = True
                    continue
                rid = rec.get("id")
                if rid is None or rid in submitted or rid in done:
                    continue
                submitted.add(rid)
                if rec.get("reroute"):
                    reroute_ids.add(rid)
                r = request_from_wire(rec)
                # backdate to the TRUE arrival: routing hops and
                # re-routes cannot reset the client's latency clock
                engine.submit(r, arrival_wall=epoch + r.arrival_s)
                progressed = True
            mode = elastic.drain_mode()
            if mode is not None:
                while not engine.scheduler.idle:
                    elastic.heartbeat(step)
                    try:
                        _log_finished(log, engine.step())
                    except FaultInjected:
                        retries += 1
                        if retries > max_retries:
                            raise
                tv_events.event("serve.drain", task=task, mode=mode,
                                completed=served, requeued=0)
                break
            if not engine.scheduler.idle:
                if step_delay_s:
                    _time.sleep(step_delay_s)
                try:
                    _log_finished(log, engine.step())
                except FaultInjected:
                    retries += 1
                    if retries > max_retries:
                        raise
                step += 1
            elif eof:
                break
            elif not progressed:
                _time.sleep(0.01)        # inbox quiet, engine idle

    elastic.heartbeat(step)
    tv_events.event("serve.alloc_check", task=task,
                    **engine.block_accounting())
    exp.stop()
    print(f"[gen {gen} route-serve-{task}] served {served} this "
          f"generation ({retries} injected-fault retries)", flush=True)
    goodput.activate(None)
    if tdir:
        tv_events.shutdown()
    bootstrap.shutdown()
    return task, served, len(done) + served
