"""Supervised serving replicas: the control-plane side of serving.

A *serving replica* is one :class:`~distributed_tensorflow_tpu.serving.
engine.InferenceEngine` driven by :func:`serving_replica` — a worker
function shaped exactly like the elastic trainer the recovery
supervisor already manages (examples/train_mnist.py elastic_worker):
module-level (picklable by reference), heartbeats once per engine step,
restartable from scratch at any instant. Run it under
``resilience.RecoverySupervisor`` and a SIGKILLed replica is detected,
its generation reformed, and the process respawned exactly like a dead
trainer — no supervisor changes needed.

**Zero dropped requests.** The replica appends one JSONL record per
COMPLETED request to ``served-<task>.jsonl`` (line-buffered, so a
SIGKILL loses at most the line in flight). On (re)start it reads that
file back, treats every recorded id as done, and re-queues the rest —
in-flight requests at kill time are simply re-served by the next
incarnation. Greedy decode over fixed weights is deterministic, so a
request that was half-decoded (or torn mid-write) re-generates the SAME
tokens; ``tools/chaos_sweep.py --serve`` gates both the completeness of
the union and the cross-generation consistency of any duplicates.

**Chaos.** Besides process-level SIGKILLs, the engine's ``serve.step``
fault site can raise mid-load; the replica retries the step under a
RetryPolicy (the site fires before any state mutation, so a retry is
always safe).
"""

from __future__ import annotations

import json
import os
import random

from distributed_tensorflow_tpu.serving.scheduler import Request


def seeded_requests(seed: int, n: int, vocab_size: int, *,
                    prompt_range: tuple = (4, 12),
                    new_tokens_range: tuple = (2, 10)) -> list[Request]:
    """Deterministic synthetic workload (the resilience/faults.py
    seeding discipline: a string-seeded stream, stable across
    processes/runs) — every replica incarnation regenerates the SAME
    request set from the seed."""
    rng = random.Random(f"dtx-serve:{seed}")
    out = []
    for i in range(n):
        plen = rng.randrange(*prompt_range)
        out.append(Request(
            id=f"r{i:04d}",
            tokens=tuple(rng.randrange(vocab_size) for _ in range(plen)),
            max_new_tokens=rng.randrange(*new_tokens_range)))
    return out


def completed_ids(path: str) -> dict[str, list]:
    """``{request_id: tokens}`` from a replica's completion log;
    torn trailing lines (SIGKILL mid-write) are skipped."""
    out: dict[str, list] = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue                  # torn line: not completed
                if "id" in rec:
                    out[rec["id"]] = rec.get("tokens", [])
    except OSError:
        pass
    return out


def serving_replica(run_dir: str, n_requests: int, seed: int,
                    vocab_size: int = 256, *, max_retries: int = 50,
                    engine_kwargs: dict | None = None,
                    ckpt_dir: str | None = None,
                    step_delay_s: float = 0.0):
    """One generation of one supervised serving replica.

    Serves the seeded workload to completion, heartbeating every engine
    step; restartable at any point via the completion log.
    ``step_delay_s`` paces the step loop (models network/request-bound
    serving; the chaos sweep uses it so a step-targeted SIGKILL has a
    real window to land in). Returns ``(task_index,
    n_served_this_generation, n_total_completed)``."""
    from distributed_tensorflow_tpu.cluster import bootstrap, elastic

    # join the distributed runtime exactly like an elastic trainer:
    # the coordination control plane (and, on the CPU test backend, the
    # gloo-configured runtime the spawn harness expects) needs the
    # client BEFORE the first jax computation
    runtime = bootstrap.initialize()
    import contextlib

    import jax
    if runtime.num_processes <= 1:
        # a single-replica supervised run never joins a distributed
        # world, but the spawn harness pre-configures gloo collectives
        # (which this jaxlib rejects without a distributed client) —
        # reset before the first computation initializes the backend
        with contextlib.suppress(Exception):
            jax.config.update("jax_cpu_collectives_implementation",
                              "none")

    from distributed_tensorflow_tpu.models.transformer import (
        TransformerConfig, TransformerLM)
    from distributed_tensorflow_tpu.resilience.faults import FaultInjected
    from distributed_tensorflow_tpu.serving.engine import InferenceEngine
    from distributed_tensorflow_tpu.telemetry import events as tv_events

    task = runtime.process_id
    n_replicas = max(1, runtime.num_processes)
    tdir = os.environ.get(tv_events.ENV_TELEMETRY_DIR)
    if tdir:
        tv_events.configure(tdir, process_id=task)
    # live goodput ledger: serve steps are goodput, replayed tokens are
    # preempt_replay badput; everything before the first step is
    # startup. Exported through the registry (goodput/* gauges) and, in
    # the event files, re-derivable fleet-wide by the supervisor's
    # export tick.
    from distributed_tensorflow_tpu.telemetry import goodput
    goodput.activate(goodput.GoodputLedger())

    cfg = TransformerConfig.tiny(max_seq_len=64)
    kwargs = dict(num_blocks=48, block_size=8, max_slots=4,
                  max_prompt_len=16, queue_capacity=n_requests + 1)
    kwargs.update(engine_kwargs or {})
    if ckpt_dir:
        engine = InferenceEngine.from_checkpoint(cfg, ckpt_dir, **kwargs)
    else:
        # seed-deterministic weights: every incarnation serves the same
        # model, so re-served requests generate identical tokens
        params = TransformerLM(cfg).init(
            jax.random.PRNGKey(0),
            jax.numpy.zeros((1, 8), jax.numpy.int32))["params"]
        engine = InferenceEngine(cfg, params, **kwargs)

    log_path = os.path.join(run_dir, f"served-{task}.jsonl")
    done = completed_ids(log_path)
    # replicas statically shard the workload (request i -> replica
    # i mod N); the union of all replicas' completion logs must cover
    # the full request set — the chaos sweep's zero-dropped gate
    mine = [r for i, r in enumerate(
        seeded_requests(seed, n_requests, vocab_size))
        if i % n_replicas == task]
    todo = [r for r in mine if r.id not in done]
    gen = elastic.generation()
    print(f"[gen {gen} serve-{task}] {len(done)} already served, "
          f"{len(todo)} of {len(mine)} to go", flush=True)
    for r in todo:
        engine.submit(r)

    served = 0
    step = 0
    retries = 0
    import time as _time

    # line-buffered like the event log: a SIGKILL loses at most one line
    with open(log_path, "a", buffering=1) as log:
        while not engine.scheduler.idle:
            elastic.heartbeat(step)
            if step_delay_s:
                _time.sleep(step_delay_s)
            try:
                finished = engine.step()
            except FaultInjected:
                retries += 1
                if retries > max_retries:
                    raise
                continue              # site fired pre-mutation: retry
            for rec in finished:
                log.write(json.dumps({
                    "id": rec["id"], "tokens": rec["tokens"],
                    "prompt_tokens": rec["prompt_tokens"],
                    "latency_s": round(rec["latency_s"], 6),
                    "gen": gen}) + "\n")
                served += 1
            step += 1
    elastic.heartbeat(step)
    print(f"[gen {gen} serve-{task}] served {served} "
          f"({len(done) + served}/{len(mine)} of this replica's shard), "
          f"{retries} injected-fault retries", flush=True)
    goodput.activate(None)
    if tdir:
        tv_events.shutdown()
    bootstrap.shutdown()
    return task, served, len(done) + served
