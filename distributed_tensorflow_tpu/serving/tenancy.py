"""Multi-tenant serving policy: priority classes, quotas, fair shares.

The policy layer under ``serving/router.py`` — everything here is a
pure, clock-explicit unit (no wall reads, no I/O) so the admission math
is testable by hand and deterministic across router incarnations:

- :class:`TenantConfig` declares one tenant: its **priority class**
  (``interactive`` or ``batch``), its **weight** (relative share under
  contention), its **token-bucket quota** (rate + burst — the per-tenant
  isolation boundary) and its **SLO thresholds** (which ride the
  declarative ``telemetry/slo.py`` burn-window machinery per tenant).
- :class:`TokenBucket` is the quota meter: ``take(cost, now)`` either
  charges or refuses, with refill purely a function of the two
  timestamps.
- :func:`fair_shares` is weighted max-min fairness (progressive
  filling): under a token budget each contending tenant gets its
  weight-proportional share, and a tenant demanding LESS than its share
  donates the surplus back to the still-hungry ones. The router calls
  it twice per admission tick — once for the interactive class, once
  for batch over whatever budget remains — which is exactly the
  "batch sheds first" pressure ordering.
- :class:`TenancyController` composes the two: ``charge`` answers
  quota, ``plan_tick`` answers weighted-fair admission with batch
  subordinated to interactive EXCEPT for batch tenants the caller has
  aged past their starvation deadline (``aged``) — the anti-starvation
  promotion that keeps batch inside its own (longer) SLO.

Rejections and sheds are *observable by cause*: the controller only
returns decisions; the router stamps them onto ``serve.reject`` /
``router.shed`` events with ``tenant`` + ``cause``, which is what
``obs_report``/``health_report`` itemize and the tenant-aware
autoscaler (resilience/autoscaler.py) attributes scale decisions to.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Iterable

#: The two priority classes, strongest first. Interactive admits ahead
#: of batch whenever the token budget cannot cover both.
PRIORITY_CLASSES = ("interactive", "batch")

#: ``serve.reject`` / shed causes the router stamps.
REJECT_CAUSES = ("quota", "overload", "shed")


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """One tenant's declarative serving contract."""

    name: str
    #: priority class: ``interactive`` (latency-sensitive, admitted
    #: first) or ``batch`` (throughput work, shed first under pressure)
    pclass: str = "interactive"
    #: relative share under contention WITHIN its class (weighted
    #: max-min — see :func:`fair_shares`)
    weight: float = 1.0
    #: token-bucket refill rate (prompt+generation tokens per second);
    #: ``inf`` = unmetered
    quota_tokens_per_s: float = math.inf
    #: bucket capacity (burst); default 4s of refill
    quota_burst: "float | None" = None
    #: per-tenant p99 latency SLO threshold (seconds)
    slo_latency_s: float = 0.5
    #: availability objective for the latency SLO
    slo_objective: float = 0.99
    #: a queued BATCH request older than this fraction of
    #: ``slo_latency_s`` is promoted into the interactive admission
    #: round — batch defers first, but never starves past its own SLO
    starvation_frac: float = 0.5

    def __post_init__(self):
        if self.pclass not in PRIORITY_CLASSES:
            raise ValueError(f"tenant {self.name}: pclass="
                             f"{self.pclass!r}; expected one of "
                             f"{PRIORITY_CLASSES}")
        if self.weight <= 0:
            raise ValueError(f"tenant {self.name}: weight must be > 0")
        if self.quota_burst is None:
            burst = (self.quota_tokens_per_s * 4.0
                     if math.isfinite(self.quota_tokens_per_s)
                     else math.inf)
            object.__setattr__(self, "quota_burst", burst)

    @property
    def starvation_deadline_s(self) -> float:
        return self.slo_latency_s * self.starvation_frac


def default_tenants() -> "tuple[TenantConfig, ...]":
    """The two-tenant shape the examples/benches drive: one
    latency-sensitive interactive tenant, one throughput batch tenant
    with a longer SLO and half the weight."""
    return (
        TenantConfig("acme", pclass="interactive", weight=2.0,
                     slo_latency_s=0.5),
        TenantConfig("batchco", pclass="batch", weight=1.0,
                     slo_latency_s=4.0),
    )


class TokenBucket:
    """Deterministic token-bucket quota meter (explicit clock)."""

    def __init__(self, rate: float, burst: float, *, now: float = 0.0):
        self.rate = float(rate)
        self.burst = float(burst)
        self._level = float(burst)
        self._stamp = float(now)

    def _refill(self, now: float):
        if now > self._stamp and math.isfinite(self.burst):
            self._level = min(self.burst,
                              self._level + (now - self._stamp)
                              * self.rate)
        self._stamp = max(self._stamp, now)

    def level(self, now: float) -> float:
        self._refill(now)
        return self._level

    def take(self, cost: float, now: float) -> bool:
        """Charge ``cost`` tokens; False (and no charge) if the bucket
        cannot cover it."""
        if not math.isfinite(self.burst):
            return True
        self._refill(now)
        if cost > self._level:
            return False
        self._level -= cost
        return True


def fair_shares(demands: "dict[str, float]",
                weights: "dict[str, float]",
                budget: float) -> "dict[str, float]":
    """Weighted max-min fair allocation (progressive filling).

    Repeatedly splits the remaining budget among still-unsatisfied
    tenants in proportion to their weights; a tenant whose demand fits
    inside its share is granted exactly its demand and its surplus
    returns to the pool. Hand-computable — the unit tests work examples
    by hand — and order-independent (a pure function of the three
    inputs).
    """
    alloc = {t: 0.0 for t in demands}
    remaining = {t: d for t, d in demands.items() if d > 0}
    budget = max(0.0, float(budget))
    while remaining and budget > 1e-12:
        wsum = sum(weights.get(t, 1.0) for t in remaining)
        share = {t: budget * weights.get(t, 1.0) / wsum
                 for t in remaining}
        satisfied = [t for t in remaining if remaining[t] <= share[t]]
        if not satisfied:
            # everyone is budget-bound: grant the proportional share
            for t in remaining:
                alloc[t] += share[t]
            return alloc
        for t in satisfied:
            alloc[t] += remaining[t]
            budget -= remaining[t]
            del remaining[t]
    return alloc


class TenancyController:
    """Quota + weighted-fair admission state for one router.

    All methods take explicit ``now`` timestamps (seconds, any
    monotonic origin) — determinism is what makes the chaos seeds
    replayable and the unit math checkable.
    """

    def __init__(self, tenants: Iterable[TenantConfig], *,
                 now: float = 0.0):
        self.tenants: "dict[str, TenantConfig]" = {}
        self._buckets: "dict[str, TokenBucket]" = {}
        self.counters: "dict[str, dict]" = {}
        for t in tenants:
            if t.name in self.tenants:
                raise ValueError(f"duplicate tenant {t.name!r}")
            self.tenants[t.name] = t
            self._buckets[t.name] = TokenBucket(
                t.quota_tokens_per_s, t.quota_burst, now=now)
            self.counters[t.name] = {
                "admitted": 0, "rejected": {}, "sheds": 0,
                "tokens_admitted": 0}

    def tenant(self, name: str) -> TenantConfig:
        return self.tenants[name]

    @staticmethod
    def cost_of(request) -> int:
        """Admission cost of one request: prompt plus the generation
        budget it reserves."""
        return len(request.tokens) + int(request.max_new_tokens)

    # -- quota ------------------------------------------------------------
    def charge(self, tenant: str, cost: float, now: float) -> bool:
        """Try to charge ``cost`` tokens against the tenant's quota
        bucket. A refusal is a ``cause="quota"`` rejection — the caller
        stamps and surfaces it."""
        ok = self._buckets[tenant].take(cost, now)
        c = self.counters[tenant]
        if ok:
            c["admitted"] += 1
            c["tokens_admitted"] += int(cost)
        else:
            c["rejected"]["quota"] = c["rejected"].get("quota", 0) + 1
        return ok

    def note_reject(self, tenant: str, cause: str):
        c = self.counters[tenant]["rejected"]
        c[cause] = c.get(cause, 0) + 1

    def note_shed(self, tenant: str):
        self.counters[tenant]["sheds"] += 1

    def quota_level(self, tenant: str, now: float) -> float:
        return self._buckets[tenant].level(now)

    def quota_utilization(self, tenant: str, now: float) -> "float | None":
        """1 - level/burst: how much of the burst allowance is
        currently spent (None for unmetered tenants)."""
        t = self.tenants[tenant]
        if not math.isfinite(t.quota_burst) or t.quota_burst <= 0:
            return None
        return round(1.0 - self._buckets[tenant].level(now)
                     / t.quota_burst, 4)

    # -- weighted-fair admission ------------------------------------------
    def plan_tick(self, demands: "dict[str, float]", *, budget: float,
                  aged: "set | frozenset" = frozenset()
                  ) -> "dict[str, float]":
        """Token allocation for one admission tick.

        ``demands`` maps tenant -> queued token demand. Interactive
        tenants (plus any batch tenant in ``aged`` — queued past its
        starvation deadline) split the budget weighted-fair first;
        batch divides whatever remains. Under pressure batch therefore
        sheds (defers) first, by construction.
        """
        weights = {n: t.weight for n, t in self.tenants.items()}
        first = {n: d for n, d in demands.items()
                 if self.tenants[n].pclass == "interactive"
                 or n in aged}
        second = {n: d for n, d in demands.items() if n not in first}
        alloc = fair_shares(first, weights, budget)
        left = budget - sum(alloc.values())
        alloc.update(fair_shares(second, weights, left))
        return {n: alloc.get(n, 0.0) for n in demands}

    # -- reporting --------------------------------------------------------
    def summary(self, now: float) -> "dict[str, dict]":
        out = {}
        for name, t in self.tenants.items():
            c = self.counters[name]
            out[name] = {
                "pclass": t.pclass, "weight": t.weight,
                "admitted": c["admitted"],
                "rejected": dict(c["rejected"]),
                "sheds": c["sheds"],
                "tokens_admitted": c["tokens_admitted"],
                "quota_utilization": self.quota_utilization(name, now),
            }
        return out


# -- per-tenant SLOs --------------------------------------------------------

def tenant_slos(cfg: TenantConfig, *, windows=None) -> list:
    """The tenant's declarative SLO set (telemetry/slo.py objects),
    named ``<tenant>/p99_latency`` so verdicts never collide across
    tenants."""
    from distributed_tensorflow_tpu.telemetry import slo as slo_lib
    return [slo_lib.SLO(name=f"{cfg.name}/p99_latency",
                        metric="latency",
                        objective=cfg.slo_objective,
                        threshold_s=cfg.slo_latency_s,
                        windows=windows
                        or slo_lib.DEFAULT_BURN_WINDOWS)]


def partition_records(records: "list[dict]") -> "dict[str, list]":
    """Split SLO completion records by their ``tenant`` stamp (records
    without one group under ``"-"``)."""
    out: "dict[str, list]" = {}
    for r in records:
        out.setdefault(r.get("tenant") or "-", []).append(r)
    return out


def evaluate_tenants(records: "list[dict]",
                     tenants: Iterable[TenantConfig], *,
                     windows=None, now=None) -> "dict[str, dict]":
    """Per-tenant SLO verdicts over a mixed completion stream: each
    tenant's records are evaluated against ITS OWN burn windows and
    threshold — one tenant's overrun cannot fire another's SLO."""
    from distributed_tensorflow_tpu.telemetry import slo as slo_lib
    by_tenant = partition_records(records)
    out: "dict[str, dict]" = {}
    for cfg in tenants:
        recs = by_tenant.get(cfg.name, [])
        if not recs:
            continue
        w = windows
        if w is None:
            span = ((recs[-1]["wall"] - recs[0]["wall"])
                    if len(recs) > 1 else 1.0)
            w = slo_lib.windows_for_span(max(span, 1e-3))
        out[cfg.name] = slo_lib.evaluate_records(
            recs, tenant_slos(cfg, windows=w), now=now)
    return out
