"""TPU topology, device assignment, and mesh construction.

TPU-native redesign of the reference's topology layer
(reference: tensorflow/python/tpu/topology.py:41 ``Topology``,
tensorflow/python/tpu/device_assignment.py:70 ``DeviceAssignment``, per
SURVEY.md §2.6). Instead of mapping logical replicas onto physical cores by
hand-building ring orders for the torus, the TPU-native design delegates
device ordering to ``jax.make_mesh`` (which knows the ICI fabric) and exposes
the result as a ``jax.sharding.Mesh`` — the single object every parallelism
axis (dp/fsdp/tp/sp/pp/ep) hangs off.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical logical axis names, in priority order. Outer axes are the ones
# whose collectives tolerate lower bandwidth (DCN), inner axes want ICI.
DATA_AXIS = "dp"          # data parallel (gradient allreduce)
FSDP_AXIS = "fsdp"        # fully-sharded data parallel (param all-gather)
TENSOR_AXIS = "tp"        # tensor/model parallel (activation collectives)
SEQUENCE_AXIS = "sp"      # sequence/context parallel (ring attention)
PIPELINE_AXIS = "pp"      # pipeline parallel (ppermute between stages)
EXPERT_AXIS = "ep"        # expert parallel (all_to_all dispatch)

ALL_AXES = (DATA_AXIS, FSDP_AXIS, TENSOR_AXIS, SEQUENCE_AXIS, PIPELINE_AXIS,
            EXPERT_AXIS)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Physical accelerator topology of the current job.

    Counterpart of ``tf.tpu.experimental.Topology``
    (reference: tensorflow/python/tpu/topology.py:41): the reference
    deserializes a TopologyProto returned by the ``ConfigureDistributedTPU``
    op; here the information comes straight from the PJRT client
    (``jax.devices()``), which already reflects libtpu's view of the slice.
    """

    devices: tuple  # all global devices, PJRT enumeration order
    num_processes: int
    process_index: int
    platform: str

    @classmethod
    def detect(cls, devices: Sequence | None = None) -> "Topology":
        devices = tuple(devices if devices is not None else jax.devices())
        return cls(
            devices=devices,
            num_processes=jax.process_count(),
            process_index=jax.process_index(),
            platform=devices[0].platform if devices else "none",
        )

    @property
    def num_devices(self) -> int:
        return len(self.devices)

    @property
    def num_devices_per_process(self) -> int:
        return max(1, self.num_devices // max(1, self.num_processes))

    def local_devices(self) -> list:
        return [d for d in self.devices
                if getattr(d, "process_index", 0) == self.process_index]

    @property
    def mesh_shape(self) -> tuple:
        """Physical mesh shape (x, y, z, core) when the backend reports
        coords; falls back to a flat (num_devices,) shape on CPU/GPU."""
        coords = [getattr(d, "coords", None) for d in self.devices]
        if any(c is None for c in coords):
            return (self.num_devices,)
        dims = tuple(max(c[i] for c in coords) + 1 for i in range(len(coords[0])))
        cores = max(getattr(d, "core_on_chip", 0) for d in self.devices) + 1
        return dims + (cores,)


@dataclasses.dataclass(frozen=True)
class DeviceAssignment:
    """Maps logical replicas to physical devices.

    Counterpart of tensorflow/python/tpu/device_assignment.py:70. The
    reference computes per-replica core rings (``_ring_3d``,
    device_assignment.py:241) because TF's TPUStrategy launches one program
    per replica; under single-program SPMD the assignment degenerates to "in
    which mesh position does each logical replica live", which is what this
    class records. Kept as an explicit object for API parity and for the
    coordinator/PS path, which still addresses individual devices.
    """

    topology: Topology
    num_replicas: int
    num_cores_per_replica: int = 1

    @classmethod
    def build(cls, topology: Topology | None = None,
              num_replicas: int | None = None,
              num_cores_per_replica: int = 1) -> "DeviceAssignment":
        topology = topology or Topology.detect()
        if num_replicas is None:
            num_replicas = topology.num_devices // num_cores_per_replica
        if num_replicas * num_cores_per_replica > topology.num_devices:
            raise ValueError(
                f"Requested {num_replicas} replicas x {num_cores_per_replica} "
                f"cores > {topology.num_devices} devices")
        return cls(topology, num_replicas, num_cores_per_replica)

    def device(self, replica: int, logical_core: int = 0):
        idx = replica * self.num_cores_per_replica + logical_core
        return self.topology.devices[idx]

    def replica_devices(self, replica: int) -> list:
        base = replica * self.num_cores_per_replica
        return list(self.topology.devices[base:base + self.num_cores_per_replica])


def _normalize_axes(axes, num_devices: int):
    """Resolve an axis spec into (names, sizes), filling one -1 wildcard."""
    if isinstance(axes, Mapping):
        names = tuple(axes.keys())
        sizes = list(axes.values())
    else:
        names, sizes = zip(*axes)
        sizes = list(sizes)
    wild = [i for i, s in enumerate(sizes) if s == -1]
    if len(wild) > 1:
        raise ValueError("At most one axis size may be -1")
    if wild:
        known = math.prod(s for s in sizes if s != -1)
        if num_devices % known:
            raise ValueError(
                f"{num_devices} devices not divisible by fixed axes {known}")
        sizes[wild[0]] = num_devices // known
    if math.prod(sizes) != num_devices:
        raise ValueError(
            f"Mesh axes {dict(zip(names, sizes))} need {math.prod(sizes)} "
            f"devices but {num_devices} are available")
    return names, tuple(sizes)


def make_mesh(axes: Mapping[str, int] | Sequence[tuple] | None = None,
              *, devices: Sequence | None = None) -> Mesh:
    """Build a ``jax.sharding.Mesh`` over the slice.

    ``axes`` maps logical axis name -> size, e.g. ``{"dp": 4, "tp": 2}``;
    one size may be ``-1`` (inferred). Defaults to pure data parallelism over
    every device. Axis order is semantic: earlier axes are "outer" (their
    collectives cross the slower links on multi-host topologies), later axes
    are "inner" (mapped to the fastest ICI neighbourhoods by
    ``jax.make_mesh``'s device ordering).

    This replaces the reference's hand-built core rings
    (tensorflow/python/tpu/device_assignment.py:343) with the mesh-first
    design XLA GSPMD expects.
    """
    devs = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {DATA_AXIS: len(devs)}
    names, sizes = _normalize_axes(axes, len(devs))
    # Auto axis types: the framework works in GSPMD mode (sharding
    # constraints + propagation), not the explicit-sharding-in-types mode.
    # Older jax (< AxisType) has only GSPMD meshes — omit the kwarg there.
    axis_type_cls = getattr(jax.sharding, "AxisType", None)
    kwargs = ({"axis_types": (axis_type_cls.Auto,) * len(names)}
              if axis_type_cls is not None else {})
    if devices is None:
        try:
            return jax.make_mesh(sizes, names, **kwargs)
        except (ValueError, RuntimeError):
            pass  # fall through to explicit reshaping
    arr = np.asarray(devs, dtype=object).reshape(sizes)
    return Mesh(arr, names, **kwargs)


def mesh_axis_size(mesh: Mesh, *names: str) -> int:
    """Product of the sizes of ``names`` that exist on ``mesh``."""
    return math.prod(mesh.shape[n] for n in names if n in mesh.shape)


DCN_AXIS = "dcn"

# Axes over which input batches shard, outermost first. Every model's
# batch sharding and shard_map spec must derive from this one list.
DATA_AXES = (DCN_AXIS, DATA_AXIS, FSDP_AXIS)


def data_axes(mesh: Mesh) -> tuple:
    """The subset of DATA_AXES present on ``mesh`` (batch-sharding axes)."""
    return tuple(a for a in DATA_AXES if a in mesh.shape)


def attention_shard_spec(mesh: Mesh):
    """PartitionSpec for (batch, heads, seq, head_dim) attention operands:
    batch over the data axes, heads over tp, seq/head_dim unsharded.
    Single source of truth for every attention entry point (dense
    shard_map path and the sp ring/ulysses path)."""
    from jax.sharding import PartitionSpec as P
    batch_axes = data_axes(mesh)
    head_axis = TENSOR_AXIS if TENSOR_AXIS in mesh.shape else None
    return P(batch_axes if batch_axes else None, head_axis, None, None)


def make_hybrid_mesh(dcn_axes: Mapping[str, int],
                     ici_axes: Mapping[str, int],
                     *, devices: Sequence | None = None) -> Mesh:
    """Mesh spanning multiple TPU slices: ``dcn_axes`` cross the
    data-center network (slow, between slices), ``ici_axes`` stay inside
    a slice (fast). ≙ the reference's two-level
    ``HierarchicalCopyAllReduce`` / ``_build_nccl_hybrid`` topology split
    (reference: tensorflow/python/distribute/cross_device_ops.py:997,
    v1/all_reduce.py:710) — but expressed once in the mesh, so every
    collective GSPMD inserts is automatically hierarchical: reduce-scatter
    inside the slice over ICI, small cross-slice reduce over DCN.

    On real multi-slice TPU, uses ``mesh_utils.create_hybrid_device_mesh``
    (slice boundaries from PJRT); elsewhere (CPU testing, single slice)
    devices are grouped contiguously, outer axes slowest-varying — the
    same comm hierarchy shape without physical DCN.
    """
    from jax.experimental import mesh_utils

    if -1 in dcn_axes.values() and -1 in ici_axes.values():
        raise ValueError("only one -1 wildcard allowed across "
                         "dcn_axes + ici_axes")
    devs = list(devices if devices is not None else jax.devices())
    dcn_names, dcn_sizes = _normalize_axes(dcn_axes, math.prod(
        dcn_axes.values()) if -1 not in dcn_axes.values() else len(devs)
        // math.prod(ici_axes.values()))
    ici_names, ici_sizes = _normalize_axes(
        ici_axes, len(devs) // math.prod(dcn_sizes))
    names = dcn_names + ici_names
    # Auto axis types when the running jax has them (see make_mesh).
    axis_type_cls = getattr(jax.sharding, "AxisType", None)
    kwargs = ({"axis_types": (axis_type_cls.Auto,) * len(names)}
              if axis_type_cls is not None else {})

    multi_slice = len({getattr(d, "slice_index", 0) for d in devs}) > 1
    if multi_slice:
        # create_hybrid_device_mesh combines shapes elementwise, so pad
        # with 1s to keep the dcn axes distinct from the ici axes.
        ici_shape = (1,) * len(dcn_sizes) + tuple(ici_sizes)
        dcn_shape = tuple(dcn_sizes) + (1,) * len(ici_sizes)
        arr = mesh_utils.create_hybrid_device_mesh(
            ici_shape, dcn_shape, devices=devs)
        return Mesh(arr, names, **kwargs)
    arr = np.asarray(devs, dtype=object).reshape(dcn_sizes + ici_sizes)
    return Mesh(arr, names, **kwargs)
