"""Multi-process runtime bootstrap.

TPU-native replacement for the reference's server/context bootstrap
(reference: tensorflow/python/eager/context.py:1014 ``enable_collective_ops``
— which starts an in-process grpc server — and context.py:903
``configure_coordination_service``; SURVEY.md §3.2). On TPU there is no grpc
data plane to start: bootstrap is exactly ``jax.distributed.initialize``,
which connects every process to the TSL coordination service (heartbeats, KV
store, barriers) and exchanges PJRT device topology. Collectives then ride
ICI/DCN inside compiled XLA programs.
"""

from __future__ import annotations

import dataclasses
import os
import threading

import jax

from distributed_tensorflow_tpu.cluster import elastic
from distributed_tensorflow_tpu.cluster.resolver import (
    ClusterResolver,
    TFConfigClusterResolver,
)

_LOCK = threading.Lock()
_RUNTIME: "DistributedRuntime | None" = None


@dataclasses.dataclass(frozen=True)
class DistributedRuntime:
    """Facts about the initialized distributed runtime."""

    coordinator_address: str | None
    num_processes: int
    process_id: int
    initialized_jax_distributed: bool
    #: Elastic cluster generation (cluster/elastic.py): 0 for a job that
    #: has never been reformed by a recovery supervisor.
    generation: int = 0

    @property
    def is_chief(self) -> bool:
        return self.process_id == 0


def initialize(resolver: ClusterResolver | None = None,
               coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> DistributedRuntime:
    """Initialize the multi-process runtime (idempotent).

    Single-process (no cluster found): no-op, returns a local runtime.
    Multi-process: calls ``jax.distributed.initialize`` with facts from the
    resolver (default: ``TF_CONFIG`` then TPU-VM env), connecting this
    process to the coordination service — the TPU-native equivalent of the
    reference's grpc server + coordination-service startup
    (collective_all_reduce_strategy.py:507 ``_initialize_multi_worker``).
    """
    global _RUNTIME
    with _LOCK:
        if _RUNTIME is not None:
            return _RUNTIME

        if coordinator_address is None and resolver is None:
            resolver = _default_resolver()

        if resolver is not None:
            spec = resolver.cluster_spec()
            if coordinator_address is None:
                coordinator_address = resolver.master() or None
            if num_processes is None:
                num_processes = resolver.num_processes()
            if process_id is None:
                process_id = resolver.process_id() if spec else 0

        num_processes = num_processes or 1
        process_id = process_id or 0

        did_init = False
        if num_processes > 1 and coordinator_address:
            # CPU backend stands in for DCN in tests/CI: use gloo so
            # cross-process collectives actually execute (the TPU path
            # needs nothing — collectives ride ICI inside XLA programs).
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
            did_init = True

        _RUNTIME = DistributedRuntime(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            initialized_jax_distributed=did_init,
            generation=elastic.generation(),
        )
        return _RUNTIME


def _default_resolver() -> ClusterResolver | None:
    if os.environ.get("TF_CONFIG"):
        return TFConfigClusterResolver()
    if os.environ.get("TPU_WORKER_HOSTNAMES"):
        from distributed_tensorflow_tpu.cluster.resolver import TPUClusterResolver
        return TPUClusterResolver()
    return None


def runtime() -> DistributedRuntime:
    """The current runtime, initializing a local one if needed."""
    return _RUNTIME if _RUNTIME is not None else initialize()


def shutdown():
    """Tear down the coordination-service connection (tests)."""
    global _RUNTIME
    with _LOCK:
        if _RUNTIME is not None and _RUNTIME.initialized_jax_distributed:
            jax.distributed.shutdown()
        _RUNTIME = None
