"""Cluster discovery: ClusterSpec and resolvers.

TPU-native counterpart of the reference's
``tensorflow/python/distribute/cluster_resolver/`` package (SURVEY.md §2.4):

- ``ClusterSpec``              ≙ tf.train.ClusterSpec
- ``ClusterResolver``          ≙ cluster_resolver.py (abstract base)
- ``TFConfigClusterResolver``  ≙ tfconfig_cluster_resolver.py:48 — the
  ``TF_CONFIG`` env-JSON contract is kept verbatim so existing launch
  tooling keeps working.
- ``TPUClusterResolver``       ≙ tpu/tpu_cluster_resolver.py:95 — on TPU-VMs
  the reference queries the GCE metadata service; here discovery reads the
  TPU-VM environment variables libtpu/JAX already standardize
  (``TPU_WORKER_HOSTNAMES``, ``TPU_WORKER_ID``, ``MEGASCALE_*``) with a
  graceful single-host fallback, since zero-egress environments cannot hit
  the metadata server.

The resolver produces *control-plane* facts only (who participates, who is
the coordinator). The data plane needs none of this — SPMD execution replaces
the reference's grpc WorkerService (SURVEY.md §5.8).
"""

from __future__ import annotations

import json
import os
from typing import Mapping, Sequence

CHIEF = "chief"
WORKER = "worker"
PS = "ps"
EVALUATOR = "evaluator"


class ClusterSpec:
    """A static description of job-name -> task addresses.

    Same shape as ``tf.train.ClusterSpec``: ``{"worker": ["h0:port", ...],
    "ps": [...]}``.
    """

    def __init__(self, cluster: Mapping[str, Sequence[str] | Mapping[int, str]]):
        self._cluster: dict[str, list[str]] = {}
        for job, tasks in dict(cluster).items():
            if isinstance(tasks, Mapping):
                size = max(tasks.keys()) + 1 if tasks else 0
                lst = [""] * size
                for i, addr in tasks.items():
                    lst[int(i)] = addr
                self._cluster[job] = lst
            else:
                self._cluster[job] = list(tasks)

    def as_dict(self) -> dict[str, list[str]]:
        return {k: list(v) for k, v in self._cluster.items()}

    @property
    def jobs(self) -> list[str]:
        return sorted(self._cluster)

    def num_tasks(self, job: str) -> int:
        return len(self._cluster.get(job, ()))

    def task_addresses(self, job: str) -> list[str]:
        if job not in self._cluster:
            raise ValueError(f"No such job: {job!r}")
        return list(self._cluster[job])

    def task_address(self, job: str, task: int) -> str:
        return self.task_addresses(job)[task]

    @property
    def num_total_tasks(self) -> int:
        return sum(len(v) for v in self._cluster.values())

    def __bool__(self) -> bool:
        return bool(self._cluster)

    def __eq__(self, other) -> bool:
        return isinstance(other, ClusterSpec) and self.as_dict() == other.as_dict()

    def __repr__(self) -> str:
        return f"ClusterSpec({self._cluster!r})"


def validate_cluster_spec(spec: ClusterSpec, task_type: str, task_id: int):
    """≙ multi_worker_util._validate_cluster_spec (multi_worker_util.py:52)."""
    if task_type and task_type not in (*spec.jobs, EVALUATOR):
        raise ValueError(f"task_type {task_type!r} not in cluster spec {spec!r}")
    if spec.num_tasks(CHIEF) > 1:
        raise ValueError("There must be at most one 'chief' job.")
    if task_type in spec.jobs and task_id >= spec.num_tasks(task_type):
        raise ValueError(
            f"task_id {task_id} out of range for job {task_type!r} "
            f"({spec.num_tasks(task_type)} tasks)")


class ClusterResolver:
    """Abstract cluster resolver (≙ cluster_resolver.py base, SURVEY §2.4)."""

    task_type: str | None = None
    task_id: int | None = None
    rpc_layer: str | None = None

    def cluster_spec(self) -> ClusterSpec:
        raise NotImplementedError

    def master(self, task_type: str | None = None, task_id: int | None = None
               ) -> str:
        """Address of the coordination-service leader ("master" kept for API
        parity). Empty string means local/single-process."""
        spec = self.cluster_spec()
        if task_type is not None and task_id is not None:
            return spec.task_address(task_type, task_id)
        if not spec:
            return ""
        return coordinator_address(spec)

    def num_accelerators(self) -> int:
        import jax
        return len(jax.local_devices())

    @property
    def environment(self) -> str:
        return ""

    # -- derived facts ----------------------------------------------------
    def is_chief(self) -> bool:
        spec = self.cluster_spec()
        if not spec:
            return True
        if not self.task_type:
            # part of a cluster but with no declared task: this process
            # cannot claim chief-only duties (checkpoint writes etc.)
            return False
        return is_chief(spec, self.task_type,
                        self.task_id if self.task_id is not None else 0)

    def num_processes(self) -> int:
        spec = self.cluster_spec()
        if not spec:
            return 1
        return (spec.num_tasks(CHIEF) + spec.num_tasks(WORKER)) or 1

    def process_id(self) -> int:
        spec = self.cluster_spec()
        if not spec:
            return 0
        return id_in_cluster(spec, self.task_type or WORKER,
                             self.task_id if self.task_id is not None else 0)


class SimpleClusterResolver(ClusterResolver):
    """Wraps a static ClusterSpec."""

    def __init__(self, cluster_spec: ClusterSpec, task_type: str = "",
                 task_id: int = 0, rpc_layer: str | None = None,
                 environment: str = ""):
        self._cluster_spec = cluster_spec
        self.task_type = task_type
        self.task_id = task_id
        self.rpc_layer = rpc_layer
        self._environment = environment
        if cluster_spec and task_type:
            validate_cluster_spec(cluster_spec, task_type, task_id)

    def cluster_spec(self) -> ClusterSpec:
        return self._cluster_spec

    @property
    def environment(self) -> str:
        return self._environment


class TFConfigClusterResolver(ClusterResolver):
    """Parses the ``TF_CONFIG`` environment JSON.

    Contract (kept bit-for-bit from the reference,
    tfconfig_cluster_resolver.py:38-45):

        TF_CONFIG='{"cluster": {"worker": ["h0:2222", "h1:2222"]},
                    "task": {"type": "worker", "index": 1}}'
    """

    def __init__(self, task_type: str | None = None, task_id: int | None = None,
                 rpc_layer: str | None = None):
        self._override_task_type = task_type
        self._override_task_id = task_id
        self.rpc_layer = rpc_layer
        tf_config = self._load()
        task = tf_config.get("task", {})
        self.task_type = (task_type if task_type is not None
                          else task.get("type"))
        self.task_id = (task_id if task_id is not None
                        else int(task.get("index", 0)))

    @staticmethod
    def _load() -> dict:
        raw = os.environ.get("TF_CONFIG", "")
        if not raw:
            return {}
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise ValueError(f"Malformed TF_CONFIG: {e}") from e

    def cluster_spec(self) -> ClusterSpec:
        return ClusterSpec(self._load().get("cluster", {}))

    @property
    def environment(self) -> str:
        return self._load().get("environment", "")


class TPUClusterResolver(ClusterResolver):
    """Discovers the TPU slice from the TPU-VM environment.

    ≙ tensorflow/python/tpu/tpu_cluster_resolver.py:95 (SURVEY §2.4). The
    reference talks to the Cloud TPU API / GCE metadata service; TPU-VM
    runtimes (and JAX's own bootstrap) surface the same facts as env vars,
    which also work with zero egress:

      - ``TPU_WORKER_HOSTNAMES``: comma-separated host list
      - ``TPU_WORKER_ID``: this host's index
      - ``MEGASCALE_COORDINATOR_ADDRESS`` (multi-slice)

    ``TPUClusterResolver.connect()`` (≙ tpu_cluster_resolver.py:111) is the
    one-call bootstrap: resolve + ``jax.distributed`` init + mesh detect.
    """

    COORD_PORT = 8476  # jax.distributed default coordination port

    def __init__(self, tpu: str | None = None, task_type: str | None = None,
                 task_id: int | None = None):
        self._tpu = tpu
        hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
        self._hosts = [h for h in hostnames.split(",") if h]
        self.task_type = task_type if task_type is not None else WORKER
        self.task_id = (task_id if task_id is not None
                        else int(os.environ.get("TPU_WORKER_ID", 0)))

    def cluster_spec(self) -> ClusterSpec:
        if not self._hosts:
            return ClusterSpec({})
        return ClusterSpec({
            WORKER: [f"{h}:{self.COORD_PORT}" for h in self._hosts]})

    def master(self, task_type=None, task_id=None) -> str:
        ms = os.environ.get("MEGASCALE_COORDINATOR_ADDRESS")
        if ms:
            return ms if ":" in ms else f"{ms}:{self.COORD_PORT}"
        return super().master(task_type, task_id)

    def get_tpu_system_metadata(self):
        """≙ tpu_cluster_resolver.py:326: summary of the TPU system."""
        from distributed_tensorflow_tpu.cluster.topology import Topology
        topo = Topology.detect()
        return {
            "num_cores": topo.num_devices,
            "num_hosts": topo.num_processes,
            "devices": topo.devices,
            "topology": topo.mesh_shape,
        }

    @classmethod
    def connect(cls, tpu: str | None = None):
        """One-call bootstrap (≙ TPUClusterResolver.connect,
        tpu_cluster_resolver.py:111): initialize the distributed runtime and
        return the resolver."""
        from distributed_tensorflow_tpu.cluster import bootstrap
        resolver = cls(tpu=tpu)
        bootstrap.initialize(resolver)
        return resolver


# ---------------------------------------------------------------------------
# multi_worker_util equivalents (≙ multi_worker_util.py, SURVEY §2.4)
# ---------------------------------------------------------------------------

def is_chief(spec: ClusterSpec, task_type: str, task_id: int) -> bool:
    """≙ multi_worker_util.is_chief (multi_worker_util.py:108)."""
    if not spec:
        return True
    if spec.num_tasks(CHIEF):
        return task_type == CHIEF
    return task_type == WORKER and task_id == 0


def coordinator_address(spec: ClusterSpec) -> str:
    """Leader for the coordination service
    (≙ multi_worker_util.collective_leader/coordination_leader,
    multi_worker_util.py:148/:182): chief:0 if present, else worker:0."""
    if spec.num_tasks(CHIEF):
        return spec.task_address(CHIEF, 0)
    if spec.num_tasks(WORKER):
        return spec.task_address(WORKER, 0)
    return ""


def id_in_cluster(spec: ClusterSpec, task_type: str, task_id: int) -> int:
    """Dense process index (≙ multi_worker_util.id_in_cluster,
    multi_worker_util.py:232): chief=0, workers follow."""
    if task_type == CHIEF:
        return 0
    if task_type == WORKER:
        return task_id + spec.num_tasks(CHIEF)
    if task_type == EVALUATOR:
        return 0  # evaluator is its own single-task world
    raise ValueError(f"Unsupported task_type {task_type!r}")


def worker_count(spec: ClusterSpec, task_type: str = WORKER) -> int:
    """≙ multi_worker_util.worker_count."""
    if task_type == EVALUATOR:
        return spec.num_tasks(EVALUATOR)
    return spec.num_tasks(CHIEF) + spec.num_tasks(WORKER)
