"""Coordination-service surface: KV store, barriers, liveness.

TPU-native equivalent of the reference's coordination service
(reference: third_party/xla/.../tsl/distributed_runtime/coordination/
coordination_service.h — task liveness via heartbeats, a distributed KV
store, barriers, and error propagation; SURVEY.md §2.7). The reference
exposes it to Python only indirectly (context.configure_coordination_service);
here it is a first-class API because the rest of the framework builds on
it: multi-host checkpoint commit barriers (checkpoint/checkpoint.py),
preemption agreement (checkpoint/failure_handling.py), and the remote
coordinator's closure/result channel (coordinator/remote_dispatch.py).

Single-process: every operation is served by an in-process fallback with
identical semantics (same code runs under 1 or N processes).
Multi-process: operations delegate to the TSL coordination service that
``jax.distributed.initialize`` connected us to (bootstrap.initialize).
"""

from __future__ import annotations

import collections
import re
import threading
import time

from distributed_tensorflow_tpu.cluster import elastic
from distributed_tensorflow_tpu.resilience import faults


class CoordinationError(RuntimeError):
    """A coordination-service operation failed (timeout, peer error)."""


def _parse_task_id(node) -> "int | None":
    """Task id from a live-nodes entry.

    Formats seen from TSL: int, ``"/job:jax_worker/task:3"``, ``"3"``.
    The task number is parsed from the trailing ``task:<n>`` field (NOT
    by collecting digits — a job name containing a digit, e.g.
    ``jax_worker_2``, must not mangle the id). Unrecognized formats
    return None.
    """
    if isinstance(node, int):
        return node
    s = str(node)
    m = re.search(r"task:(\d+)\s*$", s)
    if m:
        return int(m.group(1))
    if s.strip().isdigit():
        return int(s.strip())
    return None


class BarrierTimeoutError(CoordinationError):
    """``barrier`` timed out waiting for peers — likely a hung or dead
    task (≙ the reference's BarrierError / DeadlineExceeded status)."""


class _LocalService:
    """In-process KV/barrier service with TSL-equivalent semantics.

    Also the backend of the simulated-fleet harness
    (testing/fleet_sim.py), where hundreds of worker THREADS share one
    instance — which is why blocked readers wait on **per-key**
    conditions: the original single shared condition made every ``set``
    wake every blocked reader of every key (O(writers × waiters)
    spurious wakeups per round — at N=1000 simulated workers the reform
    storm, where every worker blocks on the new generation's config key
    while heartbeats keep streaming in, was the worst scaling offender
    the harness exposed). ``stats["waiters_woken"]`` counts real
    wakeups so the fix is testable.
    """

    def __init__(self):
        self._kv: dict[str, bytes] = {}
        self._lock = threading.Lock()
        # key -> [Condition, waiter_count]; entries exist only while a
        # reader is blocked on that key
        self._waiters: dict[str, list] = {}
        self._barriers: dict[str, dict] = {}
        #: coarse service-side counters (ops, wakeups); reads/updates
        #: are lock-protected where it matters for tests
        self.stats = collections.Counter()

    def _notify_key(self, key: str):
        """Wake only the readers blocked on ``key`` (caller holds
        ``_lock``)."""
        w = self._waiters.get(key)
        if w is not None:
            self.stats["waiters_woken"] += w[1]
            w[0].notify_all()

    def set(self, key: str, value: bytes, *, allow_overwrite: bool = True):
        with self._lock:
            if not allow_overwrite and key in self._kv:
                raise CoordinationError(f"key {key!r} already exists")
            self._kv[key] = value
            self.stats["set"] += 1
            self._notify_key(key)

    def get(self, key: str, timeout_s: float) -> bytes:
        deadline = time.monotonic() + timeout_s
        with self._lock:
            self.stats["get"] += 1
            v = self._kv.get(key)
            if v is not None:               # fast path: no condition
                return v
            w = self._waiters.get(key)
            if w is None:
                w = self._waiters[key] = [
                    threading.Condition(self._lock), 0]
            w[1] += 1
            try:
                while key not in self._kv:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0 or not w[0].wait(remaining):
                        raise CoordinationError(
                            f"timed out waiting for key {key!r}")
                return self._kv[key]
            finally:
                w[1] -= 1
                if w[1] <= 0 and self._waiters.get(key) is w:
                    del self._waiters[key]

    def try_get(self, key: str) -> bytes | None:
        with self._lock:
            self.stats["try_get"] += 1
            return self._kv.get(key)

    def dir_get(self, prefix: str) -> list[tuple[str, bytes]]:
        with self._lock:
            self.stats["dir_get"] += 1
            return sorted((k, v) for k, v in self._kv.items()
                          if k.startswith(prefix))

    def delete(self, key: str):
        """Delete ``key`` and (directory-style, matching TSL) any keys
        under ``key/``."""
        with self._lock:
            self.stats["delete"] += 1
            self._kv.pop(key, None)
            for k in [k for k in self._kv if k.startswith(key + "/")]:
                del self._kv[k]

    def increment(self, key: str, amount: int) -> int:
        with self._lock:
            self.stats["increment"] += 1
            cur = int(self._kv.get(key, b"0"))
            cur += amount
            self._kv[key] = str(cur).encode()
            self._notify_key(key)
            return cur

    def num_keys(self) -> int:
        """Live key count — the KV-size observable the lifecycle-GC
        tests bound across reforms (cluster/kv_gc.py)."""
        with self._lock:
            return len(self._kv)

    def barrier(self, name: str, timeout_s: float, n: int,
                participant: int = 0):
        """Block until ``n`` distinct participants reach ``name``.

        ``n <= 1`` passes trivially (the single-process fallback of the
        production agent). A timed-out barrier raises
        :class:`BarrierTimeoutError` NAMING the missing participant ids
        — the supervisor-facing detail the TSL barrier cannot give you,
        and the first thing an operator of an N-worker fleet needs. A
        released barrier name stays released (one-shot, matching TSL);
        use per-round names for repeated synchronization.
        """
        with self._lock:
            st = self._barriers.get(name)
            if st is None:
                st = self._barriers[name] = {
                    "cv": threading.Condition(self._lock),
                    "arrived": set(), "n": n, "done": n <= 1}
            if st["done"]:
                st["arrived"].add(participant)
                return
            st["arrived"].add(participant)
            if len(st["arrived"]) >= st["n"]:
                st["done"] = True
                st["cv"].notify_all()
                return
            deadline = time.monotonic() + timeout_s
            while not st["done"]:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not st["cv"].wait(remaining):
                    if st["done"]:      # released while timing out
                        return
                    missing = sorted(set(range(st["n"])) - st["arrived"])
                    shown = ", ".join(map(str, missing[:8]))
                    if len(missing) > 8:
                        shown += f", ... ({len(missing)} total)"
                    raise BarrierTimeoutError(
                        f"barrier {name!r} timed out after {timeout_s}s: "
                        f"{len(st['arrived'])}/{st['n']} arrived; "
                        f"missing participant(s): [{shown}]")


_LOCAL = _LocalService()


class CoordinationServiceAgent:
    """Client handle to the coordination service.

    ≙ tsl::CoordinationServiceAgent (coordination_service_agent.h). Use
    ``coordination_service()`` to get the process-wide instance; all
    methods are safe to call in single-process mode.
    """

    def __init__(self):
        self._local = _LOCAL
        self._legacy: bool | None = None
        self._inc_hint: dict[str, int] = {}
        #: per-agent KV/barrier op counts ({op_name: n}) — the raw
        #: material of the fleet-scale control-plane cost curves
        #: (bench.py --fleet). Incremented without a lock: each agent
        #: belongs to one worker (exact there); the process-wide
        #: singleton's counts are approximate under thread races, which
        #: is fine for a cost profile.
        self.op_counts = collections.Counter()

    # -- legacy-client compatibility --------------------------------------
    # jaxlib builds whose DistributedRuntimeClient lacks
    # ``key_value_try_get_bytes`` (jax < 0.5) also have a fatal read bug:
    # the bytes/dir get APIs can SEGFAULT the service-hosting process
    # when the key being read was written by the reading process itself
    # (or has been overwritten/deleted) — the binding hands out a view
    # into the in-process store. The string API copies and is safe in
    # every direction. On such clients every point read is routed
    # through string-get first, falling back to bytes-get only for
    # binary values — which in this framework are always written by a
    # PEER process (pickled closures/results), the safe direction.

    def _is_legacy(self, c) -> bool:
        if self._legacy is None:
            self._legacy = not hasattr(c, "key_value_try_get_bytes")
        return self._legacy

    @staticmethod
    def _legacy_get_once(c, key: str, wait_ms: int) -> "bytes | None":
        """One bounded point read on a legacy client; None when absent."""
        try:
            return c.blocking_key_value_get(key, wait_ms).encode()
        except UnicodeDecodeError:
            # present but binary: peer-written here, so bytes-get is safe
            try:
                return c.blocking_key_value_get_bytes(key, wait_ms)
            except Exception:
                return None
        except Exception:
            return None

    # -- identity ---------------------------------------------------------
    @property
    def _client(self):
        import jax
        return jax._src.distributed.global_state.client

    @property
    def is_distributed(self) -> bool:
        return self._client is not None

    @property
    def process_id(self) -> int:
        import jax
        return jax.process_index()

    @property
    def num_processes(self) -> int:
        import jax
        return jax.process_count()

    @property
    def is_chief(self) -> bool:
        return self.process_id == 0

    # -- KV store ---------------------------------------------------------
    # Every key and barrier name is namespaced with the elastic cluster
    # generation (cluster/elastic.py): a supervisor-reformed cluster gets
    # disjoint coordination state from every dead incarnation's, so a
    # straggler's half-written keys / half-met barriers can never leak
    # into the new generation. Generation 0 (the non-elastic default) is
    # unprefixed. Chaos sites fire on the RAW names — fault schedules
    # target logical keys, not incarnation-specific ones.

    def key_value_set(self, key: str, value: bytes | str, *,
                      allow_overwrite: bool = True):
        self.op_counts["set"] += 1
        key = elastic.namespace(key)
        data = value.encode() if isinstance(value, str) else bytes(value)
        c = self._client
        if c is None:
            self._local.set(key, data, allow_overwrite=allow_overwrite)
        else:
            c.key_value_set_bytes(key, data, allow_overwrite=allow_overwrite)

    def key_value_get(self, key: str, timeout_s: float = 60.0) -> bytes:
        """Blocking get: waits until some process sets ``key``."""
        self.op_counts["get"] += 1
        faults.fire("coord.kv_get", tag=key, exc=CoordinationError,
                    msg=f"injected fault: key_value_get({key!r})")
        key = elastic.namespace(key)
        c = self._client
        if c is None:
            return self._local.get(key, timeout_s)
        if self._is_legacy(c):
            deadline = time.monotonic() + timeout_s
            while True:
                v = self._legacy_get_once(c, key, 100)
                if v is not None:
                    return v
                if time.monotonic() >= deadline:
                    raise CoordinationError(
                        f"key_value_get({key!r}) timed out "
                        f"after {timeout_s}s")
        try:
            return c.blocking_key_value_get_bytes(key, int(timeout_s * 1000))
        except Exception as e:                      # XlaRuntimeError
            raise CoordinationError(
                f"key_value_get({key!r}) failed: {e}") from e

    def key_value_try_get(self, key: str) -> bytes | None:
        self.op_counts["try_get"] += 1
        key = elastic.namespace(key)
        c = self._client
        if c is None:
            return self._local.try_get(key)
        if self._is_legacy(c):
            # No non-blocking get on this vintage: a short blocking
            # string-get is semantically identical (None when absent).
            # Without this the bare `except: return None` below would
            # swallow the AttributeError and EVERY try_get-based poller
            # (preemption signal, heartbeats, shutdown acks) would
            # silently see nothing — the failure paths would never fire.
            return self._legacy_get_once(c, key, 50)
        try:
            return c.key_value_try_get_bytes(key)
        except Exception:
            return None

    def key_value_dir_get(self, prefix: str) -> list[tuple[str, bytes]]:
        self.op_counts["dir_get"] += 1
        prefix = elastic.namespace(prefix)
        c = self._client
        if c is None:
            return self._local.dir_get(prefix)
        try:
            return sorted(c.key_value_dir_get_bytes(prefix))
        except Exception:
            return []

    def key_value_delete(self, key: str):
        self.op_counts["delete"] += 1
        key = elastic.namespace(key)
        c = self._client
        if c is None:
            self._local.delete(key)
        else:
            c.key_value_delete(key)

    def key_value_increment(self, key: str, amount: int = 1) -> int:
        """Atomic fetch-add; returns the post-increment value."""
        self.op_counts["increment"] += 1
        key = elastic.namespace(key)
        c = self._client
        if c is None:
            return self._local.increment(key, amount)
        if hasattr(c, "key_value_increment"):
            return c.key_value_increment(key, amount)
        # Older TSL clients: emulate with dense slot claims.
        # InsertKeyValue with allow_overwrite=False IS atomic on the
        # service and each slot key is written exactly once (no
        # mutation, no directory reads — both are landmines on this
        # vintage). Probing forward from a per-process hint costs one
        # fast RPC per taken slot; coordination counters (generations,
        # incarnations) stay tiny. The final value is also published
        # under ``key`` for plain readers; slot keys live under
        # ``key/`` so a directory delete of ``key`` GCs them.
        i = self._inc_hint.get(key, 0)
        if i == 0:
            # Cold start: seed the probe hint from the published value
            # key (one safe string read). Without this, the p-th
            # process to ever increment probed ~p already-taken slots —
            # N processes touching one counter cost O(N^2) RPCs total,
            # the worst per-op scaling offender the fleet harness's
            # cost curves flagged. Seeded, each process pays one read
            # plus O(amount) probes: O(N) total. The hint may lag the
            # true tail (the value key is best-effort); probing forward
            # absorbs the slack.
            v = self._legacy_get_once(c, key, 50)
            if v is not None:
                try:
                    i = max(i, int(v))
                except ValueError:
                    pass
        claimed = 0
        limit = i + 100_000
        while claimed < amount:
            i += 1
            if i > limit:
                raise CoordinationError(
                    f"key_value_increment({key!r}) fallback exhausted "
                    f"{limit} slots")
            try:
                c.key_value_set_bytes(f"{key}/__c__/{i}", b"1",
                                      allow_overwrite=False)
                claimed += 1
            except Exception as e:
                if "ALREADY_EXISTS" not in str(e):
                    raise CoordinationError(
                        f"key_value_increment({key!r}) failed: {e}") from e
        self._inc_hint[key] = i
        # Value key for plain readers (write-direction: safe). A naive
        # publish is racy: a slower peer's SMALLER value can land after
        # ours (lost update — observed as a full-suite flake in the
        # 2-process barrier/increment test, where a reader past an
        # "everyone incremented" barrier still saw a stale total). The
        # slot keys are the ground truth, so close the race with them:
        # after publishing, probe forward for slots claimed by peers
        # and republish the larger tail until a probe issued AFTER our
        # latest publish finds nothing. Each writer's RPCs are ordered,
        # so any claim our final probe missed belongs to a peer whose
        # own (larger) publish necessarily lands after ours.
        try:
            pub = i
            c.key_value_set_bytes(key, str(pub).encode(),
                                  allow_overwrite=True)
            tail = i
            while tail < limit:
                if self._legacy_get_once(
                        c, f"{key}/__c__/{tail + 1}", 50) is not None:
                    tail += 1
                    continue
                if tail == pub:
                    break
                c.key_value_set_bytes(key, str(tail).encode(),
                                      allow_overwrite=True)
                pub = tail
            self._inc_hint[key] = max(self._inc_hint[key], tail)
        except Exception:
            pass
        return i

    # -- barriers ---------------------------------------------------------
    def barrier(self, name: str, timeout_s: float = 120.0):
        """Block until every process reaches the barrier ``name``.

        Raises :class:`BarrierTimeoutError` on timeout — the failing-fast
        behavior the reference's check_health/barrier path has
        (collective_all_reduce_strategy.py:990) rather than hanging.

        When telemetry is on, a successful barrier emits a
        ``clock.sync`` event: the release is a shared instant every
        participant observes within the release latency, so the trace
        assembler (telemetry/trace.py) uses the per-process walls
        recorded here to estimate per-host clock offsets.
        """
        self.op_counts["barrier"] += 1
        faults.fire("coord.barrier", tag=name, exc=BarrierTimeoutError,
                    msg=f"injected barrier timeout at {name!r}")
        raw_name = name
        name = elastic.namespace(name)
        c = self._client
        if c is None:
            # n/participant come from the agent's identity: 1 for the
            # production single-process fallback (trivially passes,
            # byte-identical behavior), N for the simulated-fleet
            # agents that share one _LocalService across threads.
            self._local.barrier(name, timeout_s, self.num_processes,
                                participant=self.process_id)
        else:
            try:
                c.wait_at_barrier(name, int(timeout_s * 1000))
            except Exception as e:
                raise BarrierTimeoutError(
                    f"barrier {name!r} timed out after {timeout_s}s "
                    f"(a peer process is hung or dead): {e}") from e
        self._emit_clock_sync(raw_name)

    @staticmethod
    def _emit_clock_sync(barrier_name: str):
        """One ``clock.sync`` record per barrier release (no-op with
        telemetry off — a single None check inside events.event)."""
        from distributed_tensorflow_tpu.telemetry import events as _tv
        if _tv.enabled():
            _tv.event("clock.sync", barrier=barrier_name)

    # -- liveness ---------------------------------------------------------
    def live_processes(self) -> list[int]:
        """Process ids the coordination service believes are alive.

        ≙ coordination_service.h task-state polling, the organic failure
        signal behind WorkerPreemptionHandler (SURVEY.md §5.3).
        """
        c = self._client
        if c is None:
            return [0]
        try:
            nodes = c.get_live_nodes([])
            out = []
            for n in nodes:
                tid = _parse_task_id(n)
                if tid is not None:
                    out.append(tid)
            return sorted(set(out))
        except Exception:
            # service variant without get_live_nodes: assume all alive
            import logging
            logging.getLogger(__name__).warning(
                "coordination service has no usable get_live_nodes; "
                "assuming all %d processes alive (organic failure "
                "detection degraded to heartbeats only)",
                self.num_processes)
            return list(range(self.num_processes))


_AGENT: CoordinationServiceAgent | None = None
_AGENT_LOCK = threading.Lock()


def coordination_service() -> CoordinationServiceAgent:
    """Process-wide CoordinationServiceAgent (≙ context's coordination
    service agent singleton)."""
    global _AGENT
    with _AGENT_LOCK:
        if _AGENT is None:
            _AGENT = CoordinationServiceAgent()
        return _AGENT
