"""Platform-specific cluster resolvers: Slurm, SageMaker, GCE, Kubernetes.

≙ the reference's platform resolver family (SURVEY.md §2.4, ~1,020 LoC):
tensorflow/python/distribute/cluster_resolver/slurm_cluster_resolver.py,
sagemaker_cluster_resolver.py, gce_cluster_resolver.py,
kubernetes_cluster_resolver.py. The env-variable contracts are kept
verbatim so reference launch scripts resolve identically; the cloud-API
resolvers (GCE, Kubernetes) take an injectable client so the spec-shaping
logic is testable without the optional SDKs.
"""

from __future__ import annotations

import json
import os
import re
from typing import Callable, Mapping, Sequence

from distributed_tensorflow_tpu.cluster.resolver import (
    ClusterResolver,
    ClusterSpec,
)


# ---------------------------------------------------------------------------
# Slurm (≙ slurm_cluster_resolver.py, 397 LoC — env contract kept)
# ---------------------------------------------------------------------------

def expand_hostlist(hostlist: str) -> list[str]:
    """Expand a Slurm nodelist: "n[1-3,7],m0" -> [n1, n2, n3, n7, m0]
    (≙ slurm_cluster_resolver.expand_hostlist)."""
    hosts: list[str] = []

    def expand_range(prefix: str, body: str):
        for part in body.split(","):
            if "-" in part:
                lo, hi = part.split("-")
                width = len(lo)
                for i in range(int(lo), int(hi) + 1):
                    hosts.append(f"{prefix}{str(i).zfill(width)}")
            else:
                hosts.append(f"{prefix}{part}")

    # split on commas not inside brackets
    for item in re.findall(r"[^,\[]+(?:\[[^\]]*\])?", hostlist):
        m = re.match(r"(.+?)\[([^\]]*)\]$", item)
        if m:
            expand_range(m.group(1), m.group(2))
        elif item:
            hosts.append(item)
    return hosts


def expand_tasks_per_node(spec: str) -> list[int]:
    """"2(x3),1" -> [2, 2, 2, 1] (≙ _expand_tasks_per_node)."""
    out: list[int] = []
    for part in spec.split(","):
        m = re.match(r"(\d+)(?:\(x(\d+)\))?$", part)
        if not m:
            raise ValueError(f"Bad SLURM_TASKS_PER_NODE component {part!r}")
        out.extend([int(m.group(1))] * int(m.group(2) or 1))
    return out


class SlurmClusterResolver(ClusterResolver):
    """Resolve the cluster from Slurm step environment variables.

    ≙ slurm_cluster_resolver.SlurmClusterResolver: tasks are distributed
    over the expanded nodelist according to SLURM_STEP_TASKS_PER_NODE;
    ``jobs`` maps job names to task counts (default: all "worker").
    """

    def __init__(self, jobs: Mapping[str, int] | None = None,
                 port_base: int = 8888, gpus_per_node: int | None = None,
                 gpus_per_task: int | None = None,
                 auto_set_gpu: bool = False,
                 env: Mapping[str, str] | None = None):
        del gpus_per_node, gpus_per_task, auto_set_gpu  # GPU-era knobs
        self._env = dict(env if env is not None else os.environ)
        self._port_base = port_base
        nprocs = int(self._env.get("SLURM_STEP_NUM_TASKS",
                                   self._env.get("SLURM_NPROCS", "1")))
        self._jobs = dict(jobs) if jobs else {"worker": nprocs}
        if sum(self._jobs.values()) != nprocs:
            raise ValueError(
                f"jobs {self._jobs} sum to {sum(self._jobs.values())} but "
                f"Slurm step has {nprocs} tasks")
        self._proc_id = int(self._env.get("SLURM_PROCID", "0"))
        self.task_type, self.task_id = self._my_task()

    def _addresses(self) -> list[str]:
        nodelist = self._env.get("SLURM_STEP_NODELIST",
                                 self._env.get("SLURM_NODELIST", ""))
        if not nodelist:
            raise RuntimeError("Not running under a Slurm step "
                               "(SLURM_STEP_NODELIST unset)")
        nodes = expand_hostlist(nodelist)
        tpn_spec = self._env.get("SLURM_STEP_TASKS_PER_NODE",
                                 self._env.get("SLURM_TASKS_PER_NODE", ""))
        tasks_per_node = (expand_tasks_per_node(tpn_spec) if tpn_spec
                          else [1] * len(nodes))
        addrs = []
        for node, n_tasks in zip(nodes, tasks_per_node):
            for local in range(n_tasks):
                addrs.append(f"{node}:{self._port_base + local}")
        return addrs

    def _assignment(self) -> dict[str, list[str]]:
        addrs = self._addresses()
        out: dict[str, list[str]] = {}
        i = 0
        for job, count in self._jobs.items():
            out[job] = addrs[i:i + count]
            i += count
        return out

    def _my_task(self) -> tuple[str, int]:
        i = self._proc_id
        for job, count in self._jobs.items():
            if i < count:
                return job, i
            i -= count
        raise ValueError(f"SLURM_PROCID {self._proc_id} out of range")

    def cluster_spec(self) -> ClusterSpec:
        return ClusterSpec(self._assignment())

    @property
    def environment(self) -> str:
        return ""


# ---------------------------------------------------------------------------
# SageMaker (≙ sagemaker_cluster_resolver.py, 204 LoC — env contract kept)
# ---------------------------------------------------------------------------

class SageMakerClusterResolver(ClusterResolver):
    """Resolve from SageMaker training env (SM_HOSTS / SM_CURRENT_HOST)."""

    def __init__(self, port: int = 2223,
                 env: Mapping[str, str] | None = None):
        self._env = dict(env if env is not None else os.environ)
        self._port = port
        hosts = json.loads(self._env.get("SM_HOSTS", "[]"))
        if not hosts:
            raise RuntimeError("Not on SageMaker (SM_HOSTS unset/empty)")
        self._hosts = sorted(hosts)
        current = self._env.get("SM_CURRENT_HOST", self._hosts[0])
        self.task_type = "worker"
        self.task_id = self._hosts.index(current)

    def cluster_spec(self) -> ClusterSpec:
        return ClusterSpec(
            {"worker": [f"{h}:{self._port}" for h in self._hosts]})

    @property
    def environment(self) -> str:
        return ""


# ---------------------------------------------------------------------------
# GCE (≙ gce_cluster_resolver.py, 207 LoC — instance-group discovery)
# ---------------------------------------------------------------------------

class GCEClusterResolver(ClusterResolver):
    """Resolve workers from a GCE instance group.

    ``list_instances_fn(project, zone, instance_group)`` -> hostnames;
    defaults to the Compute API via googleapiclient when installed
    (injectable for tests / alternative discovery).
    """

    def __init__(self, project: str, zone: str, instance_group: str,
                 port: int = 8470, task_type: str = "worker",
                 task_id: int = 0,
                 list_instances_fn: Callable[..., Sequence[str]] | None = None):
        self._project = project
        self._zone = zone
        self._instance_group = instance_group
        self._port = port
        self.task_type = task_type
        self.task_id = task_id
        self._list_instances = list_instances_fn or self._gce_list_instances

    @staticmethod
    def _gce_list_instances(project, zone, instance_group) -> list[str]:
        try:
            from googleapiclient import discovery  # type: ignore
        except ImportError as e:
            raise ImportError(
                "GCEClusterResolver needs google-api-python-client (or an "
                "injected list_instances_fn)") from e
        service = discovery.build("compute", "v1")
        request = service.instanceGroups().listInstances(
            project=project, zone=zone, instanceGroup=instance_group,
            body={"instanceState": "RUNNING"})
        hosts = []
        while request is not None:
            response = request.execute()
            for item in response.get("items", []):
                hosts.append(item["instance"].split("/")[-1])
            request = service.instanceGroups().listInstances_next(
                request, response)
        return hosts

    def cluster_spec(self) -> ClusterSpec:
        hosts = self._list_instances(self._project, self._zone,
                                     self._instance_group)
        return ClusterSpec(
            {self.task_type or "worker":
             [f"{h}:{self._port}" for h in sorted(hosts)]})

    @property
    def environment(self) -> str:
        return "google"


# ---------------------------------------------------------------------------
# Kubernetes (≙ kubernetes_cluster_resolver.py, 214 LoC — label selectors)
# ---------------------------------------------------------------------------

class KubernetesClusterResolver(ClusterResolver):
    """Resolve tasks from pod label selectors.

    ``job_to_label_mapping``: {"worker": ["job-name=worker"]} — each
    selector's running pods (sorted by name) become that job's tasks.
    ``list_pods_fn(selector)`` -> [(pod_name, pod_ip, phase)]; defaults
    to the kubernetes client when installed.
    """

    def __init__(self,
                 job_to_label_mapping: Mapping[str, Sequence[str]] | None
                 = None,
                 tf_server_port: int = 8470,
                 override_client=None,
                 list_pods_fn: Callable[[str], Sequence[tuple]] | None
                 = None):
        self._mapping = dict(job_to_label_mapping or
                             {"worker": ["job-name=tensorflow"]})
        self._port = tf_server_port
        self._client = override_client
        self._list_pods = list_pods_fn or self._k8s_list_pods

    def _k8s_list_pods(self, selector: str) -> list[tuple]:
        if self._client is None:
            try:
                from kubernetes import client, config  # type: ignore
            except ImportError as e:
                raise ImportError(
                    "KubernetesClusterResolver needs the kubernetes "
                    "client (or an injected list_pods_fn)") from e
            config.load_kube_config()
            self._client = client.CoreV1Api()
        ret = self._client.list_pod_for_all_namespaces(
            label_selector=selector)
        return [(i.metadata.name, i.status.pod_ip, i.status.phase)
                for i in ret.items]

    def cluster_spec(self) -> ClusterSpec:
        cluster: dict[str, list[str]] = {}
        for job, selectors in self._mapping.items():
            addrs: list[str] = []
            for selector in selectors:
                pods = sorted(self._list_pods(selector))
                for name, ip, phase in pods:
                    if phase != "Running":
                        raise RuntimeError(
                            f"pod {name} matched {selector!r} but is "
                            f"{phase}, not Running")
                    addrs.append(f"{ip}:{self._port}")
            cluster[job] = addrs
        return ClusterSpec(cluster)

    @property
    def environment(self) -> str:
        return ""
