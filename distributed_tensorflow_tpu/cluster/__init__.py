"""Cluster discovery, topology, and multi-process bootstrap.

TPU-native counterpart of the reference's ``tensorflow/python/distribute/
cluster_resolver/`` package plus ``tensorflow/python/tpu/topology.py`` /
``device_assignment.py`` (see SURVEY.md §2.4, §2.6).
"""
