"""Elastic cluster generations.

A *generation* is one incarnation of the cluster. The recovery
supervisor (resilience/supervisor.py) increments it every time it
reforms the cluster after a worker death, preemption, or stall; the new
id reaches every restarted process through the environment
(:data:`ENV_GENERATION`), the same route ``TF_CONFIG`` travels.

What the generation id buys (≙ Elastic Horovod's rendezvous version /
the reference failure-handling module's restart counter):

- **Fresh coordination namespaces.** Every KV key and barrier name the
  :class:`~distributed_tensorflow_tpu.cluster.coordination.
  CoordinationServiceAgent` touches is prefixed with ``gen<N>/`` (via
  :func:`namespace`), so a reformed cluster can never collide with a
  dead generation's half-written keys or half-met barriers — even if
  some coordination state survived the reform (a pooled service, a
  straggler that died late). Generation 0 is unprefixed, so
  non-elastic jobs are byte-identical to before.
- **Restart awareness.** Library code can ask :func:`generation` ("how
  many times has this job been reformed?") and
  :func:`under_supervisor` ("is someone going to restart me?") — the
  latter is how ``TerminationConfig.for_platform`` picks
  restart-instead-of-exit preemption handling.
- **Liveness signal.** :func:`heartbeat` writes this task's current
  step to a per-task file under :data:`ENV_SUPERVISOR_DIR`; the
  supervisor reads the files for stall detection and for step-targeted
  chaos kills. A no-op (one env lookup) outside a supervised run.
"""

from __future__ import annotations

import contextlib
import os
import threading

#: Cluster generation id, injected by the recovery supervisor.
ENV_GENERATION = "DTX_CLUSTER_GENERATION"

#: Scratch directory shared with the supervisor (heartbeat files).
ENV_SUPERVISOR_DIR = "DTX_SUPERVISOR_DIR"

_GENERATION: int | None = None
_TLS = threading.local()


def generation() -> int:
    """The current cluster generation (0 for a never-reformed job).

    A thread-local :func:`generation_override` wins over everything (the
    simulated-fleet harness runs hundreds of "workers" as threads of one
    process, each possibly in a different generation — see
    testing/fleet_sim.py); an explicit :func:`set_generation` wins next;
    otherwise the value comes from the environment on every call (no
    caching — pooled test processes swap env between runs)."""
    g = getattr(_TLS, "gen", None)
    if g is not None:
        return g
    if _GENERATION is not None:
        return _GENERATION
    try:
        return int(os.environ.get(ENV_GENERATION, "0"))
    except ValueError:
        return 0


@contextlib.contextmanager
def generation_override(gen: int):
    """Pin the generation for the CURRENT THREAD only.

    The in-process fleet simulator gives every simulated worker thread
    its own generation: a straggler thread of a dead generation keeps
    namespacing its keys with the OLD id (exactly like a straggler
    process would) while reformed workers already live in the new one.
    Nestable; restores the previous override on exit."""
    prev = getattr(_TLS, "gen", None)
    _TLS.gen = int(gen)
    try:
        yield
    finally:
        _TLS.gen = prev


def set_generation(gen: int | None):
    """Pin the generation programmatically (tests, embedded supervisors);
    ``None`` reverts to the environment."""
    global _GENERATION
    _GENERATION = None if gen is None else int(gen)


def namespace(name: str) -> str:
    """Namespace a coordination key/barrier name with the generation.

    Generation 0 returns ``name`` unchanged (non-elastic jobs keep their
    historical key layout); generation N prefixes ``gen<N>/`` so the
    reformed cluster's coordination state is disjoint from every prior
    incarnation's."""
    g = generation()
    return name if g == 0 else f"gen{g}/{name}"


def under_supervisor() -> bool:
    """True when a recovery supervisor owns this process's lifecycle."""
    return bool(os.environ.get(ENV_SUPERVISOR_DIR))


def heartbeat(step: int | None = None):
    """Report liveness (and optionally the current step) to the
    supervisor. Call once per training step; outside a supervised run
    this is a single env lookup."""
    d = os.environ.get(ENV_SUPERVISOR_DIR)
    if not d:
        return
    task = os.environ.get("DTX_MPR_TASK_INDEX", "0")
    try:
        import time
        # "<step> <wall>": the wall clock is this worker's reading of
        # the write instant; the supervisor pairs it with the file's
        # mtime (its own clock domain) into a ``clock.hb`` telemetry
        # event — the heartbeat half of cross-host clock alignment
        # (telemetry/trace.estimate_clock_offsets).
        with open(os.path.join(d, f"heartbeat-{task}"), "w") as f:
            f.write(("" if step is None else str(int(step)))
                    + f" {time.time():.6f}")
    except OSError:
        pass                      # supervisor dir raced away: non-fatal


def heartbeat_path(supervisor_dir: str, task_index: int) -> str:
    """Supervisor-side: the heartbeat file a task writes."""
    return os.path.join(supervisor_dir, f"heartbeat-{task_index}")


def drain_path(supervisor_dir: str, task_index: int | str) -> str:
    """Supervisor-side: the drain flag a task polls. The supervisor
    writes it before a SCALE reform (resilience/supervisor.py
    ``drain_on_scale``); a serving replica that sees it stops admitting
    new requests, finishes its running sequences, logs them and exits
    cleanly — so a replica removed by scale-down drops zero requests
    (the held/unfinished remainder re-shards onto the next
    generation)."""
    return os.path.join(supervisor_dir, f"drain-{task_index}")


def drain_requested(supervisor_dir: str | None = None,
                    task_index: int | str | None = None) -> bool:
    """Worker-side: has the supervisor asked this task to drain?
    Defaults resolve from the environment exactly like
    :func:`heartbeat`; explicit arguments serve in-process simulated
    workers (testing/fleet_sim.py threads share one environment).
    A single ``os.path.exists`` — cheap enough for every step."""
    d = supervisor_dir or os.environ.get(ENV_SUPERVISOR_DIR)
    if not d:
        return False
    if task_index is None:
        task_index = os.environ.get("DTX_MPR_TASK_INDEX", "0")
    return os.path.exists(drain_path(d, task_index))


def drain_mode(supervisor_dir: str | None = None,
               task_index: int | str | None = None) -> str | None:
    """The drain flag's mode, or None when no drain is requested:
    ``"fast"`` (finish only in-flight/running work — a scale-UP wants
    the capacity add now, queued work re-shards) or ``"full"`` (finish
    everything already admitted — a scale-DOWN happens at low load, so
    completing the queue before the reform keeps those requests off
    the respawn gap's latency tail)."""
    d = supervisor_dir or os.environ.get(ENV_SUPERVISOR_DIR)
    if not d:
        return None
    if task_index is None:
        task_index = os.environ.get("DTX_MPR_TASK_INDEX", "0")
    try:
        with open(drain_path(d, task_index)) as f:
            mode = f.read().strip()
        return mode if mode in ("fast", "full") else "fast"
    except OSError:
        return None


def peer_memdir(task_index: int | str | None = None) -> str | None:
    """This worker's *memdir* — the directory standing in for its
    machine's RAM/ramdisk in the peer-snapshot tier
    (checkpoint/peer_snapshot.py). Lives under the supervisor's scratch
    dir keyed by task index: it survives a process restart (the
    supervisor respawns onto the same "machine") but the supervisor
    wipes it when the machine is considered dead. ``None`` outside a
    supervised run."""
    d = os.environ.get(ENV_SUPERVISOR_DIR)
    if not d:
        return None
    if task_index is None:
        task_index = os.environ.get("DTX_MPR_TASK_INDEX", "0")
    return peer_memdir_path(d, task_index)


def peer_memdir_path(supervisor_dir: str, task_index: int | str) -> str:
    """Supervisor-side: the memdir of the machine behind a task slot."""
    return os.path.join(supervisor_dir, "peermem", f"worker-{task_index}")
