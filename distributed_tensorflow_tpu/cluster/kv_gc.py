"""KV-key lifecycle: garbage-collect dead generations' coordination keys.

Elastic recovery (cluster/elastic.py) namespaces every coordination KV
key and barrier with the cluster generation — ``gen<N>/…`` — so a
reformed cluster can never collide with a dead incarnation's state.
The flip side: every reform strands a whole namespace of keys
(heartbeat shards, telemetry snapshots, rollup partials, checkpoint
commit markers) that nothing will ever read again, and on a long
flapping run the KV grows without bound. This module is the sweeper.

Lifecycle rules (mirrored in the README "Fleet scale" section):

- A generation is **dead** once the supervisor has reformed past it
  (``generation() > N``). Generation 0 is unprefixed by design and is
  therefore never swept — its keys are the non-elastic key layout.
- A dead generation is **sweep-eligible** only after a *grace window*
  measured from its last observed heartbeat: a straggler process of
  the dead generation (SIGKILL survivor wedged in a collective, a
  thread finishing a blocking read) may still be touching its keys.
  Reads of a swept key would block/time out rather than corrupt, but a
  straggler's re-WRITE after the sweep would resurrect a half-dead
  namespace — the grace window (default :data:`DEFAULT_GRACE_S`) keeps
  the sweep strictly after the namespace has gone quiet.
- The sweep itself is one directory-style delete per dead generation
  (``key_value_delete("gen<N>")`` removes the key and everything under
  ``gen<N>/``). Deletes are a write-direction RPC — safe on every
  jaxlib vintage, unlike directory *reads* (see the legacy discipline
  in cluster/coordination.py). Live generations are untouched: the
  delete is anchored at the dead generation's prefix, issued under a
  ``generation_override(0)`` so the agent's own namespacing cannot
  re-prefix it into the current generation.

Drivers: the recovery supervisor notes each outgoing generation's last
heartbeat at reform time and polls :meth:`GenerationGC.maybe_sweep`
from its watch loop (resilience/supervisor.py); the simulated-fleet
harness does the same in-process. A chief worker can also run the
sweep itself via :func:`sweep_generations` when no supervisor owns the
KV (e.g. externally-orchestrated restarts).
"""

from __future__ import annotations

import threading
import time

from distributed_tensorflow_tpu.cluster import elastic

#: Default grace window between a generation's last observed heartbeat
#: and its sweep. Sized to comfortably exceed a straggler's longest
#: plausible in-flight operation (a blocking KV get's timeout is the
#: worst case; production deployments should set it to that timeout).
DEFAULT_GRACE_S = 30.0


def generation_prefix(gen: int) -> str:
    """The raw KV prefix of generation ``gen``'s namespace (no trailing
    slash — the directory-style delete adds it)."""
    return f"gen{int(gen)}"


def sweep_generations(agent, gens, *, current_gen: int | None = None):
    """Delete every key of each dead generation in ``gens``.

    Generation 0 and any generation >= the current one are skipped
    (never sweep a live namespace). Returns the list of generations
    actually swept. Safe to call repeatedly — deleting an
    already-empty prefix is a no-op on every backend.
    """
    cur = current_gen if current_gen is not None else elastic.generation()
    swept = []
    for g in sorted(set(int(g) for g in gens)):
        if g <= 0 or g >= cur:
            continue
        # override(0): namespace() must NOT re-prefix the dead
        # generation's key into the caller's current namespace
        with elastic.generation_override(0):
            agent.key_value_delete(generation_prefix(g))
        swept.append(g)
    return swept


class GenerationGC:
    """Grace-windowed sweeper of dead generations' KV namespaces.

    The owner (supervisor or harness) reports each generation's end via
    :meth:`note_generation_end` with the last heartbeat wall clock it
    observed from that generation, then calls :meth:`maybe_sweep`
    opportunistically (every watch tick is fine — it is an in-memory
    check unless something is actually eligible).
    """

    def __init__(self, agent, *, grace_s: float = DEFAULT_GRACE_S):
        self.agent = agent
        self.grace_s = grace_s
        self._lock = threading.Lock()
        self._ended: dict[int, float] = {}    # gen -> last heartbeat wall
        self.swept: list[int] = []

    def note_generation_end(self, gen: int, last_heartbeat_wall:
                            "float | None" = None):
        """Record that ``gen`` is dead; its grace window runs from
        ``last_heartbeat_wall`` (defaults to now — the conservative
        choice when no heartbeat was ever observed)."""
        if gen <= 0:
            return                        # gen 0 is unprefixed: never GC'd
        with self._lock:
            wall = (last_heartbeat_wall if last_heartbeat_wall is not None
                    else time.time())
            # a straggler could in principle heartbeat again; keep the max
            self._ended[gen] = max(wall, self._ended.get(gen, 0.0))

    def pending(self) -> "list[int]":
        """Dead generations noted but not yet swept."""
        with self._lock:
            return sorted(self._ended)

    def maybe_sweep(self, *, current_gen: int | None = None,
                    now: "float | None" = None) -> "list[int]":
        """Sweep every noted generation whose grace window has elapsed.
        Returns the generations swept this call."""
        now = now if now is not None else time.time()
        with self._lock:
            eligible = [g for g, wall in self._ended.items()
                        if now - wall >= self.grace_s]
        if not eligible:
            return []
        swept = sweep_generations(self.agent, eligible,
                                  current_gen=current_gen)
        with self._lock:
            for g in swept:
                self._ended.pop(g, None)
            self.swept.extend(swept)
        return swept
