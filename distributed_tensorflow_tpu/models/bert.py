"""BERT-style MLM pretraining — benchmark workload #3
(BASELINE.md: CollectiveAllReduceStrategy reference).

Reuses the flagship transformer in bidirectional-encoder mode
(cfg.causal=False) and adds masked-language-model machinery: dynamic
masking, masked-position cross-entropy, and the sharded train step. Where
the reference runs BERT through `CollectiveAllReduceStrategy` (reference:
tensorflow/python/distribute/collective_all_reduce_strategy.py:57) with
collective-V2 allreduce ops, gradients here are psum'd by GSPMD over the
same dp×fsdp×tp mesh the flagship uses.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh

from distributed_tensorflow_tpu.models.transformer import (
    TransformerConfig, TransformerLM, make_optimizer)

MASK_TOKEN = 1           # convention: [MASK] id
IGNORE_LABEL = -100


def bert_config(**kw) -> TransformerConfig:
    return TransformerConfig.bert_base(**kw)


def tiny_bert_config(**kw) -> TransformerConfig:
    return TransformerConfig.tiny(causal=False, **kw)


def apply_mlm_masking(rng, tokens, *, mask_rate: float = 0.15,
                      mask_token: int = MASK_TOKEN, vocab_size: int = 256):
    """BERT 80/10/10 dynamic masking. Returns (input_ids, labels) where
    labels is IGNORE_LABEL at unmasked positions."""
    r_select, r_kind, r_rand = jax.random.split(rng, 3)
    selected = jax.random.uniform(r_select, tokens.shape) < mask_rate
    kind = jax.random.uniform(r_kind, tokens.shape)
    random_tokens = jax.random.randint(r_rand, tokens.shape, 0, vocab_size)
    inputs = jnp.where(selected & (kind < 0.8), mask_token, tokens)
    inputs = jnp.where(selected & (kind >= 0.8) & (kind < 0.9),
                       random_tokens, inputs)
    labels = jnp.where(selected, tokens, IGNORE_LABEL)
    return inputs, labels


def mlm_loss(logits, labels):
    """Cross-entropy over masked positions only."""
    mask = labels != IGNORE_LABEL
    safe_labels = jnp.where(mask, labels, 0)
    losses = optax.softmax_cross_entropy_with_integer_labels(
        logits, safe_labels)
    denom = jnp.maximum(mask.sum(), 1)
    return (losses * mask).sum() / denom


def make_train_step(cfg: TransformerConfig, model: TransformerLM, tx,
                    seed: int = 0):
    """(state, batch{"tokens"}) -> (state, metrics). 80/10/10 masking is
    applied on-device inside the step, re-drawn per step from
    fold_in(seed, step) — dynamic masking, fresh every epoch.

    With ``cfg.loss_impl="kernel"`` the masked-position CE runs through
    the Pallas fused-CE kernels against the tied embedding
    (ops/fused_ce.py) — the (B, S, vocab) logits never materialize,
    same as the flagship LM loss. Any other setting keeps the classic
    full-logits path."""

    def kernel_loss_fn(params, inputs, labels):
        from distributed_tensorflow_tpu.ops.fused_ce import (
            fused_cross_entropy, sharded_fused_cross_entropy)
        hidden = model.apply({"params": params}, inputs,
                             return_hidden=True)
        B, S, D = hidden.shape
        mask = labels != IGNORE_LABEL
        safe = jnp.where(mask, labels, 0)
        emb = params["embed"].astype(cfg.dtype)
        if cfg.mesh is not None and cfg.mesh.size > 1:
            losses = sharded_fused_cross_entropy(
                hidden.astype(cfg.dtype), emb, safe, cfg.mesh,
                block_n=cfg.loss_block_n, block_v=cfg.loss_block_v,
                implementation=cfg.loss_kernel_impl)
        else:
            losses = fused_cross_entropy(
                hidden.reshape(B * S, D).astype(cfg.dtype), emb,
                safe.reshape(B * S), block_n=cfg.loss_block_n,
                block_v=cfg.loss_block_v,
                implementation=cfg.loss_kernel_impl).reshape(B, S)
        denom = jnp.maximum(mask.sum(), 1)
        return (losses * mask).sum() / denom

    def loss_fn(params, inputs, labels):
        if cfg.loss_impl == "kernel":
            return kernel_loss_fn(params, inputs, labels)
        logits = model.apply({"params": params}, inputs)
        return mlm_loss(logits, labels)

    def train_step(state, batch):
        rng = jax.random.fold_in(jax.random.PRNGKey(seed), state["step"])
        inputs, labels = apply_mlm_masking(
            rng, batch["tokens"], vocab_size=cfg.vocab_size)
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], inputs,
                                                  labels)
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss})

    return train_step


def make_sharded_train_step(cfg: TransformerConfig, mesh: Mesh,
                            global_batch: int, seed: int = 0):
    """All sharding/jit wiring is the flagship transformer's — only the
    per-step loss (MLM with dynamic masking) is swapped in."""
    assert not cfg.causal, "BERT requires causal=False (encoder mode)"
    from distributed_tensorflow_tpu.models.transformer import (
        make_sharded_train_step as _transformer_sharded_step)
    return _transformer_sharded_step(
        cfg, mesh, global_batch, seed=seed,
        step_factory=lambda c, m, t: make_train_step(c, m, t, seed=seed))


def synthetic_corpus(global_batch: int, seq_len: int, vocab_size: int,
                     seed: int = 0):
    rng = np.random.default_rng(seed)
    # Zipfian-ish token distribution so MLM has learnable structure.
    probs = 1.0 / np.arange(2, vocab_size + 2)
    probs /= probs.sum()
    toks = rng.choice(vocab_size, size=(global_batch, seq_len), p=probs)
    return {"tokens": jnp.asarray(toks, jnp.int32)}
