"""Online recommender: streaming DLRM over dynamic embedding tables.

The workload that makes BASELINE config #4 *online* (ROADMAP item 2):
an unbounded click stream (input/stream.py) feeds a small
Wide&Deep-style model whose user/item tables are
:class:`~distributed_tensorflow_tpu.embedding.dynamic.DynamicTable`
instances — frequency-capped admission, LFU+TTL eviction, growth —
trained continuously with **exactly-once** event application:

    ingestor --append--> stream.log --tail--> trainer --commit-->
    checkpoint{model, membership, OFFSET} --poll--> evaluator
                                                    (fresh snapshots)

The exactly-once rule is structural, not best-effort: the trainer's
stream cursor (next unapplied offset) is a LEAF of the same checkpoint
the model state commits through, so cursor and state can only move
together (the index-last commit protocol of checkpoint/checkpoint.py
makes the pair atomic). A trainer killed between apply and commit
replays exactly the uncommitted records into the last committed state
— applying each log record to the surviving lineage exactly once, by
construction. ``tools/chaos_sweep.py --online`` audits this from the
run's ``stream.*`` telemetry; tests/test_stream.py kills a trainer
between apply and commit and proves bit-equal convergence.

Gradients flow through the async-PS path when a
:class:`~distributed_tensorflow_tpu.coordinator.cluster_coordinator.
ClusterCoordinator` is supplied (closures on remote grad workers via
coordinator/remote_dispatch.py — the reference's config-#4 transport),
or a local jit program otherwise (bench/tests). Either way the
TRAINER owns the server copy: tables, membership, dense params, and
the cursor all live here, and commits happen here.
"""

from __future__ import annotations

import dataclasses
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu.embedding.dynamic import (
    DynamicTable,
    DynamicTableConfig,
    StaticHashTable,
)
from distributed_tensorflow_tpu.embedding.embedding import Adagrad
from distributed_tensorflow_tpu.input import stream as stream_lib
from distributed_tensorflow_tpu.telemetry import events as tv_events


@dataclasses.dataclass(frozen=True)
class OnlineConfig:
    """The online job's model + table + stream shape (hashable: the
    worker-side grad program caches per config)."""

    embed_dim: int = 8
    n_dense: int = 4
    hidden: tuple = (32, 16)
    dense_lr: float = 0.05
    table_lr: float = 0.05
    batch_size: int = 16
    # dynamic-table knobs (shared by the user and item tables)
    initial_capacity: int = 256
    max_capacity: int = 1024
    admission_threshold: int = 2
    ttl_steps: int = 2048
    # seeded event stream shape
    n_users: int = 50_000
    n_items: int = 10_000
    zipf_a: float = 1.2
    seed: int = 0

    def __post_init__(self):
        if self.batch_size <= 0:
            raise ValueError(f"batch_size must be positive, got "
                             f"{self.batch_size}")

    def table_config(self, name: str, seed: int) -> DynamicTableConfig:
        return DynamicTableConfig(
            dim=self.embed_dim,
            initial_capacity=self.initial_capacity,
            max_capacity=self.max_capacity,
            admission_threshold=self.admission_threshold,
            ttl_steps=self.ttl_steps,
            optimizer=Adagrad(self.table_lr),
            name=name, seed=seed)

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(embed_dim=4, hidden=(16,), initial_capacity=32,
                        max_capacity=64, n_users=500, n_items=200)
        defaults.update(kw)
        return cls(**defaults)


# ---------------------------------------------------------------------------
# Dense tower (explicit param dict — no framework state to thread
# through pickled closures) + the worker-side grad program.
# ---------------------------------------------------------------------------

def init_dense(cfg: OnlineConfig, seed: int = 0) -> dict:
    rng = np.random.default_rng([cfg.seed, seed, 0xDE45E])
    dims = (2 * cfg.embed_dim + cfg.n_dense,) + tuple(cfg.hidden) + (1,)
    params = {}
    for i in range(len(dims) - 1):
        scale = 1.0 / np.sqrt(dims[i])
        params[f"w{i}"] = rng.normal(
            0, scale, size=(dims[i], dims[i + 1])).astype(np.float32)
        params[f"b{i}"] = np.zeros(dims[i + 1], dtype=np.float32)
    return params


def _forward(cfg: OnlineConfig, params, user_rows, item_rows, dense):
    x = jnp.concatenate([user_rows, item_rows, dense], axis=-1)
    n_layers = len(cfg.hidden) + 1
    for i in range(n_layers):
        x = jnp.dot(x, params[f"w{i}"]) + params[f"b{i}"]
        if i < n_layers - 1:
            x = jax.nn.relu(x)
    return x[:, 0]


@functools.lru_cache(maxsize=8)
def _grad_program(cfg: OnlineConfig):
    """Compiled loss+grads, one per config per process (≙ the async-PS
    worker's per-process function library, wide_deep._ps_grad_program).
    Differentiates w.r.t. dense params AND the gathered embedding rows
    (the row grads scatter back through DynamicTable's sparse apply)."""

    def loss_fn(params, user_rows, item_rows, dense, labels):
        logits = _forward(cfg, params, user_rows, item_rows, dense)
        labels = labels.astype(jnp.float32)
        # sigmoid binary cross entropy
        return jnp.mean(jnp.maximum(logits, 0) - logits * labels
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))

    return jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))


def worker_grads(cfg: OnlineConfig, dense_params, user_rows, item_rows,
                 dense, labels):
    """Runs on a grad worker (scheduled closure over remote_dispatch)
    OR locally: returns ``(loss, dense_grads, user_row_grads,
    item_row_grads)`` as host arrays."""
    loss, (dgrads, ugrads, igrads) = _grad_program(cfg)(
        dense_params, jnp.asarray(user_rows), jnp.asarray(item_rows),
        jnp.asarray(dense), jnp.asarray(labels))
    host = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
    return host(loss), host(dgrads), host(ugrads), host(igrads)


@functools.lru_cache(maxsize=8)
def _dense_apply_fn(lr: float):
    @jax.jit
    def apply(params, grads, accum):
        # adagrad, mirroring the table optimizer family
        new_acc = {k: accum[k] + jnp.square(grads[k]) for k in params}
        new_p = {k: params[k] - lr * grads[k]
                 * jax.lax.rsqrt(new_acc[k] + 1e-12) for k in params}
        return new_p, new_acc

    return apply


# ---------------------------------------------------------------------------
# Checkpoint layout (fixed leaf names — required by Checkpoint restore)
# ---------------------------------------------------------------------------

def checkpoint_template(cfg: OnlineConfig) -> dict:
    """The leaf-name structure of an online checkpoint. Shapes are
    placeholders (restore is name-driven); the EVALUATOR registers this
    template to read a trainer's checkpoints without sharing live
    objects."""
    dense = init_dense(cfg)
    table = {"rows": np.zeros((1, cfg.embed_dim), np.float32),
             "aux": np.zeros(1, np.uint8)}
    return {
        "offset": np.zeros((), np.int64),
        "step": np.zeros((), np.int64),
        "commit_wall": np.zeros((), np.float64),
        "dense": {"params": dense,
                  "accum": {k: np.zeros_like(v)
                            for k, v in dense.items()}},
        "user": dict(table),
        "item": {k: v.copy() for k, v in table.items()},
    }


def unpack_restored(flat: dict, prefix: str = "online") -> dict:
    """Rebuild the nested online state from a flat restored mapping
    (``{"online/user/rows": arr, ...}`` -> nested dict)."""
    out: dict = {}
    pre = prefix + "/"
    for key, val in flat.items():
        if not key.startswith(pre):
            continue
        node = out
        parts = key[len(pre):].split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return out


# ---------------------------------------------------------------------------
# The trainer loop
# ---------------------------------------------------------------------------

class OnlineTrainer:
    """Continuous streaming trainer with exactly-once event application.

    One instance is one trainer incarnation: construct, :meth:`restore`
    (cursor + model + MEMBERSHIP come back together), then :meth:`run`
    until ``total_events`` are applied and committed. Gradients are
    computed locally, or asynchronously through ``coordinator``
    (ClusterCoordinator over remote grad workers) with up to
    ``max_in_flight`` scheduled closures; results are applied in
    schedule order, so the committed cursor is always the contiguous
    applied prefix.
    """

    def __init__(self, cfg: OnlineConfig, stream_path: str,
                 ckpt_dir: str, *, commit_every: int = 5,
                 coordinator=None, max_in_flight: int = 2,
                 static_tables: bool = False,
                 local_dir: str | None = None,
                 manager_kwargs: dict | None = None,
                 agent=None):
        from distributed_tensorflow_tpu.checkpoint.checkpoint import (
            Checkpoint, CheckpointManager)
        self.cfg = cfg
        self.stream_path = stream_path
        self.commit_every = commit_every
        self.coordinator = coordinator
        self.max_in_flight = max(1, max_in_flight)
        self.agent = agent
        if static_tables:
            self.user_table = StaticHashTable(
                cfg.embed_dim, cfg.max_capacity,
                optimizer=Adagrad(cfg.table_lr), seed=cfg.seed,
                name="user")
            self.item_table = StaticHashTable(
                cfg.embed_dim, cfg.max_capacity,
                optimizer=Adagrad(cfg.table_lr), seed=cfg.seed + 1,
                name="item")
        else:
            self.user_table = DynamicTable(
                cfg.table_config("user", cfg.seed))
            self.item_table = DynamicTable(
                cfg.table_config("item", cfg.seed + 1))
        self.dense_params = {k: jnp.asarray(v)
                             for k, v in init_dense(cfg).items()}
        self.dense_accum = {k: jnp.zeros_like(v)
                            for k, v in self.dense_params.items()}
        self.offset = 0          # next unapplied stream offset
        self.step = 0            # applied batches (the training step)
        self.events_applied = 0
        self.commits = 0
        # single_writer: the trainer alone owns the online state — the
        # ingestor/evaluator/grad workers are cluster members that
        # never checkpoint (the data_service discipline)
        self._ckpt = Checkpoint(single_writer=True,
                                online=checkpoint_template(cfg))
        self._mgr = CheckpointManager(
            self._ckpt, ckpt_dir, checkpoint_name="online",
            local_dir=local_dir, **(manager_kwargs or {}))

    # -- state <-> checkpoint ---------------------------------------------
    def _state_nested(self) -> dict:
        return {
            "offset": np.asarray(self.offset, np.int64),
            "step": np.asarray(self.step, np.int64),
            "commit_wall": np.asarray(time.time(), np.float64),
            "dense": {
                "params": {k: np.asarray(v)
                           for k, v in self.dense_params.items()},
                "accum": {k: np.asarray(v)
                          for k, v in self.dense_accum.items()}},
            "user": self.user_table.state_dict(),
            "item": self.item_table.state_dict(),
        }

    def restore(self) -> int:
        """Restore cursor + model + membership from the freshest intact
        checkpoint tier; returns the resume offset (0 = cold start)."""
        res = self._mgr.restore_latest()
        if res is None:
            tv_events.event("stream.resume", offset=0, tier="none")
            return 0
        tier, number, restored = res
        state = unpack_restored(restored)
        self.load_state(state)
        # resume the commit numbering where the lineage left it, so the
        # next save never collides with an existing checkpoint dir
        self.commits = int(number)
        tv_events.event("stream.resume", offset=self.offset, tier=tier,
                        step=self.step)
        return self.offset

    def load_state(self, state: dict):
        self.offset = int(np.asarray(state["offset"]))
        self.step = int(np.asarray(state["step"]))
        self.dense_params = {k: jnp.asarray(v) for k, v in
                             state["dense"]["params"].items()}
        self.dense_accum = {k: jnp.asarray(v) for k, v in
                            state["dense"]["accum"].items()}
        self.user_table.load_state_dict(state["user"])
        self.item_table.load_state_dict(state["item"])

    def commit(self):
        """Atomically commit model + membership + CURSOR: one
        checkpoint save (index written last = the commit point). The
        committed offset is also advertised on the coordination KV for
        cheap cross-process reads; the checkpoint remains the single
        source of truth."""
        self._ckpt._objects["online"] = self._state_nested()
        # SYNCHRONOUS commit, even with a local tier configured: the
        # cursor advertised below must never outrun the bytes on disk —
        # an async pipeline would let a SIGKILL land after the
        # stream.commit event but before any tier actually committed,
        # and the next incarnation would (correctly) replay events this
        # event claimed were applied (chaos_sweep --online catches
        # exactly that as REPLAYS COMMITTED)
        self._mgr.save(checkpoint_number=self.commits + 1,
                       async_write=False)
        self.commits += 1
        if self.agent is not None:
            try:
                self.agent.key_value_set("dtx_online/committed_offset",
                                         str(self.offset),
                                         allow_overwrite=True)
            except Exception:
                pass             # advisory only
        tv_events.event("stream.commit", offset=self.offset,
                        step=self.step, commit=self.commits)

    # -- the loop ---------------------------------------------------------
    def _batches(self, total_events: int, idle_timeout_s: float):
        """Yield fixed-size batches of events from the cursor; the tail
        batch may be short only when the stream ends exactly there."""
        ds = stream_lib.StreamDataset(self.stream_path,
                                      start_offset=self.offset)
        buf: list = []
        lo = self.offset
        for off, ev in ds.events(end_offset=total_events,
                                 idle_timeout_s=idle_timeout_s):
            buf.append(ev)
            if len(buf) == self.cfg.batch_size:
                yield lo, off + 1, buf
                buf, lo = [], off + 1
        if buf:
            yield lo, lo + len(buf), buf

    @staticmethod
    def _stack(events: list) -> dict:
        return {"user": np.asarray([e["user"] for e in events],
                                   np.int64),
                "item": np.asarray([e["item"] for e in events],
                                   np.int64),
                "dense": np.stack([e["dense"] for e in events]),
                "label": np.asarray([e["label"] for e in events],
                                    np.int32)}

    def _pad(self, batch: dict) -> tuple[dict, int]:
        """Fixed-shape batches for the jit'd grad program: a short tail
        batch repeats its last event. The padded entries' ROW grads are
        dropped (and the mean rescaled) before apply; the dense-tower
        grad keeps the duplicates — a small tail-batch bias accepted
        for a single compiled program (totals divisible by batch_size,
        the configured norm, avoid it entirely)."""
        n = len(batch["label"])
        b = self.cfg.batch_size
        if n == b:
            return batch, n
        pad = {k: np.concatenate(
            [v, np.repeat(v[-1:], b - n, axis=0)]) for k, v in
            batch.items()}
        return pad, n

    def _compute_grads(self, urows_idx, irows_idx, batch):
        urows = self.user_table.gather(urows_idx)
        irows = self.item_table.gather(irows_idx)
        args = (self.cfg,
                {k: np.asarray(v) for k, v in self.dense_params.items()},
                np.asarray(urows), np.asarray(irows),
                batch["dense"], batch["label"])
        if self.coordinator is not None:
            return self.coordinator.schedule(worker_grads, args=args)
        return worker_grads(*args)

    def _apply(self, urows_idx, irows_idx, n_real, result):
        loss, dgrads, ugrads, igrads = result
        if n_real < self.cfg.batch_size:
            # drop padded rows' grads entirely; rescale the mean
            scale = self.cfg.batch_size / n_real
            ugrads = ugrads[:n_real] * scale
            igrads = igrads[:n_real] * scale
            dgrads = {k: v * scale for k, v in dgrads.items()}
            urows_idx = urows_idx[:n_real]
            irows_idx = irows_idx[:n_real]
        self.user_table.apply_row_grads(urows_idx, ugrads,
                                        pad_to=self.cfg.batch_size)
        self.item_table.apply_row_grads(irows_idx, igrads,
                                        pad_to=self.cfg.batch_size)
        self.dense_params, self.dense_accum = _dense_apply_fn(
            self.cfg.dense_lr)(self.dense_params,
                               {k: jnp.asarray(v)
                                for k, v in dgrads.items()},
                               self.dense_accum)
        return float(loss)

    def run(self, total_events: int, *, idle_timeout_s: float = 60.0,
            heartbeat_fn=None, on_batch=None,
            crash_after_batches: int | None = None) -> dict:
        """Apply stream records ``[restore offset, total_events)`` and
        commit every ``commit_every`` batches plus once at the end.
        ``crash_after_batches`` raises AFTER apply but BEFORE the next
        commit — the kill-between-apply-and-commit regression hook.
        Returns summary counters."""
        losses: list = []
        in_flight: list = []
        batches_done = 0
        t_first = None

        def apply_one():
            nonlocal batches_done, t_first
            lo, hi, uidx, iidx, n_real, t0, rv = in_flight.pop(0)
            result = rv.fetch() if hasattr(rv, "fetch") else rv
            loss = self._apply(uidx, iidx, n_real, result)
            jax.block_until_ready(self.dense_params["w0"])
            dur = time.perf_counter() - t0
            if t_first is None:
                t_first = time.perf_counter() - dur
            self.offset = hi
            self.events_applied += n_real
            self.step += 1
            batches_done += 1
            losses.append(loss)
            tv_events.event("train.step", step=self.step, loss=loss,
                            dur_s=round(dur, 6))
            tv_events.event("stream.batch_applied", lo=lo, hi=hi,
                            n=n_real, step=self.step,
                            loss=round(loss, 5))
            if heartbeat_fn is not None:
                heartbeat_fn(batches_done)
            if on_batch is not None:
                on_batch(self)
            if crash_after_batches is not None \
                    and batches_done >= crash_after_batches:
                raise _InjectedCrash(
                    f"injected crash after {batches_done} applied "
                    f"batches (before commit)")
            if self.step % self.commit_every == 0:
                self.commit()

        for lo, hi, events in self._batches(total_events,
                                            idle_timeout_s):
            batch = self._stack(events)
            batch, n_real = self._pad(batch)
            uidx = self.user_table.translate(batch["user"])
            iidx = self.item_table.translate(batch["item"])
            t0 = time.perf_counter()
            rv = self._compute_grads(uidx, iidx, batch)
            in_flight.append((lo, hi, uidx, iidx, n_real, t0, rv))
            # apply in schedule order: the committed cursor is always
            # the contiguous applied prefix, even with a pipeline of
            # in-flight closures
            while len(in_flight) >= (self.max_in_flight
                                     if self.coordinator is not None
                                     else 1):
                apply_one()
        while in_flight:
            apply_one()
        if self.offset < total_events:
            raise TimeoutError(
                f"stream went idle at offset {self.offset} before "
                f"reaching {total_events} events")
        if self.step % self.commit_every != 0 or self.commits == 0:
            self.commit()
        wall = (time.perf_counter() - t_first) if t_first else 0.0
        return {
            "offset": self.offset,
            "steps": self.step,
            "events_applied": self.events_applied,
            "commits": self.commits,
            "loss_last": losses[-1] if losses else None,
            "events_per_sec": (self.events_applied / wall
                               if wall > 0 else None),
            "tables": {
                name: {"capacity": t.capacity, "mapped": t.mapped,
                       "admissions": t.admissions,
                       "evictions": t.evictions, "grows": t.grows}
                for name, t in (("user", self.user_table),
                                ("item", self.item_table))},
        }

    def sync(self):
        self._ckpt.sync()


class _InjectedCrash(RuntimeError):
    """Raised by ``crash_after_batches`` (tests only)."""


def table_stats_event(trainer: OnlineTrainer):
    """Emit the per-table admission/eviction/growth counters as one
    ``embed.update`` event (the obs_report 'online' section's feed)."""
    for name, t in (("user", trainer.user_table),
                    ("item", trainer.item_table)):
        tv_events.event("embed.update", table=name,
                        capacity=t.capacity, mapped=t.mapped,
                        admissions=t.admissions, evictions=t.evictions,
                        grows=t.grows, step=trainer.step)


# ---------------------------------------------------------------------------
# Evaluator side: restore fresh snapshots, stamp their stream offset
# ---------------------------------------------------------------------------

def eval_snapshot(cfg: OnlineConfig, state: dict, *, n_eval: int = 64,
                  eval_seed: int = 0xEA1) -> float:
    """Held-out loss of a restored snapshot: rebuild the tables
    (membership included) read-only and score a seeded eval batch —
    the 'servable' proof that a snapshot is a working model, not just
    bytes."""
    user = DynamicTable(cfg.table_config("user", cfg.seed)) \
        if _is_dynamic(state["user"]) else StaticHashTable(
            cfg.embed_dim, cfg.max_capacity, seed=cfg.seed)
    item = DynamicTable(cfg.table_config("item", cfg.seed + 1)) \
        if _is_dynamic(state["item"]) else StaticHashTable(
            cfg.embed_dim, cfg.max_capacity, seed=cfg.seed + 1)
    user.load_state_dict(state["user"])
    item.load_state_dict(state["item"])
    batch = stream_lib.seeded_events(
        eval_seed, 0, n_eval, n_users=cfg.n_users, n_items=cfg.n_items,
        n_dense=cfg.n_dense, zipf_a=cfg.zipf_a)
    uidx = user.translate(batch["user"], train=False)
    iidx = item.translate(batch["item"], train=False)
    params = {k: jnp.asarray(v)
              for k, v in state["dense"]["params"].items()}
    logits = _forward(cfg, params, user.gather(uidx), item.gather(iidx),
                      jnp.asarray(batch["dense"]))
    labels = jnp.asarray(batch["label"], jnp.float32)
    loss = jnp.mean(jnp.maximum(logits, 0) - logits * labels
                    + jnp.log1p(jnp.exp(-jnp.abs(logits))))
    return float(loss)


def _is_dynamic(table_state: dict) -> bool:
    import pickle as _pickle
    aux = _pickle.loads(np.asarray(table_state["aux"],
                                   dtype=np.uint8).tobytes())
    return "id_to_row" in aux
