"""Flagship Transformer LM — TPU-native, fully sharded (dp × fsdp × tp).

Covers the reference's Transformer-big / BERT workload configs
(BASELINE.md configs #3 and #5). Where the reference runs these through
`MultiWorkerMirroredStrategy` + NCCL allreduce (reference:
tensorflow/python/distribute/collective_all_reduce_strategy.py:57), the
TPU-native design expresses every parallelism axis as a sharding over one
`jax.sharding.Mesh` and lets GSPMD insert the ICI collectives:

- dp:   batch sharding, gradient psum (≙ NcclAllReduce)
- fsdp: parameter + optimizer-state sharding along `embed`
        (≙ ShardedVariable, reference sharded_variable.py:843 — but over
        the *embed* axis with all-gather on use, not axis-0 PS placement)
- tp:   head/mlp/vocab sharding (≙ experimental_split_to_logical_devices,
        reference tpu_strategy.py:516)
- sp:   ring attention over the sequence axis (parallel/sequence_parallel)

Design notes (TPU-first):
- bfloat16 activations/params compute, float32 master params + adamw state.
- Flash attention (ops/attention.py) for the O(S) memory hot path.
- `nn.scan` over layers: one compiled block body regardless of depth.
- `nn.remat` on each block: recompute activations in backward, trading
  MXU FLOPs for HBM (the profitable direction on TPU).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.utils.jax_compat import (
    safe_donate_argnums)
import optax
from flax import linen as nn
from flax.linen import partitioning as nn_partitioning
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_tensorflow_tpu.ops.attention import flash_attention

param_with_axes = nn_partitioning.param_with_axes
def with_sharding_constraint(x, logical_axes, mesh=None):
    """flax's logical-axis sharding constraint with the mesh passed
    EXPLICITLY. In this jax/flax pairing ``with mesh:`` does not set the
    abstract-mesh context flax checks (``jax.sharding.get_abstract_mesh``
    — only ``jax.sharding.set_mesh`` does), so without the ``mesh``
    kwarg every logical constraint silently no-ops and GSPMD sharding
    propagation is free to pick mixed activation layouts (dp on batch +
    fsdp on d_model) whose transitions force involuntary full
    rematerialization. Duplicate mesh axes within one spec (batch over
    (dp, fsdp) plus embed over fsdp) resolve to unsharded for the later
    logical axis, matching the old intended semantics."""
    return nn_partitioning.with_sharding_constraint(x, logical_axes,
                                                    mesh=mesh)

# Logical axis name -> mesh axes. "sp" shards the sequence axis of
# activations when the mesh has it (ring attention path); "expert" axes
# shard MoE expert weights/activations over "ep" (parallel/moe.py).
LOGICAL_AXIS_RULES = (
    ("batch", ("dcn", "dp", "fsdp")),
    ("seq", "sp"),
    ("embed", "fsdp"),
    ("heads", "tp"),
    ("kv", None),
    ("mlp", "tp"),
    ("vocab", "tp"),
    ("layers", None),
    ("norm", None),
    ("expert", "ep"),
    ("expert_mlp", "tp"),
    ("expert_embed", None),
)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 1024
    n_layers: int = 12
    n_heads: int = 16
    d_ff: int = 4096
    max_seq_len: int = 2048
    dtype: Any = jnp.bfloat16
    causal: bool = True            # False -> bidirectional encoder (BERT)
    remat: bool = True
    remat_policy: str = "nothing"  # "nothing" | "dots" (save matmul outputs)
    scan_layers: bool = True
    attention_impl: str | None = None   # None = auto (pallas on TPU)
    # Pallas kernel tile sizes; the 512/1024 defaults are from the v5e
    # block sweep (tools/perf_sweep.py) — grid overhead dominates below
    # 512 and VMEM pressure wins above 1024 at head_dim 64.
    attn_block_q: int = 512
    attn_block_k: int = 1024
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    # Sequence/context parallelism: when mesh (threaded in by
    # make_sharded_train_step) has an "sp" axis > 1, attention runs as
    # ring attention over it (parallel/sequence_parallel.py).
    mesh: Any = None
    sp_impl: str = "ring"        # "ring" | "ulysses" | "striped" (causal)
    # per-step attention inside SP: "flash" | "unfused" | "interpret";
    # None = auto (flash on TPU — sequence_parallel._resolve_attn_impl)
    sp_attn_impl: str | None = None
    # Mixture-of-Experts: moe_experts > 0 replaces every block's MLP with
    # a Switch-style MoE layer (parallel/moe.py), expert-sharded over the
    # mesh's "ep" axis; the load-balancing aux loss flows to the train
    # step through the flax "losses" collection.
    moe_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01
    # Fused (chunked) cross-entropy: > 0 splits the sequence into this many
    # chunks and computes logits + CE per chunk inside a rematerialized
    # lax.scan, so the (B, S, vocab) fp32 logits tensor never materializes
    # in HBM (the memory wall that capped global batch at 8 on v5e).
    # 0 = classic full-logits path.
    loss_chunks: int = 0
    # Backward policy for the chunk scan: "recompute" re-derives each
    # chunk's logits in the backward (minimum memory); "save" keeps the
    # bf16 chunk logits (B·S·V·2 bytes — half the fp32 full-logits peak)
    # so the backward skips the vocab-projection recompute. Interleaved
    # A/B on v5e single chip measured "save" NEUTRAL-to-slightly-slower
    # (the extra HBM traffic for the saved logits cancels the skipped
    # matmul); kept as a knob for shapes where the recompute dominates
    # (bigger vocab, shorter chunks, bandwidth-rich parts).
    loss_chunk_policy: str = "recompute"
    # Fused-CE implementation: "scan" = the lax.scan chunk path above;
    # "kernel" = the Pallas vocab-tiled online-logsumexp kernels
    # (ops/fused_ce.py) — logits tiles never leave VMEM. On sharded
    # meshes the kernels run per-shard under shard_map with a cross-
    # shard logsumexp merge for tp-sharded vocabs
    # (ops/fused_ce.py sharded_fused_cross_entropy); meshes whose
    # shapes don't divide fall back to the scan path, whose einsums
    # GSPMD partitions natively. "kernel" implies the fused loss even
    # when loss_chunks == 0.
    loss_impl: str = "scan"
    loss_block_n: int = 512
    loss_block_v: int = 1024
    # Kernel-CE lowering: "pallas" | "interpret" | "reference" | None
    # (auto: pallas on TPU, reference elsewhere). "interpret" lets CPU
    # meshes (tests, dryrun) exercise the real kernel code paths.
    loss_kernel_impl: str | None = None
    # adamw first-moment dtype: bfloat16 halves the mu read+write HBM
    # traffic of the (bandwidth-bound) optimizer update; None = fp32.
    adam_mu_dtype: Any = None
    # Fused optimizer update: one Pallas pass per parameter leaf with
    # outputs aliased onto inputs (ops/fused_adamw.py) instead of the
    # optax update→apply chain. Elementwise, so it runs per-shard under
    # shard_map on sharded meshes (param_specs threaded in by
    # make_sharded_train_step). optimizer_impl: "pallas" | "interpret" |
    # "reference" | None (auto: pallas on TPU).
    fused_optimizer: bool = False
    optimizer_impl: str | None = None

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads

    @classmethod
    def tiny(cls, **kw) -> "TransformerConfig":
        """CI-sized config: compiles in seconds on a CPU mesh."""
        defaults = dict(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                        d_ff=128, max_seq_len=128, dtype=jnp.float32,
                        attention_impl="reference")
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def bert_base(cls, **kw) -> "TransformerConfig":
        defaults = dict(vocab_size=30522, d_model=768, n_layers=12,
                        n_heads=12, d_ff=3072, max_seq_len=512, causal=False)
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def transformer_big(cls, **kw) -> "TransformerConfig":
        """≙ Transformer-big WMT (BASELINE.md config #5)."""
        defaults = dict(vocab_size=32768, d_model=1024, n_layers=12,
                        n_heads=16, d_ff=4096, max_seq_len=1024)
        defaults.update(kw)
        return cls(**defaults)


class RMSNorm(nn.Module):
    dtype: Any = jnp.bfloat16
    eps: float = 1e-6
    mesh: Any = None

    @nn.compact
    def __call__(self, x):
        scale = param_with_axes("scale", nn.initializers.ones, (x.shape[-1],),
                                jnp.float32, axes=("norm",))
        x32 = x.astype(jnp.float32)
        var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
        y = x32 * jax.lax.rsqrt(var + self.eps) * scale
        y = y.astype(self.dtype)
        if y.ndim == 3:
            # Anchor the activation layout: without this, GSPMD sharding
            # propagation flows the fsdp-sharded weights' D-axis sharding
            # backward onto the norm output (a mixed dp-batch/fsdp-D
            # layout), and resharding the norm INPUT into it is a
            # transition XLA can only do by replicating ("involuntary
            # full rematerialization" — one full-activation broadcast
            # per layer on a dp×fsdp mesh).
            y = with_sharding_constraint(y, ("batch", "seq", "embed"),
                                         mesh=self.mesh)
        return y


def rotary_embedding(x, *, base: float = 10000.0, seq_axis: int = -3):
    """RoPE with the sequence axis at ``seq_axis`` and head_dim last.

    ``seq_axis=-3``: the (..., seq, heads, head_dim) projection layout;
    ``seq_axis=-2``: the (batch, heads, seq, head_dim) attention-kernel
    layout — projecting straight into kernel layout lets q/k/v skip the
    (B,S,H,d)->(B,H,S,d) transposes."""
    seq, d = x.shape[seq_axis], x.shape[-1]
    pos = jnp.arange(seq, dtype=jnp.float32)
    inv_freq = 1.0 / (base ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = pos[:, None] * inv_freq[None, :]          # (seq, d/2)
    bshape = [1] * x.ndim
    bshape[seq_axis], bshape[-1] = seq, d // 2
    sin = jnp.sin(angles).reshape(bshape)
    cos = jnp.cos(angles).reshape(bshape)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


class MultiHeadAttention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, lengths=None):
        cfg = self.cfg
        B, S, D = x.shape
        H, hd = cfg.n_heads, cfg.head_dim

        def proj(name):
            # Project DIRECTLY into the (B, H, S, hd) kernel layout —
            # the former bshk projection + transpose(0,2,1,3) pair cost
            # ~5 ms/step in pure copies (profile: 96 copy ops/step).
            kernel = param_with_axes(
                name, nn.initializers.normal(D ** -0.5), (D, H, hd),
                jnp.float32, axes=("embed", "heads", "kv"))
            return jnp.einsum("bsd,dhk->bhsk", x,
                              kernel.astype(cfg.dtype))

        q = rotary_embedding(proj("query"), seq_axis=-2)
        k = rotary_embedding(proj("key"), seq_axis=-2)
        v = proj("value")
        mesh = cfg.mesh
        if lengths is not None:
            # Right-padded mixed-length batch (serving prefill, BERT
            # over variable-length inputs): the ONE factored mask rule
            # (ops/attention.length_valid_mask) that the KV-cache
            # incremental decode also applies — full recompute and
            # cached decode mask identically by construction. The flash
            # kernels take no per-row length, so this path runs the
            # unfused reference; serving prefill shapes are
            # latency-bound, not HBM-bound.
            from distributed_tensorflow_tpu.ops.attention import (
                mha_reference)
            o = mha_reference(q, k, v, causal=cfg.causal, lengths=lengths)
        elif (mesh is not None and "sp" in mesh.shape
                and mesh.shape["sp"] > 1):
            # Sequence-parallel path: ring attention over the sp axis
            # (reference has no SP at all — SURVEY.md §5.7).
            from distributed_tensorflow_tpu.parallel.sequence_parallel \
                import make_ring_attention
            from distributed_tensorflow_tpu.cluster.topology import \
                attention_shard_spec
            base = attention_shard_spec(mesh)
            spec = P(base[0], base[1], "sp", None)
            o = make_ring_attention(mesh, causal=cfg.causal,
                                    impl=cfg.sp_impl, spec=spec,
                                    attn_impl=cfg.sp_attn_impl,
                                    block_q=cfg.attn_block_q,
                                    block_k=cfg.attn_block_k)(q, k, v)
        elif mesh is not None and mesh.size > 1:
            # Pallas custom calls can't be partitioned by GSPMD: run the
            # kernel per-shard via shard_map over batch/head axes.
            from distributed_tensorflow_tpu.ops.attention import \
                sharded_flash_attention
            o = sharded_flash_attention(q, k, v, mesh, causal=cfg.causal,
                                        block_q=cfg.attn_block_q,
                                        block_k=cfg.attn_block_k,
                                        implementation=cfg.attention_impl)
        else:
            o = flash_attention(q, k, v, causal=cfg.causal,
                                block_q=cfg.attn_block_q,
                                block_k=cfg.attn_block_k,
                                implementation=cfg.attention_impl)
        # Named save point: the "attn" remat policy keeps this tensor so
        # the backward pass never re-runs the flash kernel forward.
        from jax.ad_checkpoint import checkpoint_name
        o = checkpoint_name(o, "attn_out")            # (B, H, S, hd)

        out_kernel = param_with_axes(
            "out", nn.initializers.normal(D ** -0.5), (H, hd, D),
            jnp.float32, axes=("heads", "kv", "embed"))
        # Contract straight from kernel layout — no transpose back.
        o = jnp.einsum("bhsk,hkd->bsd", o, out_kernel.astype(cfg.dtype))
        return with_sharding_constraint(o, ("batch", "seq", "embed"),
                                        mesh=cfg.mesh)


class MLP(nn.Module):
    """SwiGLU feed-forward, tp-sharded on the hidden axis."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        D, F = cfg.d_model, cfg.d_ff
        wi = param_with_axes("wi", nn.initializers.normal(D ** -0.5),
                             (D, 2 * F), jnp.float32, axes=("embed", "mlp"))
        wo = param_with_axes("wo", nn.initializers.normal(F ** -0.5),
                             (F, D), jnp.float32, axes=("mlp", "embed"))
        h = jnp.einsum("bsd,df->bsf", x, wi.astype(cfg.dtype))
        gate, up = jnp.split(h, 2, axis=-1)
        h = nn.silu(gate) * up
        out = jnp.einsum("bsf,fd->bsd", h, wo.astype(cfg.dtype))
        return with_sharding_constraint(out, ("batch", "seq", "embed"),
                                        mesh=cfg.mesh)


def remat_policy_for(cfg: TransformerConfig):
    """The jax.checkpoint policy named by ``cfg.remat_policy`` (shared by
    the scan-layers path and the pipeline stage body)."""
    policies = {
        "nothing": jax.checkpoint_policies.nothing_saveable,
        "dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # Save only attention outputs: O(B·S·D) per layer, and the
        # backward never recomputes the flash kernel forward.
        "attn": jax.checkpoint_policies.save_only_these_names("attn_out"),
        # Save matmul outputs AND attention outputs: backward recomputes
        # neither. Measured SLOWER than "dots" on v5e at this model size
        # (saving attention outputs costs more bandwidth than the
        # full-sequence-block kernel recompute); kept for configs where
        # the kernel recompute dominates (longer sequences, small tiles).
        "dots_attn": jax.checkpoint_policies.save_from_both_policies(
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
            jax.checkpoint_policies.save_only_these_names("attn_out")),
    }
    if cfg.remat_policy not in policies:
        raise ValueError(f"remat_policy={cfg.remat_policy!r}; "
                         f"expected one of {sorted(policies)}")
    return policies[cfg.remat_policy]


class Block(nn.Module):
    """One transformer block with a scan-compatible (carry, _) signature.

    With ``cfg.moe_experts > 0`` the dense MLP is replaced by a
    Switch-style MoE layer (parallel/moe.py) whose aux loss is sown into
    the "losses" collection — summed over layers by the train step."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, lengths=None):
        cfg = self.cfg
        x = x + MultiHeadAttention(cfg, name="attn")(
            RMSNorm(cfg.dtype, mesh=cfg.mesh)(x), lengths)
        h = RMSNorm(cfg.dtype, mesh=cfg.mesh)(x)
        if cfg.moe_experts > 0:
            from distributed_tensorflow_tpu.parallel.moe import (
                MoEConfig, MoELayer)
            moe_cfg = MoEConfig(
                num_experts=cfg.moe_experts, d_model=cfg.d_model,
                d_ff=cfg.d_ff, capacity_factor=cfg.moe_capacity_factor,
                top_k=cfg.moe_top_k, aux_loss_weight=cfg.moe_aux_weight,
                dtype=cfg.dtype, mesh=cfg.mesh)
            out, aux = MoELayer(moe_cfg, name="moe")(h)
            self.sow("losses", "moe_aux", aux,
                     reduce_fn=lambda a, b: a + b, init_fn=lambda: 0.0)
            x = x + out
        else:
            x = x + MLP(cfg, name="mlp")(h)
        return x, None


class TransformerLM(nn.Module):
    """Decoder-only LM (cfg.causal=True) or bidirectional encoder."""
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, tokens, return_hidden=False, lengths=None):
        """``lengths`` (B,) marks a right-padded mixed-length batch:
        every layer's attention masks padded keys via the factored
        ``ops.attention.length_valid_mask`` rule (the full-sequence
        recompute side of the serving KV-cache correctness contract).
        None (the default) is the historical full-sequence behavior."""
        cfg = self.cfg
        embed = param_with_axes(
            "embed", nn.initializers.normal(0.02),
            (cfg.vocab_size, cfg.d_model), jnp.float32,
            axes=("vocab", "embed"))
        # Unshard the table's d_model axis (fsdp) BEFORE the lookup: a
        # gather from the fsdp-sharded table inherits D-over-fsdp output
        # sharding, and the transition from that to the batch-sharded
        # activation layout is one GSPMD cannot do efficiently (it
        # replicates — "involuntary full rematerialization"). Gathering
        # from a D-unsharded table makes the output inherit the token
        # batch sharding directly; the explicit all-gather this forces
        # is the same V×D traffic, minus the bad transition.
        emb_c = with_sharding_constraint(embed.astype(cfg.dtype),
                                         ("vocab", None), mesh=cfg.mesh)
        x = emb_c[tokens]
        x = with_sharding_constraint(x, ("batch", "seq", "embed"),
                                     mesh=cfg.mesh)

        block = Block
        if cfg.remat:
            policy = remat_policy_for(cfg)
            block = nn_partitioning.remat(
                block, policy=policy,
                prevent_cse=not cfg.scan_layers)
        if cfg.scan_layers:
            variable_axes = {"params": 0}
            if cfg.moe_experts > 0:
                variable_axes["losses"] = 0     # per-layer aux stack
            x, _ = nn_partitioning.scan_with_axes(
                block,
                variable_axes=variable_axes,
                split_rngs={"params": True},
                in_axes=nn.broadcast,
                length=cfg.n_layers,
                axis_name="layers",
            )(cfg, name="layers")(x, lengths)
        else:
            for i in range(cfg.n_layers):
                x, _ = block(cfg, name=f"layer_{i}")(x, lengths)

        x = RMSNorm(cfg.dtype, mesh=cfg.mesh, name="final_norm")(x)
        if return_hidden:
            # Fused-loss path: the caller computes chunked logits + CE
            # against the tied embedding itself (fused_next_token_loss).
            return x
        logits = jnp.einsum("bsd,vd->bsv", x, embed.astype(cfg.dtype))
        return logits.astype(jnp.float32)


# ---------------------------------------------------------------------------
# Training step
# ---------------------------------------------------------------------------

def next_token_loss(logits, tokens):
    """Shifted next-token cross-entropy (ignores the final position)."""
    targets = tokens[:, 1:]
    logits = logits[:, :-1]
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    return losses.mean()


def fused_next_token_loss(hidden, embed, tokens, *, num_chunks,
                          compute_dtype=jnp.bfloat16,
                          chunk_policy: str = "recompute"):
    """Chunked next-token CE over the tied embedding — the fused loss.

    Equivalent to ``next_token_loss(einsum(hidden, embed), tokens)`` but
    the (B, S, vocab) fp32 logits tensor never exists: each of
    ``num_chunks`` sequence chunks computes its (B, S/num_chunks, vocab)
    logits inside a rematerialized ``lax.scan`` body, reduces them to a
    partial CE sum, and the backward recomputes one chunk's logits at a
    time. This removes the dominant HBM peak of the training step (for
    transformer_big at batch 16 / seq 1024 / vocab 32k the logits +
    their cotangent alone are 4 GiB fp32).

    ≙ the reference's fused softmax-CE op
    (TF/python/ops/nn_ops.py softmax_cross_entropy_with_logits lowering
    to a fused XLA reduction) — extended to also fuse away the vocab
    projection, which the reference never needed because GPU HBM held
    its logits.
    """
    B, S, D = hidden.shape
    if S % num_chunks:
        raise ValueError(f"seq len {S} not divisible by "
                         f"loss num_chunks={num_chunks}")
    C = S // num_chunks
    targets, mask = _shifted_targets_and_mask(tokens)
    emb = embed.astype(compute_dtype)
    xs = (hidden.reshape(B, num_chunks, C, D).swapaxes(0, 1),
          targets.reshape(B, num_chunks, C).swapaxes(0, 1),
          mask.reshape(B, num_chunks, C).swapaxes(0, 1))

    def chunk_body(carry, xtm):
        xc, tc, mc = xtm
        logits = jnp.einsum("bcd,vd->bcv", xc.astype(compute_dtype), emb)
        # Named BEFORE the fp32 cast: the "save" policy keeps the bf16
        # form (half the bandwidth/footprint of saving fp32).
        from jax.ad_checkpoint import checkpoint_name
        logits = checkpoint_name(logits, "ce_logits").astype(jnp.float32)
        ls = optax.softmax_cross_entropy_with_integer_labels(logits, tc)
        return carry + jnp.sum(ls * mc), None

    if chunk_policy == "save":
        policy = jax.checkpoint_policies.save_only_these_names("ce_logits")
    elif chunk_policy == "recompute":
        policy = jax.checkpoint_policies.nothing_saveable
    else:
        raise ValueError(f"chunk_policy={chunk_policy!r}; expected "
                         f"'recompute' or 'save'")
    total, _ = jax.lax.scan(
        jax.checkpoint(chunk_body, policy=policy),
        jnp.zeros((), jnp.float32), xs)
    return total / (B * (S - 1))


def _shifted_targets_and_mask(tokens):
    """Next-token shift shared by every fused-loss path: position t
    predicts token t+1; the final position has no target (pad target 0,
    mask 0) — identical semantics to ``next_token_loss``."""
    B, S = tokens.shape
    targets = jnp.concatenate(
        [tokens[:, 1:], jnp.zeros((B, 1), tokens.dtype)], axis=1)
    mask = jnp.concatenate(
        [jnp.ones((B, S - 1), jnp.float32), jnp.zeros((B, 1), jnp.float32)],
        axis=1)
    return targets, mask


def kernel_next_token_loss(hidden, embed, tokens, *,
                           compute_dtype=jnp.bfloat16,
                           block_n: int = 512, block_v: int = 1024,
                           implementation: str | None = None,
                           mesh=None):
    """Shifted next-token CE via the Pallas fused-CE kernels
    (ops/fused_ce.py) — same semantics as ``fused_next_token_loss`` /
    ``next_token_loss`` but the (B, S, vocab) logits tensor never exists
    even per-chunk: vocab tiles stream through VMEM.

    With a sharded ``mesh`` the kernels run per-shard under shard_map
    (tokens over dcn/dp/fsdp/sp, vocab over tp with a cross-shard
    logsumexp merge — ops/fused_ce.py sharded_fused_cross_entropy).
    The next-token SHIFT happens here, outside the shard_map, so GSPMD
    handles the sp-boundary halo exchange of the shifted targets."""
    B, S, D = hidden.shape
    targets, mask = _shifted_targets_and_mask(tokens)
    if mesh is not None and mesh.size > 1:
        from distributed_tensorflow_tpu.ops.fused_ce import (
            sharded_fused_cross_entropy)
        losses = sharded_fused_cross_entropy(
            hidden.astype(compute_dtype), embed.astype(compute_dtype),
            targets, mesh, block_n=block_n, block_v=block_v,
            implementation=implementation)
        return jnp.sum(losses * mask) / (B * (S - 1))
    from distributed_tensorflow_tpu.ops.fused_ce import fused_cross_entropy
    losses = fused_cross_entropy(
        hidden.reshape(B * S, D).astype(compute_dtype),
        embed.astype(compute_dtype), targets.reshape(B * S),
        block_n=block_n, block_v=block_v, implementation=implementation)
    return jnp.sum(losses * mask.reshape(B * S)) / (B * (S - 1))


def make_optimizer(cfg: TransformerConfig):
    return optax.adamw(cfg.learning_rate, weight_decay=cfg.weight_decay,
                       mu_dtype=cfg.adam_mu_dtype)


def _find_adam_state(opt_state):
    """Index of the ScaleByAdamState (count/mu/nu) in an optax chain
    state tuple; raises if the transform isn't adam-shaped."""
    for i, s in enumerate(opt_state):
        if hasattr(s, "mu") and hasattr(s, "nu") and hasattr(s, "count"):
            return i
    raise ValueError(
        "fused_optimizer=True needs an optax.adamw-style chain state "
        f"(ScaleByAdamState not found in {type(opt_state)})")


def make_loss_fn(cfg: TransformerConfig, model: TransformerLM):
    """loss_fn(params, tokens) -> scalar for ``cfg``/``model`` — the
    objective shared by the GSPMD step, the bucketed data-parallel step,
    and the pipeline schedules. With MoE the per-layer load-balancing aux
    losses (flax "losses" collection) are summed in (≙ Switch
    Transformer training)."""

    if cfg.loss_impl not in ("scan", "kernel"):
        raise ValueError(f"loss_impl={cfg.loss_impl!r}; expected "
                         f"'scan' or 'kernel'")
    # The kernel CE path runs everywhere: plain on a single chip,
    # per-shard under shard_map on sharded meshes (tokens over
    # dcn/dp/fsdp/sp, vocab over tp with a cross-shard logsumexp merge
    # — ops/fused_ce.py sharded_fused_cross_entropy). Only meshes whose
    # shard counts don't divide the batch/seq/vocab shapes fall back to
    # the scan path, whose einsums GSPMD partitions natively.
    # loss_impl="kernel" implies a FUSED loss in every case: the
    # fallback uses the scan path with a default chunk count rather
    # than ever materializing full (B, S, vocab) logits.
    use_kernel = cfg.loss_impl == "kernel"
    fused = cfg.loss_chunks > 0 or cfg.loss_impl == "kernel"
    if cfg.loss_chunks > 0:
        scan_chunks = cfg.loss_chunks
    else:
        # kernel→scan fallback default: the largest power of two that
        # divides the sequence length, capped at 8 (a blind 8 would
        # crash at trace time on seq lens not divisible by 8)
        scan_chunks = 1
        while (scan_chunks < 8
               and cfg.max_seq_len % (scan_chunks * 2) == 0):
            scan_chunks *= 2

    def _kernel_mesh_ok(B, S):
        mesh = cfg.mesh
        if mesh is None or mesh.size == 1:
            return True
        n_batch = 1
        for a in ("dcn", "dp", "fsdp"):
            if a in mesh.shape:
                n_batch *= mesh.shape[a]
        sp = mesh.shape.get("sp", 1)
        tp = mesh.shape.get("tp", 1)
        return (B % n_batch == 0 and S % sp == 0
                and cfg.vocab_size % tp == 0)

    def objective(out, params, tokens):
        if use_kernel and _kernel_mesh_ok(*out.shape[:2]):
            return kernel_next_token_loss(
                out, params["embed"], tokens, compute_dtype=cfg.dtype,
                block_n=cfg.loss_block_n, block_v=cfg.loss_block_v,
                implementation=cfg.loss_kernel_impl, mesh=cfg.mesh)
        if fused:
            return fused_next_token_loss(
                out, params["embed"], tokens,
                num_chunks=scan_chunks, compute_dtype=cfg.dtype,
                chunk_policy=cfg.loss_chunk_policy)
        return next_token_loss(out, tokens)

    def loss_fn(params, tokens):
        if cfg.moe_experts > 0:
            out, out_vars = model.apply({"params": params}, tokens, fused,
                                        mutable=["losses"])
            aux = sum(jnp.sum(leaf) for leaf in
                      jax.tree_util.tree_leaves(out_vars.get("losses", {})))
            return objective(out, params, tokens) + aux
        out = model.apply({"params": params}, tokens, fused)
        return objective(out, params, tokens)

    return loss_fn


def make_train_step(cfg: TransformerConfig, model: TransformerLM, tx,
                    param_specs=None):
    """Functional (state, batch) -> (state, metrics) SPMD step built on
    :func:`make_loss_fn`. ``param_specs`` (a pytree of PartitionSpecs
    matching params) lets the fused optimizer run per-shard on sharded
    meshes."""
    loss_fn = make_loss_fn(cfg, model)

    # The fused update needs per-shard execution on sharded meshes; with
    # no param_specs on a >1 mesh the pallas call would run replicated
    # (GSPMD can't partition it) — keep the optax path there.
    use_fused_opt = cfg.fused_optimizer and (
        cfg.mesh is None or cfg.mesh.size == 1 or param_specs is not None)

    def fused_opt_step(state, grads):
        from distributed_tensorflow_tpu.ops.fused_adamw import (
            fused_adamw_update)
        opt_state = state["opt_state"]
        # The fused kernel REPLACES the whole optax chain with AdamW on
        # cfg.learning_rate/weight_decay — a tx with extra stateful
        # transforms would be silently skipped. Require the state
        # structure to match make_optimizer(cfg) exactly so a custom tx
        # (clipping, schedules, different chain) fails loudly here.
        expected = jax.eval_shape(
            lambda p: make_optimizer(cfg).init(p), state["params"])
        if (jax.tree_util.tree_structure(expected)
                != jax.tree_util.tree_structure(opt_state)):
            raise ValueError(
                "fused_optimizer=True supports exactly the "
                "make_optimizer(cfg) adamw chain; the provided "
                "optimizer's state structure differs — set "
                "fused_optimizer=False or use make_optimizer(cfg)")
        idx = _find_adam_state(opt_state)
        adam = opt_state[idx]
        params, mu, nu, count = fused_adamw_update(
            state["params"], grads, adam.mu, adam.nu, adam.count,
            lr=cfg.learning_rate, weight_decay=cfg.weight_decay,
            implementation=cfg.optimizer_impl, mesh=cfg.mesh,
            param_specs=param_specs)
        new_adam = adam._replace(count=count, mu=mu, nu=nu)
        return params, tuple(new_adam if i == idx else s
                             for i, s in enumerate(opt_state))

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"],
                                                  batch["tokens"])
        if use_fused_opt:
            params, opt_state = fused_opt_step(state, grads)
        else:
            updates, opt_state = tx.update(grads, state["opt_state"],
                                           state["params"])
            params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss})

    return train_step


def mesh_axis_rules(mesh: Mesh, rules: Sequence = LOGICAL_AXIS_RULES):
    """Restrict logical-axis rules to the axes this mesh actually has, so
    the same model code runs on any mesh (dp-only, dp×tp, dp×fsdp×tp, …)."""
    out = []
    for logical, target in rules:
        if target is None:
            out.append((logical, None))
        elif isinstance(target, tuple):
            kept = tuple(a for a in target if a in mesh.shape)
            out.append((logical, kept if kept else None))
        else:
            out.append((logical, target if target in mesh.shape else None))
    return out


def _shard_like(tree, params_treedef, param_shardings, replicated):
    """Give every sub-tree that structurally matches ``params`` (mu, nu in
    adamw) the param shardings; replicate everything else."""
    def per_node(node):
        if jax.tree_util.tree_structure(node) == params_treedef:
            return param_shardings
        if hasattr(node, "_fields"):          # optax NamedTuple state
            return type(node)(*[per_node(getattr(node, f))
                                for f in node._fields])
        if isinstance(node, tuple):
            return tuple(per_node(x) for x in node)
        return jax.tree_util.tree_map(lambda _: replicated, node)
    return per_node(tree)


def state_shardings_for(model, tx, mesh: Mesh, example_tokens,
                        rules: Sequence | None = None):
    """Derive NamedShardings for the full train state from the model's
    logical axis metadata (the flax ``params_axes`` collection)."""
    rules = mesh_axis_rules(mesh) if rules is None else rules
    rng = jax.random.PRNGKey(0)
    with nn_partitioning.axis_rules(list(rules)):
        var_shapes = jax.eval_shape(
            lambda r: model.init(r, example_tokens), rng)
        logical_specs = nn_partitioning.get_axis_names(
            var_shapes["params_axes"])
        mesh_specs = nn_partitioning.logical_to_mesh(logical_specs)
    param_shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), mesh_specs,
        is_leaf=lambda x: isinstance(x, P))
    if hasattr(param_shardings, "unfreeze"):
        param_shardings = param_shardings.unfreeze()

    params_treedef = jax.tree_util.tree_structure(var_shapes["params"])
    replicated = NamedSharding(mesh, P())
    opt_shapes = jax.eval_shape(tx.init, var_shapes["params"])
    opt_shardings = _shard_like(opt_shapes, params_treedef,
                                param_shardings, replicated)
    return {"params": param_shardings, "opt_state": opt_shardings,
            "step": replicated}


def make_sharded_train_step(cfg: TransformerConfig, mesh: Mesh,
                            global_batch: int, seed: int = 0,
                            step_factory=None, grad_sync: str = "auto",
                            zero: int = 0):
    """Initialize sharded state and return (state, jitted step_fn).

    The returned step consumes batches of shape (global_batch, seq);
    inputs are sharded ("batch" over dcn×dp×fsdp, "seq" over sp if
    present) and all gradient/weight collectives are inserted by GSPMD
    over the mesh — the TPU-native replacement for the reference's
    CrossDeviceOps.batch_reduce (cross_device_ops.py:871).

    ``grad_sync`` selects the gradient-reduction schedule:

    - ``"bucketed"`` — explicit shard_map step with reverse-layer-order
      bucketed gradient allreduce (collectives.GradientBucketer): each
      bucket's psum launches as soon as backprop has produced its
      gradients, overlapping ICI/DCN reduction with the remaining
      backward pass. Pure data-parallel meshes only (axes ⊆ {dcn, dp}).
      On a hybrid dcn×dp mesh each bucket takes the hierarchical path,
      the DCN hop overlapping the next bucket's ICI phases.
    - ``"gspmd"`` — one compiler-scheduled sync (the pre-ISSUE-6 path).
    - ``"auto"`` (default) — "bucketed" on >1-device pure-dp meshes
      (no MoE, default step), "gspmd" otherwise.
    - ``"none"`` — MEASUREMENT ONLY: the bucketed step with the gradient
      sync deleted (each shard applies its LOCAL grads — replicas
      diverge, so never train with this). Timing full vs "none" isolates
      the step's exposed collective time; bench.py's phase-breakdown
      rows (``compute_frac``/``collective_frac``/``overlap_eff``) are
      the full/none/collective-only delta.

    ``step_factory(cfg, model, tx)`` lets variants (BERT MLM) swap the
    per-step loss while reusing all sharding/jit wiring.

    ``zero`` selects ZeRO optimizer-state sharding over the dp axis
    (parallel/zero.py; params stay replicated over dp, Adam slots exist
    only for each rank's 1/N bucket slice — bit-identical to replicated
    Adam). Level 1 all-reduces gradients as usual; level 2
    reduce-scatters them so the full gradient buffer never materializes
    either. On meshes that are not exactly ("dp",), gradient sync stays
    with GSPMD and levels 1/2 behave identically (slots sharded, grads
    compiler-managed).
    """
    from distributed_tensorflow_tpu.cluster.topology import \
        data_axes as mesh_data_axes
    pure_dp = (set(mesh.shape) <= {"dcn", "dp"} and mesh.size > 1
               and cfg.moe_experts == 0 and step_factory is None)
    if grad_sync not in ("auto", "bucketed", "gspmd", "none"):
        raise ValueError(f"grad_sync={grad_sync!r}; expected auto/"
                         f"bucketed/gspmd/none")
    if zero not in (0, 1, 2):
        raise ValueError(f"zero={zero!r}; expected 0, 1, or 2")
    if zero:
        if step_factory is not None:
            raise ValueError("zero= is not supported with step_factory")
        if cfg.moe_experts > 0:
            raise NotImplementedError("zero= with MoE is not supported")
        if cfg.fused_optimizer:
            raise ValueError("zero= replaces the optimizer update; set "
                             "fused_optimizer=False")
        if grad_sync != "auto":
            raise ValueError("zero= owns the gradient sync schedule; "
                             "leave grad_sync='auto'")
        if tuple(mesh.axis_names) == ("dp",):
            return _make_zero_dp_train_step(cfg, mesh, global_batch,
                                            seed, level=zero)
        return _make_zero_gspmd_train_step(cfg, mesh, global_batch,
                                           seed, level=zero)
    if grad_sync in ("bucketed", "none") and not pure_dp:
        raise ValueError(
            f"grad_sync={grad_sync!r} needs a pure data-parallel mesh "
            f"(axes ⊆ {{dcn, dp}}, >1 device, no MoE); got "
            f"{dict(mesh.shape)}")
    if pure_dp and grad_sync in ("auto", "bucketed", "none"):
        return _make_bucketed_dp_train_step(cfg, mesh, global_batch, seed,
                                            sync=grad_sync != "none")
    if cfg.mesh is None:
        cfg = dataclasses.replace(cfg, mesh=mesh)
    model = TransformerLM(cfg)
    tx = make_optimizer(cfg)
    rng = jax.random.PRNGKey(seed)
    tokens_shape = jnp.zeros((global_batch, cfg.max_seq_len), jnp.int32)

    state_shardings = state_shardings_for(model, tx, mesh, tokens_shape)

    def init_fn(rng):
        params = model.init(rng, tokens_shape)["params"]
        return {"params": params, "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    replicated = NamedSharding(mesh, P())
    data_axes = mesh_data_axes(mesh)
    seq_axis = "sp" if "sp" in mesh.shape else None
    batch_shardings = {"tokens": NamedSharding(
        mesh, P(data_axes if data_axes else None, seq_axis))}

    rules = mesh_axis_rules(mesh)
    factory = step_factory or make_train_step
    factory_kwargs = {}
    import inspect
    if "param_specs" in inspect.signature(factory).parameters:
        factory_kwargs["param_specs"] = jax.tree_util.tree_map(
            lambda ns: ns.spec, state_shardings["params"])
    step = factory(cfg, model, tx, **factory_kwargs)
    with mesh, nn_partitioning.axis_rules(rules):
        state = jax.jit(init_fn, out_shardings=state_shardings)(rng)
        step_jit = jax.jit(
            step,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(state_shardings, replicated),
            donate_argnums=safe_donate_argnums((0,)))

    def wrapped_step(state, batch):
        with mesh, nn_partitioning.axis_rules(rules):
            return step_jit(state, batch)

    return state, wrapped_step


def _make_bucketed_dp_train_step(cfg: TransformerConfig, mesh: Mesh,
                                 global_batch: int, seed: int = 0,
                                 *, sync: bool = True):
    """Pure data-parallel train step with explicit comm/compute overlap:
    the whole step runs under shard_map, per-device grads are reduced by
    collectives.GradientBucketer in reverse layer order (last-layer
    buckets launch while earlier layers still differentiate), and the
    replicated optimizer applies locally. Parameters are replicated on a
    pure-dp mesh, so state/step signatures match the GSPMD path
    (state replicated, batch sharded over dcn×dp).

    ``sync=False`` deletes the gradient collectives (grad_sync="none"):
    the identical program minus the reduction, for isolating exposed
    collective time in phase-breakdown measurements."""
    from distributed_tensorflow_tpu.cluster.topology import \
        data_axes as mesh_data_axes
    from distributed_tensorflow_tpu.parallel.collectives import (
        GradientBucketer, ReduceOp)
    from distributed_tensorflow_tpu.parallel.collectives import (
        all_reduce as collectives_all_reduce)

    data_axes = mesh_data_axes(mesh)
    n_shards = 1
    for a in data_axes:
        n_shards *= mesh.shape[a]
    if global_batch % n_shards:
        raise ValueError(f"global_batch={global_batch} not divisible by "
                         f"{n_shards} data shards of {dict(mesh.shape)}")
    # inside shard_map everything is per-shard: plain local kernels, no
    # nested sharding machinery (same convention as the pipeline path)
    cfg_local = dataclasses.replace(cfg, mesh=None)
    model = TransformerLM(cfg_local)
    tx = make_optimizer(cfg)
    loss_fn = make_loss_fn(cfg_local, model)

    outer = inner = None
    if len(data_axes) == 2 and all(mesh.shape[a] > 1 for a in data_axes):
        outer, inner = data_axes           # ("dcn", "dp") hybrid
    bucketer = GradientBucketer(data_axes, outer_axis=outer,
                                inner_axis=inner)

    rng = jax.random.PRNGKey(seed)
    tokens_shape = jnp.zeros((global_batch, cfg.max_seq_len), jnp.int32)
    replicated = NamedSharding(mesh, P())

    def init_fn(rng):
        params = model.init(rng, tokens_shape)["params"]
        return {"params": params, "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    state_shardings = jax.tree_util.tree_map(
        lambda _: replicated, jax.eval_shape(init_fn, rng))
    state = jax.jit(init_fn, out_shardings=state_shardings)(rng)

    def spmd_step(state, batch):
        # local mean loss; the global objective is the mean over shards,
        # so grads sync as a bucketed MEAN allreduce
        loss, grads = jax.value_and_grad(loss_fn)(state["params"],
                                                  batch["tokens"])
        if sync:
            grads = bucketer.all_reduce(grads, op=ReduceOp.MEAN)
            loss = collectives_all_reduce(loss, data_axes, ReduceOp.MEAN)
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss})

    batch_spec = {"tokens": P(data_axes)}
    state_spec = jax.tree_util.tree_map(lambda _: P(), state)
    shard_step = jax.shard_map(
        spmd_step, mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        check_vma=False)
    batch_shardings = {"tokens": NamedSharding(mesh, P(data_axes))}
    step_jit = jax.jit(
        shard_step,
        in_shardings=(state_shardings, batch_shardings),
        out_shardings=(state_shardings, replicated),
        donate_argnums=safe_donate_argnums((0,)))

    def wrapped_step(state, batch):
        with mesh:
            return step_jit(state, batch)

    return state, wrapped_step


def _make_zero_dp_train_step(cfg: TransformerConfig, mesh: Mesh,
                             global_batch: int, seed: int = 0,
                             *, level: int = 1):
    """Pure data-parallel train step with ZeRO-sharded optimizer state
    (parallel/zero.py). Like :func:`_make_bucketed_dp_train_step` the
    whole step runs under shard_map with replicated params, but Adam's
    mu/nu exist only as each rank's 1/N slice of the packed parameter
    buckets. Level 1 syncs gradients with the same bucketed MEAN
    allreduce as the replicated path (bit-identical grads); level 2
    reduce-scatters the same packed buckets instead, so each rank only
    materializes its gradient shard. After the sliced update an
    all-gather over dp rebuilds the parameters — bit-identical to
    replicated Adam (tests/test_zero.py)."""
    from distributed_tensorflow_tpu import telemetry as _telemetry
    from distributed_tensorflow_tpu.parallel.collectives import (
        GradientBucketer, ReduceOp)
    from distributed_tensorflow_tpu.parallel.collectives import (
        all_reduce as collectives_all_reduce)
    from distributed_tensorflow_tpu.parallel.zero import (
        ZeroPartition, zero_opt_state)

    if tuple(mesh.axis_names) != ("dp",):
        raise ValueError(f"ZeRO explicit dp path needs a ('dp',) mesh, "
                         f"got {tuple(mesh.axis_names)}")
    n_shards = mesh.size
    if global_batch % n_shards:
        raise ValueError(f"global_batch={global_batch} not divisible by "
                         f"dp={n_shards}")
    cfg_local = dataclasses.replace(cfg, mesh=None)
    model = TransformerLM(cfg_local)
    tx = make_optimizer(cfg)
    loss_fn = make_loss_fn(cfg_local, model)
    bucketer = GradientBucketer(("dp",))

    rng = jax.random.PRNGKey(seed)
    tokens_shape = jnp.zeros((global_batch, cfg.max_seq_len), jnp.int32)
    replicated = NamedSharding(mesh, P())

    def init_params(rng):
        return model.init(rng, tokens_shape)["params"]

    params_abstract = jax.eval_shape(init_params, rng)
    param_shardings = jax.tree_util.tree_map(
        lambda _: replicated, params_abstract)
    params = jax.jit(init_params, out_shardings=param_shardings)(rng)

    leaves_abs, _ = jax.tree_util.tree_flatten(params_abstract)
    # same bucket plan as the bucketer's gradient sync, so the level-2
    # reduce-scatter runs over the very buffers level 1 would pmean
    partition = ZeroPartition(leaves_abs, n_shards)
    opt_state, opt_shardings, opt_specs = zero_opt_state(
        tx, partition, mesh, axes=("dp",))
    _telemetry.event("zero.partition", axis="dp", level=int(level),
                     **partition.summary())

    state = {"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    state_shardings = {"params": param_shardings,
                       "opt_state": opt_shardings, "step": replicated}
    state_spec = {"params": jax.tree_util.tree_map(
                      lambda _: P(), params_abstract),
                  "opt_state": opt_specs, "step": P()}

    def spmd_step(state, batch):
        params = state["params"]
        loss, grads = jax.value_and_grad(loss_fn)(params, batch["tokens"])
        loss = collectives_all_reduce(loss, ("dp",), ReduceOp.MEAN)
        rank = jax.lax.axis_index("dp")
        if level == 1:
            grads = bucketer.all_reduce(grads, op=ReduceOp.MEAN)
            g_shards = partition.shard(
                partition.pack(jax.tree_util.tree_leaves(grads)), rank)
        else:
            g_shards = partition.reduce_scatter_mean(
                jax.tree_util.tree_leaves(grads), "dp")
        pl, td = jax.tree_util.tree_flatten(params)
        p_shards = partition.shard(partition.pack(pl), rank)
        updates, new_opt = tx.update(g_shards, state["opt_state"],
                                     p_shards)
        new_shards = optax.apply_updates(p_shards, updates)
        flats = partition.all_gather_flats(new_shards, "dp")
        new_params = jax.tree_util.tree_unflatten(
            td, partition.unpack(flats))
        return ({"params": new_params, "opt_state": new_opt,
                 "step": state["step"] + 1},
                {"loss": loss})

    batch_spec = {"tokens": P("dp")}
    shard_step = jax.shard_map(
        spmd_step, mesh=mesh,
        in_specs=(state_spec, batch_spec),
        out_specs=(state_spec, P()),
        check_vma=False)
    batch_shardings = {"tokens": NamedSharding(mesh, P("dp"))}
    step_jit = jax.jit(
        shard_step,
        in_shardings=(state_shardings, batch_shardings),
        out_shardings=(state_shardings, replicated),
        donate_argnums=safe_donate_argnums((0,)))

    def wrapped_step(state, batch):
        with mesh:
            return step_jit(state, batch)

    return state, wrapped_step


def _make_zero_gspmd_train_step(cfg: TransformerConfig, mesh: Mesh,
                                global_batch: int, seed: int = 0,
                                *, level: int = 1):
    """ZeRO optimizer-state sharding on a general mesh (dp×tp, single
    device, dcn hybrids) as a split program: the gradient computation
    stays a GSPMD jit exactly like the replicated path (so grads are
    bit-identical to it), and the optimizer update runs as a nested
    shard_map (parallel/zero.make_zero_update) that slices each dp
    rank's bucket shard of the mesh-local parameter blocks, updates it,
    and all-gathers over dp alone. Gradient sync is compiler-managed
    here, so levels 1 and 2 both shard only the slots."""
    from distributed_tensorflow_tpu.cluster.topology import \
        data_axes as mesh_data_axes
    from distributed_tensorflow_tpu.parallel.zero import make_zero_update

    del level  # grads are GSPMD-synced: levels differ only on pure dp
    if cfg.mesh is None:
        cfg = dataclasses.replace(cfg, mesh=mesh)
    model = TransformerLM(cfg)
    tx = make_optimizer(cfg)
    rng = jax.random.PRNGKey(seed)
    tokens_shape = jnp.zeros((global_batch, cfg.max_seq_len), jnp.int32)

    shardings = state_shardings_for(model, tx, mesh, tokens_shape)
    param_shardings = shardings["params"]
    param_specs = jax.tree_util.tree_map(
        lambda ns: ns.spec, param_shardings,
        is_leaf=lambda x: isinstance(x, NamedSharding))
    replicated = NamedSharding(mesh, P())
    rules = mesh_axis_rules(mesh)

    def init_params(rng):
        return model.init(rng, tokens_shape)["params"]

    with mesh, nn_partitioning.axis_rules(rules):
        params_abstract = jax.eval_shape(init_params, rng)
        params = jax.jit(init_params,
                         out_shardings=param_shardings)(rng)

    opt_state, opt_shardings, zero_update = make_zero_update(
        tx, mesh, param_specs, params_abstract, axis_name="dp")
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    state_shardings = {"params": param_shardings,
                       "opt_state": opt_shardings, "step": replicated}

    loss_fn = make_loss_fn(cfg, model)
    data_axes = mesh_data_axes(mesh)
    seq_axis = "sp" if "sp" in mesh.shape else None
    batch_shardings = {"tokens": NamedSharding(
        mesh, P(data_axes if data_axes else None, seq_axis))}

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"],
                                                  batch["tokens"])
        new_params, new_opt = zero_update(state["params"], grads,
                                          state["opt_state"])
        return ({"params": new_params, "opt_state": new_opt,
                 "step": state["step"] + 1},
                {"loss": loss})

    with mesh, nn_partitioning.axis_rules(rules):
        step_jit = jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(state_shardings, replicated),
            donate_argnums=safe_donate_argnums((0,)))

    def wrapped_step(state, batch):
        with mesh, nn_partitioning.axis_rules(rules):
            return step_jit(state, batch)

    return state, wrapped_step


def make_pipelined_train_step(cfg: TransformerConfig, mesh: Mesh,
                              global_batch: int, num_microbatches: int,
                              seed: int = 0, schedule: str = "gpipe",
                              interleave: int = 2, zero: int = 0,
                              offload_activations=False):
    """Pipeline parallelism for the flagship transformer over a dp×pp
    mesh (parallel/pipeline.py; the reference has NO pipeline
    parallelism — SURVEY.md §2.8 row PP). ``schedule`` picks "gpipe"
    (forward pipeline + autodiff reverse; bubble (S-1)/(M+S-1),
    activation memory O(M)), "1f1b" (interleaved
    one-forward-one-backward with per-stage rematerialization; bubble
    2(S-1)/(M+2(S-1)) in the lockstep realization, activation memory
    O(S) — see parallel/pipeline.py), or "interleaved" (Megatron-style
    virtual stages: each pp rank holds ``interleave`` non-adjacent
    layer chunks, bubble (vW+W-2)/(Mv+vW+W-2) — below plain 1F1B for
    v>=2). All schedules compute the same objective; 1F1B and
    interleaved are loss-parity-tested against GPipe.

    - The scan-over-layers parameter stack (L, ...) regroups to
      (pp, L/pp, ...) with the stage axis sharded over "pp": each device
      holds exactly its stage's layers.
    - Microbatches flow through stages via ppermute inside a lax.scan
      (pipeline_apply); autodiff through it yields the reverse-schedule
      backward pipeline, with gradient accumulation over microbatches
      falling out of the loss mean.
    - Embedding + final norm + logits run as plain GSPMD ops outside the
      shard_map (batch sharded over dp, replicated over pp).

    ``offload_activations`` (1F1B only) re-realizes the schedule as a
    host-driven cycle loop whose per-stage activation stash spills to
    HOST memory between a microbatch's forward and backward
    (parallel/offload.py): device activation residency drops from
    O(min(M, 2S-1)) microbatches per rank to O(1). ``True`` spills
    (async device->host copies through the ``offload.spill`` chaos
    fault site); ``"device"`` runs the same host-driven loop with the
    stash kept as device arrays — the two are bit-identical end to end
    (the spill itself changes nothing), and vs the fused single-jit
    schedule losses are bit-identical with params agreeing to float
    tolerance (cross-program fusion artifact, see parallel/offload.py).

    Returns (state, step_fn) like make_sharded_train_step.
    """
    from distributed_tensorflow_tpu.parallel.pipeline import (
        make_1f1b_fn, make_interleaved_1f1b_fn, make_pipelined_fn)

    if schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(f"schedule={schedule!r}; expected 'gpipe', "
                         f"'1f1b', or 'interleaved'")
    if offload_activations not in (False, True, "device"):
        raise ValueError(f"offload_activations={offload_activations!r}; "
                         f"expected False, True, or 'device'")
    if offload_activations and schedule != "1f1b":
        raise ValueError(
            "offload_activations requires schedule='1f1b': GPipe keeps "
            "O(M) activations alive inside autodiff (nothing discrete "
            "to spill) and the interleaved stash ring is not yet "
            "host-realized")
    if not cfg.scan_layers:
        raise ValueError("pipeline path requires scan_layers=True")
    if cfg.moe_experts > 0:
        raise NotImplementedError(
            "MoE under pipeline parallelism is not supported yet: the "
            "aux-loss 'losses' collection cannot escape the shard_map "
            "stage body — use make_sharded_train_step on a dp×ep mesh")
    n_stages = mesh.shape.get("pp", 1)
    n_chunks = int(interleave) if schedule == "interleaved" else 1
    if n_chunks < 1:
        raise ValueError(f"interleave must be >= 1, got {interleave}")
    if cfg.n_layers % (n_stages * n_chunks):
        raise ValueError(f"n_layers={cfg.n_layers} not divisible by "
                         f"pp*interleave={n_stages * n_chunks}")
    if global_batch % num_microbatches:
        raise ValueError(f"global_batch={global_batch} not divisible by "
                         f"num_microbatches={num_microbatches}")
    mb = global_batch // num_microbatches
    n_dp = mesh.shape.get("dp", 1)
    if schedule in ("1f1b", "interleaved") and mb % n_dp:
        # these schedules run the microbatch dim through shard_map,
        # which needs exact divisibility (GPipe's GSPMD constraint pads)
        raise ValueError(
            f"schedule={schedule!r} needs the microbatch size "
            f"(global_batch/num_microbatches = {mb}) divisible by "
            f"dp={n_dp}; raise global_batch or lower num_microbatches")
    if schedule == "interleaved" and num_microbatches % n_stages:
        raise ValueError(
            f"schedule='interleaved' needs num_microbatches "
            f"({num_microbatches}) divisible by pp={n_stages} "
            f"(microbatches flow in groups of pp per chunk)")
    per_stage = cfg.n_layers // (n_stages * n_chunks)
    # One pipeline.schedule event per built step: the compiled schedule
    # is a single fused program, so the trace assembler renders its
    # analytic per-stage timeline (pipeline.schedule_spans) from this
    # record next to the measured step spans.
    from distributed_tensorflow_tpu import telemetry as _telemetry
    from distributed_tensorflow_tpu.parallel.pipeline import (
        bubble_fraction as _bubble)
    _telemetry.event("pipeline.schedule", schedule=schedule,
                     n_stages=int(n_stages),
                     n_micro=int(num_microbatches),
                     interleave=int(n_chunks),
                     offload=bool(offload_activations),
                     bubble_fraction=round(_bubble(n_stages,
                                                   num_microbatches,
                                                   schedule,
                                                   interleave=n_chunks),
                                           6))
    # inside the shard_map region blocks run per-shard: no nested
    # sharding machinery, direct attention kernel
    cfg_local = dataclasses.replace(cfg, mesh=None)
    block = Block(cfg_local)

    model = TransformerLM(dataclasses.replace(cfg, mesh=None))
    rng = jax.random.PRNGKey(seed)
    tokens_shape = jnp.zeros((global_batch, cfg.max_seq_len), jnp.int32)
    params = model.init(rng, tokens_shape)["params"]
    params = params.unfreeze() if hasattr(params, "unfreeze") else dict(params)

    # regroup the layer stack: (L, ...) -> (pp, L/pp, ...); interleaved
    # adds a chunk axis — (L, ...) -> (v, pp, L/(v*pp), ...) -> swap to
    # (pp, v, ...) so model stage j*pp + k lands on worker k, chunk j
    # (the NON-adjacent assignment the schedule requires).
    if schedule == "interleaved":
        params["layers"] = jax.tree_util.tree_map(
            lambda p: jnp.swapaxes(
                p.reshape(n_chunks, n_stages, per_stage, *p.shape[1:]),
                0, 1),
            params["layers"])
    else:
        params["layers"] = jax.tree_util.tree_map(
            lambda p: p.reshape(n_stages, per_stage, *p.shape[1:]),
            params["layers"])

    replicated = NamedSharding(mesh, P())
    stage_sharded = NamedSharding(mesh, P("pp"))
    param_shardings = {
        k: (jax.tree_util.tree_map(lambda _: stage_sharded, v)
            if k == "layers"
            else jax.tree_util.tree_map(lambda _: replicated, v))
        for k, v in params.items()}
    params = jax.tree_util.tree_map(jax.device_put, params,
                                    param_shardings)

    tx = make_optimizer(cfg)
    if zero:
        if zero not in (1, 2):
            raise ValueError(f"zero={zero!r}; expected 0, 1, or 2")
        # ZeRO over dp composes with the pipeline: layer grads come out
        # of the schedule already pmean'd over dp, so the sharded update
        # slices — never re-reduces — them. The full replicated slot
        # tree is never materialized.
        from distributed_tensorflow_tpu.parallel.zero import (
            make_zero_update)
        param_specs = jax.tree_util.tree_map(
            lambda ns: ns.spec, param_shardings,
            is_leaf=lambda x: isinstance(x, NamedSharding))
        params_abstract = jax.tree_util.tree_map(
            lambda p: jax.ShapeDtypeStruct(p.shape, p.dtype), params)
        opt_state, opt_shardings, zero_update = make_zero_update(
            tx, mesh, param_specs, params_abstract, axis_name="dp")
    else:
        opt_state = tx.init(params)
        opt_shardings = _shard_like(
            jax.eval_shape(lambda: opt_state),
            jax.tree_util.tree_structure(params), param_shardings,
            replicated)
    state = {"params": params, "opt_state": opt_state,
             "step": jnp.zeros((), jnp.int32)}
    state_shardings = {"params": param_shardings,
                       "opt_state": opt_shardings, "step": replicated}

    def stage_fn(stage_params, x):
        """Apply this stage's layer group: local scan over L/pp blocks."""
        def body(carry, layer_params):
            y, _ = block.apply({"params": layer_params}, carry)
            return y, None

        if cfg.remat:
            body = jax.checkpoint(body, policy=remat_policy_for(cfg))
        out, _ = jax.lax.scan(body, x, stage_params)
        return out

    mb_spec = P(None, "dp" if "dp" in mesh.shape else None)
    norm = RMSNorm(cfg.dtype)

    if schedule in ("1f1b", "interleaved"):
        def head_fn(head_params, y_mb, tokens_mb):
            """Per-microbatch loss head on the last stage's output:
            final norm + tied-embedding logits + shifted CE."""
            x = norm.apply({"params": head_params["final_norm"]}, y_mb)
            embed = head_params["embed"].astype(cfg.dtype)
            logits = jnp.einsum("bsd,vd->bsv", x,
                                embed).astype(jnp.float32)
            return next_token_loss(logits, tokens_mb)

        if offload_activations:
            # host-driven realization: one jitted cycle program called
            # C times with the stash routed through the host store, a
            # jitted finalize, and a jitted optimizer apply. The step is
            # NOT one fused jit — that is the point: the host sits on
            # the spill path between forward and backward.
            from distributed_tensorflow_tpu.parallel.offload import (
                Offloaded1F1B)
            runner = Offloaded1F1B(
                mesh, stage_fn, head_fn, param_spec=P("pp"),
                data_spec=mb_spec,
                spill=offload_activations != "device")

            def embed_lookup(embed, tokens):
                x = embed.astype(cfg.dtype)[tokens]     # (B, S, D)
                x = x.reshape(num_microbatches, mb, *x.shape[1:])
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, mb_spec))

            embed_jit = jax.jit(embed_lookup)

            if zero:
                def apply_fn(params, grads, opt_state):
                    return zero_update(params, grads, opt_state)
            else:
                def apply_fn(params, grads, opt_state):
                    updates, opt_state = tx.update(grads, opt_state,
                                                   params)
                    return (optax.apply_updates(params, updates),
                            opt_state)

            apply_jit = jax.jit(
                apply_fn, out_shardings=(param_shardings, opt_shardings))

            def offload_step(state, batch):
                with mesh:
                    tokens = batch["tokens"]
                    params = state["params"]
                    x_mb, embed_vjp = jax.vjp(
                        lambda e: embed_jit(e, tokens), params["embed"])
                    t_mb = jax.device_put(
                        tokens.reshape(num_microbatches, mb,
                                       tokens.shape[1]),
                        NamedSharding(mesh, mb_spec))
                    head_params = {"final_norm": params["final_norm"],
                                   "embed": params["embed"]}
                    loss, g_layers, g_head, g_x = runner.value_and_grads(
                        params["layers"], head_params, x_mb, t_mb)
                    (g_embed_in,) = embed_vjp(g_x.astype(x_mb.dtype))
                    grads = {"layers": g_layers,
                             "final_norm": g_head["final_norm"],
                             "embed": g_embed_in + g_head["embed"]}
                    new_params, new_opt = apply_jit(
                        params, grads, state["opt_state"])
                    return ({"params": new_params, "opt_state": new_opt,
                             "step": state["step"] + 1},
                            {"loss": loss})

            return state, offload_step

        if schedule == "interleaved":
            pipelined_1f1b = make_interleaved_1f1b_fn(
                mesh, stage_fn, head_fn, n_chunks=n_chunks,
                param_spec=P("pp"), data_spec=mb_spec)
        else:
            pipelined_1f1b = make_1f1b_fn(mesh, stage_fn, head_fn,
                                          param_spec=P("pp"),
                                          data_spec=mb_spec)

        def value_and_grads(params, tokens):
            def embed_lookup(embed):
                x = embed.astype(cfg.dtype)[tokens]     # (B, S, D)
                x = x.reshape(num_microbatches, mb, *x.shape[1:])
                return jax.lax.with_sharding_constraint(
                    x, NamedSharding(mesh, mb_spec))
            x_mb, embed_vjp = jax.vjp(embed_lookup, params["embed"])
            t_mb = jax.lax.with_sharding_constraint(
                tokens.reshape(num_microbatches, mb, tokens.shape[1]),
                NamedSharding(mesh, mb_spec))
            head_params = {"final_norm": params["final_norm"],
                           "embed": params["embed"]}
            loss, g_layers, g_head, g_x = pipelined_1f1b(
                params["layers"], head_params, x_mb, t_mb)
            (g_embed_in,) = embed_vjp(g_x.astype(x_mb.dtype))
            grads = {"layers": g_layers,
                     "final_norm": g_head["final_norm"],
                     # embedding is tied: input-lookup + logits grads
                     "embed": g_embed_in + g_head["embed"]}
            return loss, grads
    else:
        pipelined = make_pipelined_fn(
            mesh, stage_fn, param_spec=P("pp"), data_spec=mb_spec)

        def loss_fn(params, tokens):
            embed = params["embed"].astype(cfg.dtype)
            x = embed[tokens]                           # (B, S, D)
            x = x.reshape(num_microbatches, mb, *x.shape[1:])
            x = jax.lax.with_sharding_constraint(
                x, NamedSharding(mesh, mb_spec))
            out = pipelined(params["layers"], x)
            x = out.reshape(global_batch, *out.shape[2:])
            x = norm.apply({"params": params["final_norm"]}, x)
            logits = jnp.einsum("bsd,vd->bsv", x,
                                embed).astype(jnp.float32)
            return next_token_loss(logits, tokens)

        def value_and_grads(params, tokens):
            return jax.value_and_grad(loss_fn)(params, tokens)

    def train_step(state, batch):
        loss, grads = value_and_grads(state["params"], batch["tokens"])
        if zero:
            new_params, opt_state = zero_update(state["params"], grads,
                                                state["opt_state"])
        else:
            updates, opt_state = tx.update(grads, state["opt_state"],
                                           state["params"])
            new_params = optax.apply_updates(state["params"], updates)
        return ({"params": new_params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss})

    data_axes = "dp" if "dp" in mesh.shape else None
    batch_shardings = {"tokens": NamedSharding(mesh, P(data_axes))}
    with mesh:
        step_jit = jax.jit(train_step,
                           in_shardings=(state_shardings, batch_shardings),
                           out_shardings=(state_shardings, replicated),
                           donate_argnums=safe_donate_argnums((0,)))

    def wrapped(state, batch):
        with mesh:
            return step_jit(state, batch)

    return state, wrapped


def synthetic_tokens(global_batch: int, seq_len: int, vocab_size: int,
                     seed: int = 0):
    rng = jax.random.PRNGKey(seed)
    return jax.random.randint(rng, (global_batch, seq_len), 0, vocab_size,
                              dtype=jnp.int32)
