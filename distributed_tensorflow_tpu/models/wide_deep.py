"""Wide&Deep / DLRM — benchmark workload #4
(BASELINE.md: ParameterServerStrategy async-PS reference).

The reference shards its embedding tables across parameter servers with
axis-0 partitioners and looks them up remotely per step (reference:
tensorflow/python/distribute/sharded_variable.py:843 ``ShardedVariable``,
:995 ``embedding_lookup``; parameter_server_strategy_v2.py:689 variable
round-robin). The TPU-native redesign keeps tables *on device*, sharded
over the mesh's model axis ("tp"), and lets GSPMD turn gather + combine
into the same partitioned-lookup pattern SparseCore embedding uses
(reference tpu_embedding_v3.py:498) — no RPC per lookup.

Two training modes:
- **SPMD sync** (`make_sharded_train_step`): embeddings row-sharded over
  tp, dense layers replicated, batch over dp. One jit program.
- **Async PS** (`examples`/coordinator): the ClusterCoordinator schedules
  steps on workers with host-memory tables via ShardedVariable
  (parallel/sharded_variable.py) — API-parity path with the reference.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.utils.jax_compat import (
    safe_donate_argnums)
import numpy as np
import optax
from flax import linen as nn
from flax.linen import partitioning as nn_partitioning
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

param_with_axes = nn_partitioning.param_with_axes


@dataclasses.dataclass(frozen=True)
class WideDeepConfig:
    vocab_sizes: tuple = (1000, 1000, 500, 100)   # one per categorical col
    embed_dim: int = 32
    num_dense_features: int = 13
    mlp_dims: tuple = (256, 128, 64)
    dtype: Any = jnp.float32
    learning_rate: float = 1e-3
    # "dot" = DLRM pairwise feature interaction; "concat" = Wide&Deep
    interaction: str = "concat"

    @classmethod
    def tiny(cls, **kw):
        defaults = dict(vocab_sizes=(64, 64, 32), embed_dim=8,
                        num_dense_features=4, mlp_dims=(32, 16))
        defaults.update(kw)
        return cls(**defaults)

    @classmethod
    def dlrm_like(cls, **kw):
        defaults = dict(vocab_sizes=(int(1e5),) * 26, embed_dim=64,
                        num_dense_features=13, mlp_dims=(512, 256, 128),
                        interaction="dot")
        defaults.update(kw)
        return cls(**defaults)


# Logical axes: embedding rows shard over the model axis, the TPU-native
# form of the reference's axis-0 PS sharding (sharded_variable.py:47
# Partitioner family).
WIDE_DEEP_RULES = (
    ("table_rows", "tp"),
    ("table_cols", None),
    ("hidden", None),
    ("features", None),
)


def _interact(cfg: WideDeepConfig, embs: Sequence, dense):
    """Feature interaction shared by both towers: DLRM pairwise dots
    ("dot") or plain concatenation ("concat")."""
    if cfg.interaction == "dot":
        stacked = jnp.stack(list(embs), axis=1)        # (B, T, E)
        inter = jnp.einsum("bte,bse->bts", stacked, stacked)
        iu = jnp.triu_indices(len(embs), k=1)
        feats = [inter[:, iu[0], iu[1]], dense]
    else:
        feats = list(embs) + [dense]
    return jnp.concatenate(feats, axis=-1).astype(cfg.dtype)


class WideDeep(nn.Module):
    cfg: WideDeepConfig

    @nn.compact
    def __call__(self, dense, categorical):
        """dense: (B, num_dense); categorical: (B, n_tables) int ids."""
        cfg = self.cfg
        embs = []
        wide_logits = []
        for i, vocab in enumerate(cfg.vocab_sizes):
            table = param_with_axes(
                f"table_{i}", nn.initializers.normal(0.01),
                (vocab, cfg.embed_dim), jnp.float32,
                axes=("table_rows", "table_cols"))
            # Row gather — GSPMD partitions this lookup across the tp
            # shards of the table (SparseCore-style), ≙ reference
            # sharded_variable.embedding_lookup (:995).
            embs.append(table[categorical[:, i]])
            wide = param_with_axes(
                f"wide_{i}", nn.initializers.zeros, (vocab,), jnp.float32,
                axes=("table_rows",))
            wide_logits.append(wide[categorical[:, i]])

        # DLRM pairwise dots or Wide&Deep concat — shared helper
        x = _interact(cfg, embs, dense)

        for j, width in enumerate(cfg.mlp_dims):
            w = param_with_axes(
                f"mlp_{j}", nn.initializers.lecun_normal(),
                (x.shape[-1], width), jnp.float32,
                axes=("features", "hidden"))
            b = param_with_axes(f"bias_{j}", nn.initializers.zeros,
                                (width,), jnp.float32, axes=("hidden",))
            x = nn.relu(jnp.dot(x, w.astype(cfg.dtype)) + b)

        w_out = param_with_axes("out", nn.initializers.lecun_normal(),
                                (x.shape[-1], 1), jnp.float32,
                                axes=("features", None))
        deep_logit = jnp.dot(x, w_out.astype(cfg.dtype))[:, 0]
        return deep_logit.astype(jnp.float32) + sum(wide_logits)


def make_optimizer(cfg: WideDeepConfig):
    return optax.adagrad(cfg.learning_rate)   # the classic W&D/DLRM choice


def make_train_step(cfg: WideDeepConfig, model: WideDeep, tx):
    def loss_fn(params, batch):
        logits = model.apply({"params": params}, batch["dense"],
                             batch["categorical"])
        return optax.sigmoid_binary_cross_entropy(
            logits, batch["label"].astype(jnp.float32)).mean()

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(state["params"], batch)
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        return ({"params": params, "opt_state": opt_state,
                 "step": state["step"] + 1},
                {"loss": loss})

    return train_step


def make_sharded_train_step(cfg: WideDeepConfig, mesh: Mesh,
                            global_batch: int, seed: int = 0):
    """SPMD: tables row-sharded over tp, batch over dp, one jit program."""
    model = WideDeep(cfg)
    tx = make_optimizer(cfg)
    rng = jax.random.PRNGKey(seed)
    n_tables = len(cfg.vocab_sizes)
    dense_shape = jnp.zeros((global_batch, cfg.num_dense_features))
    cat_shape = jnp.zeros((global_batch, n_tables), jnp.int32)

    from distributed_tensorflow_tpu.models.transformer import \
        mesh_axis_rules
    rules = mesh_axis_rules(mesh, WIDE_DEEP_RULES)

    with nn_partitioning.axis_rules(rules):
        var_shapes = jax.eval_shape(
            lambda r: model.init(r, dense_shape, cat_shape), rng)
        logical = nn_partitioning.get_axis_names(var_shapes["params_axes"])
        mesh_specs = nn_partitioning.logical_to_mesh(logical)
    param_shardings = jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), mesh_specs,
        is_leaf=lambda x: isinstance(x, P))
    if hasattr(param_shardings, "unfreeze"):
        param_shardings = param_shardings.unfreeze()

    replicated = NamedSharding(mesh, P())
    # adagrad state mirrors params
    from distributed_tensorflow_tpu.models.transformer import _shard_like
    params_treedef = jax.tree_util.tree_structure(var_shapes["params"])
    opt_shapes = jax.eval_shape(tx.init, var_shapes["params"])
    opt_shardings = _shard_like(opt_shapes, params_treedef,
                                param_shardings, replicated)
    state_shardings = {"params": param_shardings,
                       "opt_state": opt_shardings, "step": replicated}

    def init_fn(rng):
        params = model.init(rng, dense_shape, cat_shape)["params"]
        return {"params": params, "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    from distributed_tensorflow_tpu.cluster.topology import \
        data_axes as mesh_data_axes
    data_axes = mesh_data_axes(mesh) or None
    batch_shardings = {
        "dense": NamedSharding(mesh, P(data_axes)),
        "categorical": NamedSharding(mesh, P(data_axes)),
        "label": NamedSharding(mesh, P(data_axes)),
    }

    step = make_train_step(cfg, model, tx)
    with mesh, nn_partitioning.axis_rules(rules):
        state = jax.jit(init_fn, out_shardings=state_shardings)(rng)
        step_jit = jax.jit(step,
                           in_shardings=(state_shardings, batch_shardings),
                           out_shardings=(state_shardings, replicated),
                           donate_argnums=safe_donate_argnums((0,)))

    def wrapped(state, batch):
        with mesh, nn_partitioning.axis_rules(rules):
            return step_jit(state, batch)

    return state, wrapped


def build_feature_config(cfg: WideDeepConfig):
    """The Wide&Deep feature/table layout for the embedding API: a deep
    table (embed_dim) and a dim-1 wide table (combiner=sum) per
    categorical column, each with per-table Adagrad (≙ the feature_config
    trees passed to reference tpu_embedding_v2.py:76)."""
    from distributed_tensorflow_tpu import embedding as emb_lib
    deep_tables = [emb_lib.TableConfig(v, cfg.embed_dim, name=f"table_{i}",
                                       optimizer=emb_lib.Adagrad(
                                           cfg.learning_rate))
                   for i, v in enumerate(cfg.vocab_sizes)]
    wide_tables = [emb_lib.TableConfig(v, 1, name=f"wide_{i}",
                                       combiner="sum",
                                       optimizer=emb_lib.Adagrad(
                                           cfg.learning_rate))
                   for i, v in enumerate(cfg.vocab_sizes)]
    return {
        "deep": tuple(emb_lib.FeatureConfig(t, name=f"deep_{i}")
                      for i, t in enumerate(deep_tables)),
        "wide": tuple(emb_lib.FeatureConfig(t, name=f"wide_{i}")
                      for i, t in enumerate(wide_tables)),
    }


def _embedding_loss_fn(cfg: WideDeepConfig, feature_config, model):
    """Shared W&D-through-embedding-API objective: deep acts into the
    dense tower, wide acts summed into the logit, sigmoid CE."""
    from distributed_tensorflow_tpu import embedding as emb_lib
    n_tables = len(cfg.vocab_sizes)

    def loss_fn(dense_params, tables, batch):
        feats = {
            "deep": tuple(batch["categorical"][:, i]
                          for i in range(n_tables)),
            "wide": tuple(batch["categorical"][:, i]
                          for i in range(n_tables)),
        }
        acts = emb_lib.lookup(tables, feature_config, feats)
        logits = model.apply({"params": dense_params},
                             list(acts["deep"]), batch["dense"])
        logits = logits + sum(w[:, 0] for w in acts["wide"])
        return optax.sigmoid_binary_cross_entropy(
            logits, batch["label"].astype(jnp.float32)).mean()

    return loss_fn


class WideDeepDense(nn.Module):
    """The dense tower only: consumes PRE-LOOKED-UP embedding activations
    (the TPUEmbedding API path — ≙ how reference DLRM models consume
    dequeued activations from tpu_embedding_v2.py while the tables train
    decoupled)."""
    cfg: WideDeepConfig

    @nn.compact
    def __call__(self, emb_acts: Sequence, dense):
        cfg = self.cfg
        x = _interact(cfg, emb_acts, dense)
        for j, width in enumerate(cfg.mlp_dims):
            x = nn.relu(nn.Dense(width, name=f"mlp_{j}")(x))
        return nn.Dense(1, name="out")(x)[:, 0].astype(jnp.float32)


def make_embedding_train_step(cfg: WideDeepConfig, mesh: Mesh,
                              global_batch: int, seed: int = 0):
    """DLRM/W&D through the TPU embedding API (embedding/embedding.py):

    - one TableConfig per categorical column (+ a dim-1 "wide" table per
      column, combiner=sum — the wide half of Wide&Deep);
    - tables row-sharded over "tp" via the embedding layer's own state
      (≙ tpu_embedding_v3.py:498 SparseCore sharding), NOT flax params;
    - table gradients applied by the per-table Adagrad — decoupled from
      the dense tower's optax optimizer (≙ tpu_embedding_v2.py:754
      apply_gradients).

    Returns (state, step_fn) with state = {"dense": ..., "emb": ...}.
    """
    from distributed_tensorflow_tpu import embedding as emb_lib

    feature_config = build_feature_config(cfg)

    rng = jax.random.PRNGKey(seed)
    rng, emb_rng, dense_rng = jax.random.split(rng, 3)
    emb_state = emb_lib.create_state(feature_config, mesh=mesh,
                                     shard_axis="tp", rng=emb_rng)

    model = WideDeepDense(cfg)
    n_tables = len(cfg.vocab_sizes)
    sample_acts = [jnp.zeros((global_batch, cfg.embed_dim))
                   for _ in range(n_tables)]
    sample_dense = jnp.zeros((global_batch, cfg.num_dense_features))
    dense_params = model.init(dense_rng, sample_acts, sample_dense)["params"]
    tx = make_optimizer(cfg)

    from distributed_tensorflow_tpu.cluster.topology import \
        data_axes as mesh_data_axes
    data_axes = mesh_data_axes(mesh) or None
    replicated = NamedSharding(mesh, P())
    batch_sh = NamedSharding(mesh, P(data_axes))
    table_sh = (NamedSharding(mesh, P("tp", None))
                if "tp" in mesh.shape else replicated)
    emb_shardings = jax.tree_util.tree_map(
        lambda x: table_sh if getattr(x, "ndim", 0) == 2 else replicated,
        emb_state)
    dense_state = {"params": dense_params, "opt_state": tx.init(dense_params)}
    dense_shardings = jax.tree_util.tree_map(lambda _: replicated,
                                             dense_state)
    state = {"dense": jax.device_put(dense_state, replicated),
             "emb": jax.tree_util.tree_map(jax.device_put, emb_state,
                                           emb_shardings)}
    state_shardings = {"dense": dense_shardings, "emb": emb_shardings}
    batch_shardings = {"dense": batch_sh, "categorical": batch_sh,
                       "label": batch_sh}

    loss_fn = _embedding_loss_fn(cfg, feature_config, model)

    def train_step(state, batch):
        loss, (dgrads, tgrads) = jax.value_and_grad(
            loss_fn, argnums=(0, 1))(state["dense"]["params"],
                                     state["emb"]["tables"], batch)
        updates, opt_state = tx.update(dgrads, state["dense"]["opt_state"],
                                       state["dense"]["params"])
        dense_params = optax.apply_updates(state["dense"]["params"], updates)
        emb = emb_lib.apply_gradients(state["emb"], tgrads, feature_config)
        return ({"dense": {"params": dense_params, "opt_state": opt_state},
                 "emb": emb}, {"loss": loss})

    with mesh:
        step_jit = jax.jit(train_step,
                           in_shardings=(state_shardings, batch_shardings),
                           out_shardings=(state_shardings, replicated),
                           donate_argnums=safe_donate_argnums((0,)))

    def wrapped(state, batch):
        with mesh:
            return step_jit(state, batch)

    return state, wrapped


def synthetic_clicks(cfg: WideDeepConfig, n: int, seed: int = 0):
    """Click-through data where the label depends on feature crosses."""
    rng = np.random.default_rng(seed)
    dense = rng.normal(size=(n, cfg.num_dense_features)).astype("float32")
    cat = np.stack([rng.integers(0, v, size=n) for v in cfg.vocab_sizes],
                   axis=1).astype("int32")
    score = dense.mean(1) + 0.3 * np.cos(cat.sum(1))
    label = (score > np.median(score)).astype("int32")
    return {"dense": jnp.asarray(dense), "categorical": jnp.asarray(cat),
            "label": jnp.asarray(label)}


# ---------------------------------------------------------------------------
# Async parameter-server composition (BASELINE.md config #4):
# embedding API tables + dense tower, trained asynchronously through the
# ClusterCoordinator's remote dispatch. ≙ parameter_server_strategy_v2.py:77
# (coordinator-owned variables, worker-computed steps) composed with
# tpu_embedding_v2.py:76 (feature_config-driven tables) — the two APIs the
# reference's config #4 uses together.
#
# Topology: the coordinator process owns the "server copy" of all state
# (tables + slots + dense params + optax state); workers hold per-worker
# datasets and compute gradients for whatever parameter snapshot each
# scheduled closure carries; the coordinator applies gradients AS RESULTS
# ARRIVE — the async-PS staleness semantics (a gradient may be computed
# against parameters a few updates old, exactly like the reference's
# unsynchronized PS reads/writes).
# ---------------------------------------------------------------------------

import functools as _functools


@_functools.lru_cache(maxsize=4)
def _ps_feature_config(cfg: WideDeepConfig):
    return build_feature_config(cfg)


@_functools.lru_cache(maxsize=4)
def _ps_optimizer(cfg: WideDeepConfig):
    return make_optimizer(cfg)


def ps_init_state(cfg: WideDeepConfig, seed: int = 0) -> dict:
    """Coordinator-side server copy of the full DLRM state (host arrays —
    small enough to ship inside scheduled closures; bulk activations
    never leave the workers)."""
    from distributed_tensorflow_tpu import embedding as emb_lib
    rng = jax.random.PRNGKey(seed)
    rng, emb_rng, dense_rng = jax.random.split(rng, 3)
    feature_config = build_feature_config(cfg)
    emb_state = emb_lib.create_state(feature_config, rng=emb_rng)
    model = WideDeepDense(cfg)
    n_tables = len(cfg.vocab_sizes)
    sample_acts = [jnp.zeros((2, cfg.embed_dim)) for _ in range(n_tables)]
    sample_dense = jnp.zeros((2, cfg.num_dense_features))
    dense_params = model.init(dense_rng, sample_acts,
                              sample_dense)["params"]
    tx = make_optimizer(cfg)
    return {"dense": {"params": dense_params,
                      "opt_state": tx.init(dense_params)},
            "emb": emb_state}


@_functools.lru_cache(maxsize=4)
def _ps_grad_program(cfg: WideDeepConfig):
    """Worker-side compiled grad program, built once per process (the
    worker's analogue of the reference's per-worker function library)."""
    feature_config = build_feature_config(cfg)
    model = WideDeepDense(cfg)
    loss_fn = _embedding_loss_fn(cfg, feature_config, model)
    return jax.jit(jax.value_and_grad(
        lambda dp, tabs, batch: loss_fn(dp, tabs, batch),
        argnums=(0, 1)))


def ps_worker_grads(cfg: WideDeepConfig, dense_params, tables, it):
    """Runs ON a worker (scheduled closure): pull the next batch from
    THIS worker's dataset iterator (a per-worker resource handle) and
    return (loss, dense grads, table grads) as host arrays."""
    batch = next(it)
    loss, (dgrads, tgrads) = _ps_grad_program(cfg)(dense_params, tables,
                                                   batch)
    host = lambda t: jax.tree_util.tree_map(np.asarray, t)  # noqa: E731
    return host(loss), host(dgrads), host(tgrads)


def ps_apply_grads(cfg: WideDeepConfig, state: dict, dgrads,
                   tgrads) -> dict:
    """Coordinator-side asynchronous apply: update the CURRENT server
    copy with a (possibly stale) worker gradient."""
    from distributed_tensorflow_tpu import embedding as emb_lib
    tx = _ps_optimizer(cfg)
    updates, opt_state = tx.update(dgrads, state["dense"]["opt_state"],
                                   state["dense"]["params"])
    dense_params = optax.apply_updates(state["dense"]["params"], updates)
    emb = emb_lib.apply_gradients(state["emb"], tgrads,
                                  _ps_feature_config(cfg))
    return {"dense": {"params": dense_params, "opt_state": opt_state},
            "emb": emb}


def train_dlrm_async_ps(cfg: WideDeepConfig, coord, *, steps: int,
                        batch_size: int = 32, max_in_flight: int = 4,
                        dataset_seed: int = 0, log_every: int = 0,
                        on_step=None):
    """Drive config #4 end-to-end: per-worker datasets live on the
    workers, grad closures are scheduled across them with transparent
    preemption retry, and the coordinator folds results into the server
    copy as they land. Returns (final_state, losses).

    ``coord`` is a ClusterCoordinator (local lanes or remote worker
    processes — the same loop runs over both transports).
    """
    state = ps_init_state(cfg)
    dataset_fn = _functools.partial(_ps_dataset, cfg, batch_size,
                                    dataset_seed)
    per_worker_it = coord.create_per_worker_dataset(dataset_fn)
    losses: list = []
    in_flight: list = []
    scheduled = 0
    while scheduled < steps or in_flight:
        while scheduled < steps and len(in_flight) < max_in_flight:
            rv = coord.schedule(
                ps_worker_grads,
                args=(cfg, state["dense"]["params"],
                      state["emb"]["tables"], per_worker_it))
            in_flight.append(rv)
            scheduled += 1
        rv = in_flight.pop(0)
        loss, dgrads, tgrads = rv.fetch()
        state = ps_apply_grads(cfg, state, dgrads, tgrads)
        losses.append(float(loss))
        if on_step is not None:
            on_step(len(losses))
        if log_every and len(losses) % log_every == 0:
            recent = losses[-log_every:]
            print(f"step {len(losses):4d}  loss "
                  f"{sum(recent) / len(recent):.4f}", flush=True)
    return state, losses


_LOCAL_DS_COUNTER = iter(range(1 << 30))


def _ps_dataset(cfg: WideDeepConfig, batch_size: int, seed: int):
    """Per-worker dataset factory (runs on the worker): an endless
    shuffled stream over the synthetic click data. Each worker's stream
    is decorrelated by its worker id (remote lanes) or a process-local
    counter (thread lanes) — N workers must not feed N clones of the
    same batch sequence (≙ the reference's per-worker dataset_fn
    receiving a distinct InputContext.input_pipeline_id)."""
    from distributed_tensorflow_tpu.coordinator.remote_dispatch import (
        current_worker_service)
    svc = current_worker_service()
    wid = svc.worker_id if svc is not None else next(_LOCAL_DS_COUNTER)
    seed = seed * 1009 + wid
    data = synthetic_clicks(cfg, 1024, seed=seed)
    data = {k: np.asarray(v) for k, v in data.items()}
    n = data["label"].shape[0]

    def gen():
        rng = np.random.default_rng(seed)
        while True:
            idx = rng.integers(0, n, size=batch_size)
            yield {k: jnp.asarray(v[idx]) for k, v in data.items()}

    return gen()
