"""Model zoo matching the reference's benchmark workloads (BASELINE.md):

1. MNIST CNN          — ``mnist_cnn``
2. ResNet-50          — ``resnet``
3. BERT               — ``bert``
4. Wide&Deep / DLRM   — ``wide_deep``
5. Transformer (WMT)  — ``transformer``
"""

import importlib

__all__ = ["mnist_cnn", "resnet", "bert", "wide_deep", "transformer"]


def __getattr__(name):
    if name in __all__:
        return importlib.import_module(
            f"distributed_tensorflow_tpu.models.{name}")
    raise AttributeError(name)
