"""MNIST CNN — benchmark workload #1 (BASELINE.md: MirroredStrategy ref).

A small conv net in flax.linen with a functional train step designed for
``Strategy.compile_step`` (native path) and a TF-parity ``train_step`` for
``Strategy.run``.
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn


class MNISTCNN(nn.Module):
    """conv3x3(32) -> conv3x3(64) -> maxpool -> dense(128) -> dense(10)."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(32, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(64, (3, 3), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(128, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x


def create_train_state(rng, learning_rate: float = 1e-3,
                       image_shape=(1, 28, 28, 1)):
    model = MNISTCNN()
    params = model.init(rng, jnp.zeros(image_shape))["params"]
    tx = optax.adam(learning_rate)
    return {"params": params, "opt_state": tx.init(params), "step": 0}, model, tx


def make_train_step(model: MNISTCNN, tx):
    """Functional SPMD train step: (state, batch) -> (state, metrics).

    Gradient sync is implicit: params are replicated, batch is sharded over
    the data axes, so XLA inserts the allreduce — the TPU-native form of
    NcclAllReduce.batch_reduce (cross_device_ops.py:871 in the reference).
    """

    def loss_fn(params, images, labels):
        logits = model.apply({"params": params}, images)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        return loss, logits

    def train_step(state, batch):
        (loss, logits), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], batch["image"], batch["label"])
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1}
        return new_state, {"loss": loss, "accuracy": acc}

    return train_step


def synthetic_data(n: int = 512, seed: int = 0):
    """Deterministic synthetic MNIST-shaped data (zero-egress environment)."""
    import numpy as np
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n, 28, 28, 1)).astype("float32")
    # labels carry signal: derived from per-image statistics so the model
    # can actually fit them
    labels = (np.abs(images.mean(axis=(1, 2, 3))) * 40).astype("int32") % 10
    return {"image": images, "label": labels}
