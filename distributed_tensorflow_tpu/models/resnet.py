"""ResNet-50 — benchmark workload #2 (BASELINE.md: MWMS/NCCL reference).

TPU-native redesign: where the reference trains ResNet-50 with
`MultiWorkerMirroredStrategy` + NCCL allreduce (reference:
tensorflow/python/distribute/collective_all_reduce_strategy.py:57), here
the train step is one jit-compiled SPMD program over the mesh — batch
sharded over dp, gradient psum inserted by GSPMD over ICI.

TPU-first details:
- bfloat16 conv compute, float32 batch-norm statistics and parameters
  (bf16 variance is numerically unsafe).
- NHWC layout (TPU conv-friendly); convolutions hit the MXU via XLA's
  implicit im2col.
- BatchNorm under SPMD jit computes *global-batch* statistics by
  construction (the mean over a dp-sharded axis is the global mean;
  GSPMD inserts the reduce) — stronger than the reference, whose BN
  under MirroredStrategy normalizes per replica. ``sync_batch_norm``
  additionally psums stats when running inside shard_map (the
  TF-parity Strategy.run path, where batches really are per-replica).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Sequence

import jax
import jax.numpy as jnp

from distributed_tensorflow_tpu.utils.jax_compat import (
    safe_donate_argnums)
import optax
from flax import linen as nn
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: tuple = (3, 4, 6, 3)       # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    sync_batch_norm: bool = False
    axis_names: tuple = ("dp",)             # BN sync axes (if enabled)
    learning_rate: float = 0.1
    momentum: float = 0.9
    weight_decay: float = 1e-4
    label_smoothing: float = 0.1

    @classmethod
    def resnet50(cls, **kw):
        return cls(**kw)

    @classmethod
    def tiny(cls, **kw):
        """CI-sized: resnet-8-ish on 32x32 inputs."""
        defaults = dict(stage_sizes=(1, 1), num_classes=10, width=8,
                        dtype=jnp.float32)
        defaults.update(kw)
        return cls(**defaults)


class BatchNorm(nn.Module):
    """BN with float32 statistics and optional shard_map-mode sync.

    Under SPMD jit, batch statistics are global across the sharded batch
    (≙ SyncBatchNormalization — beyond the reference's per-replica keras
    BN). ``sync_axes`` adds an explicit psum for shard_map contexts.
    """
    use_running_average: bool
    sync_axes: tuple = ()
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean",
                                lambda: jnp.zeros(features, jnp.float32))
        ra_var = self.variable("batch_stats", "var",
                               lambda: jnp.ones(features, jnp.float32))
        scale = self.param("scale", nn.initializers.ones, (features,),
                           jnp.float32)
        bias = self.param("bias", nn.initializers.zeros, (features,),
                          jnp.float32)

        x32 = x.astype(jnp.float32)
        if self.use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            reduce_axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x32, axis=reduce_axes)
            mean2 = jnp.mean(jnp.square(x32), axis=reduce_axes)
            if self.sync_axes:
                # Only meaningful inside shard_map (the TF-parity
                # Strategy.run path). Under SPMD jit the mean over a
                # dp-sharded batch axis is already the GLOBAL mean —
                # GSPMD inserts the cross-replica reduce itself.
                try:
                    mean = jax.lax.pmean(mean, self.sync_axes)
                    mean2 = jax.lax.pmean(mean2, self.sync_axes)
                except NameError:   # axis not bound: jit/GSPMD context
                    pass
            var = mean2 - jnp.square(mean)
            if not self.is_initializing():
                ra_mean.value = (self.momentum * ra_mean.value
                                 + (1 - self.momentum) * mean)
                ra_var.value = (self.momentum * ra_var.value
                                + (1 - self.momentum) * var)
        y = (x32 - mean) * jax.lax.rsqrt(var + self.epsilon)
        return (y * scale + bias).astype(self.dtype)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    cfg: ResNetConfig
    train: bool

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        norm = functools.partial(
            BatchNorm, use_running_average=not self.train,
            sync_axes=cfg.axis_names if cfg.sync_batch_norm else (),
            dtype=cfg.dtype)
        conv = functools.partial(nn.Conv, use_bias=False, dtype=cfg.dtype)

        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(self.filters, (3, 3), strides=(self.strides,) * 2)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = conv(4 * self.filters, (1, 1))(y)
        y = norm()(y)

        if residual.shape != y.shape:
            residual = conv(4 * self.filters, (1, 1),
                            strides=(self.strides,) * 2,
                            name="proj")(residual)
            residual = norm(name="proj_bn")(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    cfg: ResNetConfig
    train: bool = True

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        norm = functools.partial(
            BatchNorm, use_running_average=not self.train,
            sync_axes=cfg.axis_names if cfg.sync_batch_norm else (),
            dtype=cfg.dtype)
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.width, (7, 7), strides=(2, 2), use_bias=False,
                    dtype=cfg.dtype, name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(cfg.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(cfg.width * 2 ** i, strides, cfg,
                                    self.train,
                                    name=f"stage{i}_block{j}")(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(cfg.num_classes, dtype=jnp.float32,
                     name="classifier")(x.astype(jnp.float32))
        return x


def make_train_step(cfg: ResNetConfig, model: ResNet, tx):
    """(state, batch) -> (state, metrics). state: params/batch_stats/
    opt_state/step; batch: {"image": NHWC, "label": int}."""

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {"params": params, "batch_stats": batch_stats}, images,
            mutable=["batch_stats"])
        one_hot = optax.smooth_labels(
            jax.nn.one_hot(labels, cfg.num_classes), cfg.label_smoothing)
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        return loss, (logits, mutated["batch_stats"])

    def train_step(state, batch):
        (loss, (logits, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state["params"], state["batch_stats"],
                                   batch["image"], batch["label"])
        updates, opt_state = tx.update(grads, state["opt_state"],
                                       state["params"])
        params = optax.apply_updates(state["params"], updates)
        acc = jnp.mean(jnp.argmax(logits, -1) == batch["label"])
        return ({"params": params, "batch_stats": new_stats,
                 "opt_state": opt_state, "step": state["step"] + 1},
                {"loss": loss, "accuracy": acc})

    return train_step


def make_optimizer(cfg: ResNetConfig, total_steps: int = 10000):
    schedule = optax.cosine_decay_schedule(cfg.learning_rate, total_steps)
    # Standard ResNet recipe: no L2 on BN scale/bias or biases (any 1-D
    # parameter) — decaying BN scales toward 0 degrades final accuracy.
    decay_mask = lambda params: jax.tree_util.tree_map(
        lambda p: p.ndim > 1, params)
    return optax.chain(
        optax.add_decayed_weights(cfg.weight_decay, mask=decay_mask),
        optax.sgd(schedule, momentum=cfg.momentum, nesterov=True))


def make_sharded_train_step(cfg: ResNetConfig, mesh: Mesh,
                            global_batch: int, image_size: int = 224,
                            seed: int = 0):
    """Data-parallel SPMD training over the mesh's data axes: params and
    BN stats replicated, batch sharded, gradient allreduce by GSPMD (the
    TPU-native MultiWorkerMirroredStrategy — SURVEY.md §2.8 row 2)."""
    model = ResNet(cfg, train=True)
    tx = make_optimizer(cfg)
    rng = jax.random.PRNGKey(seed)
    image_shape = (global_batch, image_size, image_size, 3)

    def init_fn(rng):
        variables = model.init(rng, jnp.zeros(image_shape, jnp.float32))
        params = variables["params"]
        return {"params": params,
                "batch_stats": variables.get("batch_stats", {}),
                "opt_state": tx.init(params),
                "step": jnp.zeros((), jnp.int32)}

    from distributed_tensorflow_tpu.cluster.topology import \
        data_axes as mesh_data_axes
    replicated = NamedSharding(mesh, P())
    data_axes = mesh_data_axes(mesh) or None
    batch_shardings = {
        "image": NamedSharding(mesh, P(data_axes)),
        "label": NamedSharding(mesh, P(data_axes)),
    }
    state_shardings = jax.tree_util.tree_map(lambda _: replicated,
                                             jax.eval_shape(init_fn, rng))

    with mesh:
        state = jax.jit(init_fn, out_shardings=state_shardings)(rng)
        step_jit = jax.jit(
            make_train_step(cfg, model, tx),
            in_shardings=(state_shardings, batch_shardings),
            out_shardings=(state_shardings, replicated),
            donate_argnums=safe_donate_argnums((0,)))

    def wrapped(state, batch):
        with mesh:
            return step_jit(state, batch)

    return state, wrapped


def synthetic_images(n: int, image_size: int = 224, num_classes: int = 1000,
                     seed: int = 0):
    """Deterministic synthetic imagenet-shaped data with learnable signal."""
    import numpy as np
    rng = np.random.default_rng(seed)
    images = rng.normal(size=(n, image_size, image_size, 3)).astype("float32")
    labels = (np.abs(images.mean(axis=(1, 2, 3))) * 40).astype(
        "int32") % num_classes
    return {"image": images, "label": labels}
