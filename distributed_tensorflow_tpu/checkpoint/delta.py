"""Delta snapshots: row-sparse publish chain for dynamic tables.

An online recommender's :class:`~distributed_tensorflow_tpu.embedding.
dynamic.DynamicTable` at 10⁶ rows moves well under 1% of them per
snapshot interval (Zipf traffic: the hot head trains constantly, the
tail sleeps) — yet a full snapshot re-serializes every row every time.
This module publishes the table as a **chain**:

- a **full** record — the complete ``state_dict`` (base), then
- **delta** records — only the rows/sketch-cells touched since the
  previous publish (``DynamicTable.state_delta``), each carrying its
  parent's ``(seq, crc)`` so the chain is verifiable link by link,

with a fresh full every ``full_every`` publishes (bounds reconstruct
cost) and FORCED on table growth (capacity changed ⇒ every row moved ⇒
only a full is honest; ``state_delta`` returns None and the publisher
falls back).

Every record is one file, committed write-once: header JSON line
(kind, seq, step, parent link, payload size, payload crc32) + pickled
payload, fsynced then ``os.replace``d into place — a torn write is
never visible under the final name, and a post-rename tear (the
``delta.publish`` chaos site's ``corrupt`` action, mirroring
``checkpoint.commit``) is caught by the crc at read time.

:meth:`DeltaSnapshotStore.reconstruct` walks the newest intact full
forward through its crc-linked deltas and returns a table
**bit-identical** to one restored from a full snapshot taken at the
same instant (tests/test_rollout.py proves it at 10⁶-row scale). A
broken link — missing seq, crc mismatch, parent mismatch — stops the
walk: the longest intact prefix serves, honestly stale rather than
silently wrong; a corrupt newest full falls back to the prior full.
"""

from __future__ import annotations

import json
import os
import pickle
import re
import zlib

import numpy as np

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.resilience import faults

_HEADER_MAX = 4096


class DeltaChainError(RuntimeError):
    """No intact full record exists — nothing is reconstructable."""


def _fsync_dir(path: str):
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _aux_equal(a, b) -> bool:
    """Deep equality over the pickled aux structure: arrays compare by
    dtype+contents, dicts by key set, scalars by ==. (The pickle BYTES
    are not comparable — dict insertion order differs between a stepped
    table and a reconstructed one.)"""
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a, b = np.asarray(a), np.asarray(b)
        return (a.dtype == b.dtype and a.shape == b.shape
                and bool(np.array_equal(a, b)))
    if isinstance(a, dict) and isinstance(b, dict):
        return (set(a) == set(b)
                and all(_aux_equal(a[k], b[k]) for k in a))
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return (len(a) == len(b)
                and all(_aux_equal(x, y) for x, y in zip(a, b)))
    return a == b


def states_equal(sd_a: dict, sd_b: dict) -> bool:
    """Bit-identity between two ``DynamicTable.state_dict`` results:
    rows byte-equal AND every aux leaf (slots, membership, sketch,
    free list, counters) exactly equal."""
    ra, rb = np.asarray(sd_a["rows"]), np.asarray(sd_b["rows"])
    if ra.dtype != rb.dtype or ra.shape != rb.shape \
            or not np.array_equal(ra, rb):
        return False
    aux_a = pickle.loads(np.asarray(sd_a["aux"],
                                    dtype=np.uint8).tobytes())
    aux_b = pickle.loads(np.asarray(sd_b["aux"],
                                    dtype=np.uint8).tobytes())
    return _aux_equal(aux_a, aux_b)


class DeltaSnapshotStore:
    """Publish/reconstruct a :class:`DynamicTable` as a full+delta
    record chain under one directory (see module docstring)."""

    def __init__(self, directory: str, name: str = "table",
                 full_every: int = 8):
        if full_every < 1:
            raise ValueError(f"full_every must be >= 1, got "
                             f"{full_every}")
        self.directory = directory
        self.name = name
        self.full_every = int(full_every)
        os.makedirs(directory, exist_ok=True)
        self.published_full = 0
        self.published_delta = 0
        # resume the chain a prior incarnation left behind: parent
        # linkage + full cadence come from the newest intact record
        self._last: "tuple[int, int] | None" = None   # (seq, crc)
        self._since_full = 0
        for seq, kind, path in self._scan():
            hdr, payload = self._read_record(path)
            if hdr is None:
                continue
            self._last = (seq, int(hdr["crc"]))
            self._since_full = (0 if kind == "full"
                                else self._since_full + 1)

    # -- record files ------------------------------------------------------
    def _path(self, kind: str, seq: int) -> str:
        return os.path.join(self.directory,
                            f"{self.name}-{kind}-{seq:06d}.rec")

    def _scan(self) -> "list[tuple[int, str, str]]":
        """[(seq, kind, path)] sorted by seq, committed records only."""
        pat = re.compile(re.escape(self.name)
                         + r"-(full|delta)-(\d+)\.rec$")
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return []
        for f in entries:
            m = pat.match(f)
            if m:
                out.append((int(m.group(2)), m.group(1),
                            os.path.join(self.directory, f)))
        return sorted(out)

    @staticmethod
    def _read_record(path: str):
        """(header, payload) with the crc verified, or (None, None)
        for any torn/corrupt/unreadable record."""
        try:
            with open(path, "rb") as f:
                line = f.readline(_HEADER_MAX)
                hdr = json.loads(line.decode())
                payload = f.read(int(hdr["payload_bytes"]) + 1)
        except (OSError, ValueError, KeyError):
            return None, None
        if len(payload) != int(hdr["payload_bytes"]):
            return None, None               # truncated or trailing junk
        if (zlib.crc32(payload) & 0xFFFFFFFF) != int(hdr["crc"]):
            return None, None
        return hdr, payload

    def _write_record(self, kind: str, seq: int, obj, *,
                      step: int, parent: "tuple[int, int] | None"):
        payload = pickle.dumps(obj, protocol=4)
        crc = zlib.crc32(payload) & 0xFFFFFFFF
        hdr = {"kind": kind, "seq": seq, "step": int(step),
               "payload_bytes": len(payload), "crc": crc}
        if parent is not None:
            hdr["parent_seq"], hdr["parent_crc"] = parent
        path = self._path(kind, seq)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write((json.dumps(hdr) + "\n").encode())
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        # chaos BEFORE the rename: ``raise`` fails the publish with no
        # committed record (retry-safe — the tmp is orphaned, never
        # visible); ``corrupt`` tears the record AFTER commit, the
        # exact failure the crc chain exists to catch
        decision = faults.fire(
            "delta.publish", tag=seq, exc=OSError,
            msg=f"injected delta-publish failure for {path}")
        os.replace(tmp, path)
        _fsync_dir(self.directory)
        if decision is not None and decision.action == "corrupt":
            size = os.path.getsize(path)
            with open(path, "rb+") as f:
                f.truncate(max(size - max(size // 4, 1), 0))
        return path, crc, len(payload)

    # -- publish -----------------------------------------------------------
    def publish(self, table, *, force_full: bool = False) -> dict:
        """Publish the table's current state as the chain's next
        record. Delta when possible (a clean parent exists, capacity
        unchanged, cadence not due), full otherwise. On success the
        table is marked clean — its next ``state_delta`` is relative
        to THIS record."""
        seq = (self._last[0] + 1) if self._last else 1
        delta = None if force_full else table.state_delta()
        full = (force_full or delta is None or self._last is None
                or self._since_full + 1 >= self.full_every)
        if full:
            kind, obj, parent = "full", table.state_dict(), None
        else:
            kind, obj, parent = "delta", delta, self._last
        dirty = getattr(table, "dirty_rows", None)
        path, crc, nbytes = self._write_record(
            kind, seq, obj, step=getattr(table, "step", 0),
            parent=parent)
        table.mark_clean()
        self._last = (seq, crc)
        self._since_full = 0 if kind == "full" else self._since_full + 1
        if kind == "full":
            self.published_full += 1
        else:
            self.published_delta += 1
        telemetry.event("delta.publish", kind=kind, seq=seq,
                        bytes=nbytes, step=getattr(table, "step", 0),
                        dirty_rows=dirty)
        return {"kind": kind, "seq": seq, "path": path,
                "bytes": nbytes, "crc": crc}

    # -- reconstruct -------------------------------------------------------
    def reconstruct(self, cfg) -> "tuple[object, dict]":
        """Rebuild a table from the chain: newest intact full, then
        every crc+parent-linked delta after it, in seq order. Returns
        ``(table, info)``; ``info['chain_broken']`` is True when a
        broken link truncated the walk (the longest intact prefix
        serves). Raises :class:`DeltaChainError` when no intact full
        exists anywhere."""
        from distributed_tensorflow_tpu.embedding.dynamic import (
            DynamicTable)
        recs = self._scan()
        by_seq = {seq: (kind, path) for seq, kind, path in recs}
        max_seq = recs[-1][0] if recs else 0
        fulls = [seq for seq, kind, _ in recs if kind == "full"]
        for base_seq in reversed(fulls):
            hdr, payload = self._read_record(by_seq[base_seq][1])
            if hdr is None:
                continue                    # corrupt full: try older
            table = DynamicTable(cfg)
            table.load_state_dict(pickle.loads(payload))
            prev = (base_seq, int(hdr["crc"]))
            applied = 0
            for seq in range(base_seq + 1, max_seq + 1):
                nxt = by_seq.get(seq)
                if nxt is None or nxt[0] != "delta":
                    break           # gap, or a (corrupt) newer full
                dh, dp = self._read_record(nxt[1])
                if dh is None or (dh.get("parent_seq"),
                                  dh.get("parent_crc")) != prev:
                    break           # torn record / link mismatch
                table.apply_state_delta(pickle.loads(dp))
                prev = (seq, int(dh["crc"]))
                applied += 1
            return table, {"base_seq": base_seq,
                           "served_seq": prev[0],
                           "applied_deltas": applied,
                           # anything newer than what we served means a
                           # link somewhere refused to verify
                           "chain_broken": prev[0] < max_seq,
                           "records": len(recs)}
        raise DeltaChainError(
            f"{self.name}: no intact full record under "
            f"{self.directory} ({len(recs)} records on disk)")

    def record_sizes(self) -> "list[dict]":
        """[{seq, kind, bytes}] for every committed record — the bench
        reads delta-vs-full bytes off this."""
        out = []
        for seq, kind, path in self._scan():
            try:
                out.append({"seq": seq, "kind": kind,
                            "bytes": os.path.getsize(path)})
            except OSError:
                pass
        return out
