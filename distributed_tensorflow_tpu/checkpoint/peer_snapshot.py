"""In-memory host snapshots + peer replicas (the warm checkpoint tiers).

The fast half of the recovery ladder (``host`` and ``peer`` in
host > peer > local-disk > durable-disk): every worker keeps the last K
device->host snapshots of the shards *it* owns, plus a replica of one
ring-assigned peer's shards, so a reformed cluster can usually restore
from a surviving worker's memory in seconds instead of re-reading disk
(≙ the reference's preemption-aware ``failure_handling`` saving stack
taken one tier hotter; same idea as Gemini/CheckFreq-style in-memory
checkpointing).

Pieces:

- :class:`HostSnapshot` — one worker's host copy of its shard arrays at
  a step, plus the checkpoint index needed to reassemble them.
- :class:`SnapshotStore` — bounded per-owner retention (own snapshots
  AND peer replicas), mirrored write-through to a *memdir*: a directory
  standing in for node RAM/ramdisk that survives a **process** restart
  but not a **machine** loss (the recovery supervisor wipes a dead
  worker's memdir; a straggler restarted on the same machine keeps
  its). ``load_surviving()`` re-reads the memdir after a restart.
- :func:`exchange` — the ring replication step, run at each snapshot
  boundary over the coordination KV (generation-namespaced): worker *i*
  publishes its packed snapshot and stores a replica of worker
  ``(i+1) % N``'s. One replica per worker means any *single* worker
  death leaves every shard recoverable from memory; adjacent double
  deaths fall through to the disk tiers.
- :func:`negotiate` — the cluster-consistent restore decision for a
  reformed generation: every worker publishes its surviving inventory,
  the chief picks the freshest *complete* memory step (every owner of
  that capture must be held by someone) or the freshest intact disk
  checkpoint, and publishes the decision; holders then publish the
  needed parts and everyone reassembles. All KV reads are of
  peer-written keys (a worker never re-reads what it wrote — the safe
  direction on legacy TSL clients; see cluster/coordination.py).

The KV transfer path is sized for coordination-plane state (model +
optimizer shards of test-scale jobs, tens of MB); a production
deployment would swap the transfer for a bulk channel (gloo/NCCL
broadcast) behind the same negotiation.
"""

from __future__ import annotations

import dataclasses
import io
import json
import os
import shutil
from typing import Any, Mapping

import numpy as np

from distributed_tensorflow_tpu.resilience import faults

#: Reserved npz key carrying the JSON metadata record.
_META_KEY = "__dtx_snapshot_meta__"


@dataclasses.dataclass
class HostSnapshot:
    """One worker's host-RAM copy of its checkpoint shards at a step."""

    owner: int                    # process id that captured it
    step: int
    world: int                    # num_processes at capture time
    index: dict                   # checkpoint index (leaves meta)
    arrays: dict[str, np.ndarray]  # shard arrays incl. "::off" offsets

    @property
    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.arrays.values())


def pack(snap: HostSnapshot) -> bytes:
    """Serialize to self-describing npz bytes (the on-disk shard format
    plus a metadata record) — safe to ship over the coordination KV."""
    meta = json.dumps({"owner": snap.owner, "step": snap.step,
                       "world": snap.world, "index": snap.index})
    buf = io.BytesIO()
    np.savez(buf, **snap.arrays,
             **{_META_KEY: np.frombuffer(meta.encode(), dtype=np.uint8)})
    return buf.getvalue()


def unpack(data: bytes) -> HostSnapshot:
    with np.load(io.BytesIO(data)) as z:
        meta = json.loads(bytes(z[_META_KEY]).decode())
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
    return HostSnapshot(owner=int(meta["owner"]), step=int(meta["step"]),
                        world=int(meta["world"]), index=meta["index"],
                        arrays=arrays)


#: KV blob chunk size — comfortably under the coordination service's
#: 4 MiB grpc message cap.
CHUNK = 2 << 20


def kv_put_blob(agent, prefix: str, data: bytes):
    """Publish ``data`` under ``prefix`` as write-once chunk keys with a
    committed-last count key (readers can never observe a partial
    blob). Chunks stay under the grpc message cap.

    The transport is agent-agnostic: anything exposing
    ``key_value_set``/``key_value_get`` works — the coordination
    service's KV for ring replication here, and serving's file-backed
    :class:`~distributed_tensorflow_tpu.serving.migrate.FileKV` for
    KV-block migration (serving/migrate.py reuses this exact
    chunked write-once protocol, so a writer SIGKILLed mid-publish
    never leaves an adoptable half-blob)."""
    n = max(1, (len(data) + CHUNK - 1) // CHUNK)
    for i in range(n):
        agent.key_value_set(f"{prefix}/c{i}",
                            data[i * CHUNK:(i + 1) * CHUNK])
    agent.key_value_set(f"{prefix}/n", str(n))


def kv_get_blob(agent, prefix: str, timeout_s: float) -> bytes:
    """Fetch a blob :func:`kv_put_blob` published (blocks until the
    committed-last count key lands, so a torn publish is never read)."""
    n = int(agent.key_value_get(f"{prefix}/n", timeout_s=timeout_s))
    return b"".join(
        agent.key_value_get(f"{prefix}/c{i}", timeout_s=timeout_s)
        for i in range(n))


def kv_blob_committed(agent, prefix: str) -> bool:
    """Non-blocking: has a blob under ``prefix`` fully committed? Needs
    an agent with ``key_value_try_get`` (FileKV has one)."""
    return agent.key_value_try_get(f"{prefix}/n") is not None


# backwards-compatible private spellings (pre-factoring callers)
_CHUNK = CHUNK
_kv_put_blob = kv_put_blob
_kv_get_blob = kv_get_blob


def ring_source(pid: int, world: int) -> int:
    """The peer whose snapshots ``pid`` replicates (its ring successor)."""
    return (pid + 1) % world


def ring_replicator(pid: int, world: int) -> int:
    """The peer that replicates ``pid``'s snapshots."""
    return (pid - 1) % world


def assign_replicators(world: int,
                       domains: "Mapping[int, object] | None" = None
                       ) -> "dict[int, int]":
    """{owner: replicator} — the placement policy of the replica ring.

    Without ``domains`` this is the historical blind ring
    (``replicator = (owner - 1) % world``) byte for byte. With a
    ``{pid: failure_domain}`` map, every owner's replicator is placed
    OUTSIDE the owner's failure domain whenever any other domain has a
    member — so a whole-domain loss (rack power, ToR switch) can never
    take a snapshot and its only replica together, which is exactly
    what the blind ring lets happen when adjacent pids share a rack.
    Replicas are spread by load (fewest replicas held, lowest pid to
    break ties), so one replicator may hold several owners' replicas
    when domains are unequal — deterministic for a given (world,
    domains), and every participant computes the identical assignment
    with no extra coordination.
    """
    if world < 2:
        return {}
    if not domains:
        return {o: (o - 1) % world for o in range(world)}
    dom = {p: str(domains[p]) if p in domains else f"__solo{p}"
           for p in range(world)}
    load = {p: 0 for p in range(world)}
    out: "dict[int, int]" = {}
    for owner in range(world):
        cands = [p for p in range(world)
                 if p != owner and dom[p] != dom[owner]]
        if not cands:                     # single-domain fleet: any
            cands = [p for p in range(world) if p != owner]  # peer
        pick = min(cands, key=lambda p: (load[p], p))
        out[owner] = pick
        load[pick] += 1
    return out


def replica_sources(pid: int, world: int,
                    domains: "Mapping[int, object] | None" = None
                    ) -> "tuple[int, ...]":
    """The owners whose snapshots ``pid`` must replicate under
    :func:`assign_replicators` (the inverse map; possibly several, or
    none, when domains are unequal)."""
    return tuple(sorted(o for o, r in
                        assign_replicators(world, domains).items()
                        if r == pid))


class SnapshotStore:
    """Bounded retention of host snapshots (own + peer replicas).

    ``memdir`` mirrors every snapshot to node-local storage standing in
    for host RAM: it survives a process restart (straggler respawned on
    the same machine) but is wiped by the supervisor when the machine
    is considered dead. ``None`` keeps snapshots purely in-process.
    """

    def __init__(self, memdir: str | None = None, *, keep: int = 2):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.memdir = memdir
        self.keep = keep
        # owner -> {step -> HostSnapshot}, each owner pruned to ``keep``
        self._snaps: dict[int, dict[int, HostSnapshot]] = {}
        if memdir:
            os.makedirs(memdir, exist_ok=True)

    # -- write ------------------------------------------------------------
    def put(self, snap: HostSnapshot):
        """Retain ``snap`` (own capture or a peer replica), pruning the
        owner's oldest beyond ``keep``; mirrored to the memdir."""
        per_owner = self._snaps.setdefault(snap.owner, {})
        per_owner[snap.step] = snap
        evicted = sorted(per_owner)[:-self.keep]
        for step in evicted:
            del per_owner[step]
        if self.memdir:
            self._mirror(snap)
            for step in evicted:
                shutil.rmtree(self._snap_dir(snap.owner, step),
                              ignore_errors=True)

    def _snap_dir(self, owner: int, step: int) -> str:
        return os.path.join(self.memdir, f"o{owner}", f"s{step}")

    def _mirror(self, snap: HostSnapshot):
        """Write-through with a commit marker: part first, ``meta.json``
        last — a loader only trusts directories whose meta landed."""
        d = self._snap_dir(snap.owner, snap.step)
        os.makedirs(d, exist_ok=True)
        part = os.path.join(d, "part.npz")
        with open(part + ".tmp", "wb") as f:
            np.savez(f, **snap.arrays)
        os.replace(part + ".tmp", part)
        meta = os.path.join(d, "meta.json")
        with open(meta + ".tmp", "w") as f:
            json.dump({"owner": snap.owner, "step": snap.step,
                       "world": snap.world, "index": snap.index}, f)
        os.replace(meta + ".tmp", meta)

    # -- read -------------------------------------------------------------
    def get(self, owner: int, step: int) -> HostSnapshot | None:
        return self._snaps.get(owner, {}).get(step)

    def inventory(self) -> dict[int, dict[int, int]]:
        """{owner: {step: world-at-capture}} of everything held."""
        return {o: {s: snap.world for s, snap in per.items()}
                for o, per in self._snaps.items()}

    def load_surviving(self) -> int:
        """Re-populate from the memdir after a process restart; returns
        the number of snapshots recovered. Torn mirrors (no meta.json)
        and unreadable parts are skipped."""
        if not self.memdir or not os.path.isdir(self.memdir):
            return 0
        loaded = 0
        for od in sorted(os.listdir(self.memdir)):
            if not od.startswith("o"):
                continue
            for sd in sorted(os.listdir(os.path.join(self.memdir, od))):
                d = os.path.join(self.memdir, od, sd)
                try:
                    with open(os.path.join(d, "meta.json")) as f:
                        meta = json.load(f)
                    with np.load(os.path.join(d, "part.npz")) as z:
                        arrays = {k: z[k] for k in z.files}
                except (OSError, ValueError, KeyError):
                    continue
                self.put(HostSnapshot(
                    owner=int(meta["owner"]), step=int(meta["step"]),
                    world=int(meta["world"]), index=meta["index"],
                    arrays=arrays))
                loaded += 1
        return loaded


# ---------------------------------------------------------------------------
# Ring replication (at each snapshot boundary)
# ---------------------------------------------------------------------------

def exchange(store: SnapshotStore, snap: HostSnapshot, agent, *,
             timeout_s: float = 60.0,
             domains: "Mapping[int, object] | None" = None) -> bool:
    """Collective ring replication for one snapshot step: publish this
    worker's packed snapshot under a per-(step, worker) KV key and store
    a replica of every owner :func:`assign_replicators` assigned to this
    worker (exactly the ring source without ``domains``; with a domain
    map, replicas are placed across failure domains — possibly several
    owners, possibly none). Every worker snapshots the same steps (the
    save cadence is deterministic), so the blocking fetches are a
    near-lockstep rendezvous. A missing peer (died mid-run) degrades
    to no-replica-update — the supervisor will reform shortly anyway.
    Returns True when every assigned replica was stored.
    """
    if not getattr(agent, "is_distributed", False) or agent.num_processes < 2:
        return False
    pid, world = agent.process_id, agent.num_processes
    faults.fire("peer.exchange", tag=str(pid), exc=OSError,
                msg=f"injected peer-exchange failure (worker {pid})")
    _kv_put_blob(agent, f"peer_snap/s{snap.step}/w{pid}", pack(snap))
    ok = False
    for src in replica_sources(pid, world, domains):
        try:
            data = _kv_get_blob(agent, f"peer_snap/s{snap.step}/w{src}",
                                timeout_s=timeout_s)
        except Exception:
            return False          # peer dead/slow: replica skipped
        try:
            store.put(unpack(data))
        except (ValueError, KeyError):
            return False          # torn/alien payload: replica skipped
        ok = True
    return ok


# ---------------------------------------------------------------------------
# Reform-time restore negotiation
# ---------------------------------------------------------------------------

def _complete_memory_steps(all_inv: Mapping[int, Mapping]) -> dict[int, int]:
    """{step: world-at-capture} of steps where EVERY owner of that
    capture is held by someone — the only memory states that can be
    reassembled into the full checkpoint."""
    # step -> (world, set of owners held)
    by_step: dict[int, tuple[int, set[int]]] = {}
    for inv in all_inv.values():
        for owner, steps in inv.items():
            for step, world in steps.items():
                w, owners = by_step.setdefault(int(step),
                                               (int(world), set()))
                owners.add(int(owner))
    return {step: world for step, (world, owners) in by_step.items()
            if owners >= set(range(world))}


def _decide(all_inv: Mapping[int, Mapping],
            disk_best: "tuple[int, str, str] | None") -> dict:
    """The chief's restore decision: freshest complete memory step vs
    freshest intact disk checkpoint; memory wins ties (warmer tier).

    ``all_inv``: {pid: {owner: {step: world}}} — surviving inventories.
    ``disk_best``: (step, path, tier) of the best disk candidate.
    """
    complete = _complete_memory_steps(all_inv)
    mem_step = max(complete) if complete else None
    disk_step = disk_best[0] if disk_best else None
    if mem_step is not None and (disk_step is None or mem_step >= disk_step):
        world = complete[mem_step]
        holders: dict[str, int] = {}
        for owner in range(world):
            # prefer the owner itself (its own memory — no transfer),
            # else the lowest-pid holder (deterministic)
            cands = sorted(pid for pid, inv in all_inv.items()
                           if mem_step in inv.get(owner, {}))
            holders[str(owner)] = owner if owner in cands else cands[0]
        return {"source": "memory", "step": mem_step, "world": world,
                "holders": holders,
                "disk_step": disk_step}
    if disk_best is not None:
        return {"source": "disk", "step": disk_best[0],
                "path": disk_best[1], "tier": disk_best[2],
                "mem_step": mem_step}
    return {"source": "none"}


def negotiate(store: SnapshotStore, agent,
              disk_best: "tuple[int, str, str] | None", *,
              timeout_s: float = 60.0) -> dict:
    """Agree cluster-wide on the restore source for this generation.

    Collective: EVERY process of the (reformed) cluster must call this
    exactly once per generation. Keys ride the generation-namespaced KV,
    so a dead incarnation's negotiation can never bleed in. The chief
    decides (it alone sees every inventory) and publishes; everyone else
    blocks on the decision. Single-process/non-distributed: decided
    locally from this store alone.
    """
    inv = store.inventory()
    if not getattr(agent, "is_distributed", False) or agent.num_processes < 2:
        return _decide({0: inv}, disk_best)
    pid, world = agent.process_id, agent.num_processes
    # JSON keys must be strings; keep the wire format canonical
    wire = {str(o): {str(s): w for s, w in per.items()}
            for o, per in inv.items()}
    agent.key_value_set(f"elastic_restore/inv/p{pid}", json.dumps(wire))
    agent.barrier("elastic_restore/inv", timeout_s=timeout_s)
    if agent.is_chief:
        all_inv: dict[int, dict] = {pid: inv}
        for i in range(world):
            if i == pid:
                continue          # own inventory: local copy (never
            v = agent.key_value_try_get(  # self-read the KV — legacy
                f"elastic_restore/inv/p{i}")   # client hazard)
            if v is None:
                continue          # peer died between barrier and read
            try:
                peer = json.loads(v)
            except ValueError:
                continue
            all_inv[i] = {int(o): {int(s): int(w) for s, w in per.items()}
                          for o, per in peer.items()}
        decision = _decide(all_inv, disk_best)
        agent.key_value_set("elastic_restore/decision",
                            json.dumps(decision))
        return decision
    raw = agent.key_value_get("elastic_restore/decision",
                              timeout_s=timeout_s)
    return json.loads(raw)


def fetch_parts(store: SnapshotStore, agent, decision: Mapping, *,
                timeout_s: float = 60.0) -> list[HostSnapshot]:
    """Execute a ``memory`` decision: publish the parts this process was
    designated holder of, fetch the rest from their holders over the KV
    (never re-reading a self-written key), and return every owner's
    snapshot at the agreed step."""
    step = int(decision["step"])
    holders = {int(o): int(p) for o, p in decision["holders"].items()}
    pid = agent.process_id if getattr(agent, "is_distributed", False) else 0
    for owner, holder in sorted(holders.items()):
        if holder != pid:
            continue
        snap = store.get(owner, step)
        if snap is not None and getattr(agent, "is_distributed", False):
            _kv_put_blob(agent, f"elastic_restore/part/s{step}/o{owner}",
                         pack(snap))
    parts: list[HostSnapshot] = []
    for owner, holder in sorted(holders.items()):
        local = store.get(owner, step)
        if local is not None:
            parts.append(local)   # held here (own or replica): no fetch
            continue
        data = _kv_get_blob(
            agent, f"elastic_restore/part/s{step}/o{owner}",
            timeout_s=timeout_s)
        parts.append(unpack(data))
    return parts


def wipe_memdir(memdir: str):
    """Supervisor-side: the machine behind ``memdir`` is dead — its
    in-memory snapshots (own AND replicas it held) are gone."""
    shutil.rmtree(memdir, ignore_errors=True)


def any_fetched_remotely(store: SnapshotStore, decision: Mapping) -> bool:
    """True when executing ``decision`` required at least one remote
    fetch for this process (distinguishes the ``peer`` tier from pure
    ``host`` restores)."""
    step = int(decision["step"])
    return any(store.get(int(o), step) is None
               for o in decision["holders"])
