"""Passive preemption monitor.

≙ tensorflow/python/distribute/failure_handling/preemption_watcher.py:45
``PreemptionWatcher`` (SURVEY.md §2.5): watches for a platform preemption
notice without wrapping the train loop; exposes ``preemption_message`` once
one arrives, so user code can poll between steps.
"""

from __future__ import annotations

import signal
import threading
import time
from typing import Callable


class PreemptionWatcher:
    """Watches SIGTERM (and an optional poll fn) in the background."""

    def __init__(self, watcher_fn: Callable[[], bool] | None = None,
                 poll_interval: float = 1.0):
        self._message: str | None = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._handler = None
        self._prev_handler = None
        self._install()
        self._thread = None
        if watcher_fn is not None:
            def loop():
                while not self._stop.is_set():
                    try:
                        if watcher_fn():
                            self._set("platform notice")
                            return
                    except Exception:
                        pass
                    time.sleep(poll_interval)

            self._thread = threading.Thread(target=loop, daemon=True)
            self._thread.start()

    def _install(self):
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def handler(signum, frame):
                self._set(f"signal {signum}")
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, handler)
            # kept for stop(): stacked watchers must unwind LIFO without
            # leaking handlers across tests
            self._handler = handler
            self._prev_handler = prev
        except (ValueError, OSError):
            pass

    def _set(self, msg: str):
        with self._lock:
            self._message = msg

    @property
    def preemption_message(self) -> str | None:
        with self._lock:
            return self._message

    def block_until_worker_exit(self, timeout: float | None = None):
        """≙ PreemptionWatcher.block_until_worker_exit."""
        start = time.time()
        while self.preemption_message is None:
            if timeout is not None and time.time() - start > timeout:
                return
            time.sleep(0.05)

    def stop(self):
        """Stop the poll thread and restore the SIGTERM handler that was
        installed before this watcher (only if ours is still the current
        one — an out-of-order stop must not break a newer watcher's
        chain)."""
        self._stop.set()
        if (self._handler is not None
                and threading.current_thread() is threading.main_thread()):
            try:
                if signal.getsignal(signal.SIGTERM) is self._handler:
                    signal.signal(signal.SIGTERM, self._prev_handler)
                    self._handler = None
            except (ValueError, OSError):
                pass
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
