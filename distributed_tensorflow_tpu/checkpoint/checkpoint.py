"""Object-graph checkpointing with sharded, async-capable writes.

TPU-native counterpart of the reference's checkpoint layer
(reference: tensorflow/python/checkpoint/checkpoint.py:2061
``tf.train.Checkpoint``, :1179 ``TrackableSaver``;
checkpoint_management.py:519 ``CheckpointManager`` — SURVEY.md §5.4).

Design: a checkpoint is a directory of per-host ``.npz`` shard files plus a
JSON index. Each host writes exactly the array shards it owns
(``addressable_shards``) — the TPU-native form of the reference's
"chief writes the real checkpoint, non-chiefs write temp dirs" protocol
(multi_worker_util.should_save_checkpoint): with sharded state every host
*must* write, and restore reassembles per-host. Distributed-variable policy
integration (≙ values.py:1159-1294 saveables): mirrored variables save one
copy (process 0 owns the replica), ON_READ variables save their reduced
value, ShardedVariables save as slices of one logical tensor.

Async saves (≙ async_checkpoint_helper.py): device->host transfer happens
synchronously (cheap), file writes on a background thread.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.parallel.values import DistributedVariable
from distributed_tensorflow_tpu.resilience import faults

_INDEX_FILE = "checkpoint.index.json"
_LATEST_FILE = "checkpoint"  # ≙ the reference's `checkpoint` state file


class CheckpointCorruptError(RuntimeError):
    """A shard file fails its recorded checksum/size — the checkpoint is
    torn (truncated write, partial commit) and must not be restored."""


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _flatten(tree, prefix=""):
    """Flatten a nested dict/list/variable tree into {path: leaf}."""
    out = {}
    if isinstance(tree, DistributedVariable):
        out[prefix or "var"] = tree
    elif isinstance(tree, Mapping):
        for k in sorted(tree.keys()):
            out.update(_flatten(tree[k], f"{prefix}/{k}" if prefix else str(k)))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}/{i}" if prefix else str(i)))
    elif hasattr(tree, "__dict__") and hasattr(tree, "_checkpoint_children"):
        for k, v in tree._checkpoint_children().items():
            out.update(_flatten(v, f"{prefix}/{k}" if prefix else k))
    else:
        out[prefix or "value"] = tree
    return out


class Checkpoint:
    """Object-style checkpoint of a pytree of arrays/variables.

    ``Checkpoint(state=pytree, ...)`` snapshots leaves on ``save`` and
    restores *in place* for DistributedVariables (values re-placed with
    their original sharding) or returns the restored pytree from
    ``restore``.
    """

    def __init__(self, **objects):
        self._objects = objects
        self._save_counter = 0
        self._async_thread: threading.Thread | None = None
        self._async_error: BaseException | None = None

    @property
    def save_counter(self) -> int:
        return self._save_counter

    # -- save -------------------------------------------------------------
    def save(self, file_prefix: str, *, async_write: bool = False) -> str:
        """Write ``<file_prefix>-<counter>/``; returns the path.

        Multi-host: every process calls this; each writes only shards it
        owns. Process 0 writes the index.
        """
        self._save_counter += 1
        path = f"{file_prefix}-{self._save_counter}"
        self.write(path, async_write=async_write)
        return path

    def write(self, path: str, *, async_write: bool = False) -> str:
        # span covers the BLOCKING portion (device->host + commit when
        # sync; device->host + thread handoff when async) — the async
        # file IO reports separately via the checkpoint.commit event
        with telemetry.span("checkpoint.save", path=path,
                            async_write=async_write):
            return self._write_impl(path, async_write=async_write)

    def _write_impl(self, path: str, *, async_write: bool) -> str:
        flat = _flatten(self._objects)
        proc = jax.process_index()
        tmp = f"{path}.tmp.{proc}"
        os.makedirs(tmp, exist_ok=True)

        # Read each leaf ONCE (ON_READ variables reduce on read — a device
        # computation that must not run twice), then start every
        # device->host transfer before blocking on any
        # (≙ async_checkpoint_helper.py's copy-then-write split).
        vals: dict[str, Any] = {}
        for name, leaf in flat.items():
            val = (leaf.read_value() if isinstance(leaf, DistributedVariable)
                   else leaf)
            vals[name] = val
            if isinstance(val, jax.Array):
                for s in val.addressable_shards:
                    s.data.copy_to_host_async()

        index: dict[str, Any] = {"leaves": {}, "format": 1}
        host_arrays: dict[str, np.ndarray] = {}
        for name, leaf in flat.items():
            arr, meta, offset = self._extract(name, leaf, vals[name])
            index["leaves"][name] = meta
            if arr is not None:
                key = self._fname(name)
                host_arrays[key] = arr
                if offset is not None:
                    host_arrays[key + "::off"] = np.asarray([offset],
                                                            dtype=np.int64)

        def finish():
            # fsync BEFORE the rename into place: an OS crash after the
            # rename must not leave a shard whose data pages never hit
            # disk (rename is only atomic for the directory entry).
            with telemetry.span("checkpoint.commit", path=path):
                shard = os.path.join(tmp, f"shard_{proc}.npz")
                with open(shard, "wb") as f:
                    np.savez(f, **host_arrays)
                    f.flush()
                    os.fsync(f.fileno())
                self._commit(tmp, path, index)

        def finish_async():
            try:
                finish()
            except BaseException as e:   # surfaced on next sync/save/restore
                self._async_error = e

        if async_write:
            # device->host already done above (np arrays); file IO async
            self._join_pending()
            self._async_thread = threading.Thread(target=finish_async,
                                                  daemon=True)
            self._async_thread.start()
        else:
            finish()                     # sync path: raise right here
        return path

    def _commit(self, tmp: str, path: str, index: dict):
        """Multi-host commit protocol (≙ checkpoint_management's
        chief-writes-last contract, hardened):

        1. every process renames its shard files into ``path``;
        2. cross-process barrier — no host proceeds until ALL shards are
           in place (TSL coordination service; no-op single-process);
        3. process 0 writes the index — which records every shard file's
           size + crc32 (gathered over the KV store) — to a temp name
           and atomically renames it LAST: the index's existence marks
           the checkpoint complete (``_list_checkpoints`` keys on it,
           and verifies the recorded sizes), so a torn checkpoint is
           never observable;
        4. exit barrier so no process returns (and e.g. starts a restore
           or another save into the same path) before the index exists.

        Chaos site ``checkpoint.commit``: ``raise`` fails the commit,
        ``corrupt`` tears this process's shard AFTER the index lands —
        the exact failure the size/crc records exist to catch.
        """
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        decision = faults.fire("checkpoint.commit", tag=path, exc=OSError,
                               msg=f"injected commit failure for {path}")
        # Per-file integrity record for this process's shards, taken
        # while they are still private to us (pre-rename).
        sums = {f: {"crc32": _crc32_file(os.path.join(tmp, f)),
                    "size": os.path.getsize(os.path.join(tmp, f))}
                for f in os.listdir(tmp)}
        os.makedirs(path, exist_ok=True)
        for f in os.listdir(tmp):
            os.replace(os.path.join(tmp, f), os.path.join(path, f))
        os.rmdir(tmp)
        # Token = basename + abspath hash: two saves into different
        # directories that share a basename (e.g. every Model backup dir
        # is ".../backup") must NOT meet at the same barrier.
        import hashlib
        token = (os.path.basename(path) + "."
                 + hashlib.sha1(os.path.abspath(path).encode())
                 .hexdigest()[:12])
        # Save-counter suffix: a re-save into the SAME path must use
        # fresh KV keys (legacy TSL clients cannot safely re-read
        # deleted-then-recreated keys).
        sums_prefix = f"dtx_ckpt_sums/{token}.{self._save_counter}"
        if agent.is_distributed:
            try:
                agent.key_value_set(f"{sums_prefix}/p{agent.process_id}",
                                    json.dumps(sums))
            except Exception:
                pass            # degraded: index carries fewer records
            try:
                agent.barrier(f"ckpt_shards/{token}", timeout_s=600.0)
            except Exception as e:
                # Peer death mid-save (preemption best-effort path): a
                # possibly-incomplete checkpoint beats none. Warn loudly.
                import sys
                print(f"[dtx.checkpoint] WARNING: shard barrier failed "
                      f"({e}); committing possibly-incomplete checkpoint "
                      f"{path}", file=sys.stderr)
        if agent.is_chief:
            all_sums = dict(sums)
            if agent.is_distributed:
                # enumerated point reads (every process published before
                # the shard barrier; legacy TSL clients hang on remote
                # GetKeyValueDir, and a dead peer just contributes no
                # record — best-effort by design)
                for i in range(agent.num_processes):
                    v = agent.key_value_try_get(f"{sums_prefix}/p{i}")
                    if v is None:
                        continue
                    try:
                        all_sums.update(json.loads(v))
                    except ValueError:
                        pass
            index["shards"] = all_sums
            tmp_index = os.path.join(path, _INDEX_FILE + ".tmp")
            with open(tmp_index, "w") as f:
                json.dump(index, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_index, os.path.join(path, _INDEX_FILE))
        if agent.is_distributed:
            try:
                agent.barrier(f"ckpt_index/{token}", timeout_s=600.0)
            except Exception:
                pass            # exit barrier is best-effort by nature
            if agent.is_chief:
                try:
                    agent.key_value_delete(sums_prefix)
                except Exception:
                    pass
        if decision is not None and decision.action == "corrupt":
            # Torn write AFTER the commit protocol finished: the index
            # says the checkpoint is complete, the storage disagrees.
            shard = os.path.join(path, f"shard_{jax.process_index()}.npz")
            size = os.path.getsize(shard)
            with open(shard, "rb+") as f:
                f.truncate(max(size - max(size // 4, 1), 0))

    def _join_pending(self):
        if self._async_thread is not None and self._async_thread.is_alive():
            self._async_thread.join()
        if self._async_error is not None:
            err, self._async_error = self._async_error, None
            raise RuntimeError("async checkpoint write failed") from err

    def sync(self):
        """Block until any async write completed (≙ AsyncCheckpoint sync)."""
        self._join_pending()

    @staticmethod
    def _fname(name: str) -> str:
        return re.sub(r"[^A-Za-z0-9_.-]", "__", name)

    def _extract(self, name, leaf, val=None):
        """Returns (host_array_or_None, index_meta) for this process.
        ``val`` is the pre-read leaf value (read exactly once by write)."""
        if isinstance(leaf, DistributedVariable):
            if val is None:
                val = leaf.read_value()
            meta = {"kind": "variable", "shape": list(np.shape(val)),
                    "dtype": str(np.asarray(val).dtype) if np.ndim(val) == 0
                    else str(val.dtype)}
            # mirrored/on-read-reduced: single logical tensor, process 0 owns
            if getattr(val, "sharding", None) is not None and \
                    not val.sharding.is_fully_replicated:
                # sharded: save only addressable rows with their offset
                shards = [(s.index, np.asarray(s.data))
                          for s in val.addressable_shards if s.replica_id == 0]
                meta["kind"] = "sharded_variable"
                meta["slices"] = [self._slice_meta(idx) for idx, _ in shards]
                arr, offset = None, None
                if shards:
                    shards = sorted(
                        shards, key=lambda t: (t[0][0].start or 0))
                    for (ia, _), (ib, _) in zip(shards, shards[1:]):
                        if (ia[0].stop or 0) != (ib[0].start or 0):
                            raise NotImplementedError(
                                f"process owns non-contiguous axis-0 slices "
                                f"of {name!r} ({ia[0]} then {ib[0]}); "
                                f"restore would permute rows silently")
                    arr = np.concatenate(
                        [a for _, a in shards], axis=0) \
                        if len(shards) > 1 else shards[0][1]
                    # This process's global axis-0 offset: restore orders
                    # parts by it (file order is NOT slice order).
                    offset = shards[0][0][0].start or 0
                return arr, meta, offset
            if jax.process_index() == 0:
                return np.asarray(val), meta, None
            return None, meta, None
        arr = np.asarray(leaf)
        meta = {"kind": "array", "shape": list(arr.shape),
                "dtype": str(arr.dtype)}
        return (arr if jax.process_index() == 0 else None), meta, None

    @staticmethod
    def _slice_meta(index) -> list:
        out = []
        for sl in index:
            out.append([sl.start if sl.start is not None else 0,
                        sl.stop if sl.stop is not None else -1])
        return out

    # -- restore ----------------------------------------------------------
    def restore(self, path: str) -> dict:
        """Restore from ``path``. DistributedVariables are assigned in
        place (re-placed with their sharding); plain leaves are returned in
        the result pytree."""
        with telemetry.span("checkpoint.restore", path=path):
            return self._restore_impl(path)

    def _restore_impl(self, path: str) -> dict:
        self._join_pending()
        index_path = os.path.join(path, _INDEX_FILE)
        if not os.path.exists(index_path):
            raise FileNotFoundError(f"No checkpoint index at {path}")
        with open(index_path) as f:
            index = json.load(f)
        # Integrity first (size is cheap, crc reads the file the load
        # below reads anyway): a truncated/corrupt shard must surface as
        # CheckpointCorruptError, not an obscure zipfile traceback.
        # Pre-checksum checkpoints (no "shards" record) skip this.
        for f_name, meta in index.get("shards", {}).items():
            fpath = os.path.join(path, f_name)
            if not os.path.exists(fpath):
                raise CheckpointCorruptError(
                    f"checkpoint {path} is missing shard {f_name}")
            size = os.path.getsize(fpath)
            if size != meta.get("size"):
                raise CheckpointCorruptError(
                    f"shard {f_name} in {path} is {size} bytes, index "
                    f"records {meta.get('size')} (torn write?)")
            if "crc32" in meta and _crc32_file(fpath) != meta["crc32"]:
                raise CheckpointCorruptError(
                    f"shard {f_name} in {path} fails its crc32 "
                    f"(corrupt data)")
        shards = {}
        shard_pat = re.compile(r"shard_(\d+)\.npz$")
        for f_name in sorted(os.listdir(path),
                             key=lambda n: (int(shard_pat.match(n).group(1))
                                            if shard_pat.match(n) else -1)):
            if shard_pat.match(f_name):
                shards[f_name] = np.load(os.path.join(path, f_name))

        def lookup(name):
            key = self._fname(name)
            parts = []
            for shard in shards.values():
                if key in shard.files:
                    off = (int(shard[key + "::off"][0])
                           if key + "::off" in shard.files else 0)
                    parts.append((off, shard[key]))
            if not parts:
                raise KeyError(f"Leaf {name!r} missing from checkpoint {path}")
            parts.sort(key=lambda t: t[0])   # slice order, not file order
            return [a for _, a in parts]

        flat = _flatten(self._objects)
        restored = {}
        for name, leaf in flat.items():
            parts = lookup(name)
            if isinstance(leaf, DistributedVariable):
                meta = index["leaves"].get(name, {})
                if meta.get("kind") == "sharded_variable":
                    full = np.concatenate(parts, axis=0) if len(parts) > 1 \
                        else parts[0]
                else:
                    full = parts[0]
                leaf.assign(full.reshape(leaf.shape) if full.shape !=
                            tuple(leaf.shape) else full)
                restored[name] = leaf
            else:
                restored[name] = parts[0]
        return restored

    def read(self, path: str) -> dict:
        return self.restore(path)

    def restore_into(self, path: str) -> dict:
        """Restore from ``path`` AND update the tracked objects in
        place: DistributedVariables are assigned (as in
        :meth:`restore`), and plain-array leaves are replaced inside the
        tracked pytrees, so code holding this ``Checkpoint`` (e.g. a
        SidecarEvaluator's eval_fn) sees the restored state without
        private-attribute surgery. Returns the flat restored mapping."""
        flat_restored = self.restore(path)

        def rebuild(obj, prefix):
            if isinstance(obj, DistributedVariable) or hasattr(obj,
                                                               "assign"):
                return obj                 # assigned in place already
            if isinstance(obj, Mapping):
                return type(obj)(
                    {k: rebuild(obj[k],
                                f"{prefix}/{k}" if prefix else str(k))
                     for k in obj})
            if isinstance(obj, (list, tuple)):
                vals = [rebuild(v, f"{prefix}/{i}" if prefix else str(i))
                        for i, v in enumerate(obj)]
                return type(obj)(vals) if not hasattr(obj, "_fields") \
                    else type(obj)(*vals)
            if (hasattr(obj, "__dict__")
                    and hasattr(obj, "_checkpoint_children")):
                for k, child in obj._checkpoint_children().items():
                    newc = rebuild(child,
                                   f"{prefix}/{k}" if prefix else k)
                    if newc is not child:
                        if k in vars(obj):
                            setattr(obj, k, newc)
                        else:
                            raise ValueError(
                                f"restore_into cannot write restored "
                                f"child {k!r} back into "
                                f"{type(obj).__name__}: "
                                f"_checkpoint_children keys must be "
                                f"attributes (or use .assign leaves)")
                return obj
            return flat_restored.get(prefix or "value", obj)

        for name in list(self._objects):
            self._objects[name] = rebuild(self._objects[name], name)
        return flat_restored

    def get(self, name: str):
        """Public access to a tracked object by constructor kwarg name."""
        return self._objects[name]


class CheckpointManager:
    """Rotation + latest-tracking (≙ checkpoint_management.py:519).

    ``max_to_keep`` oldest-first deletion, ``keep_checkpoint_every_n_hours``
    pinning, ``restore_or_initialize`` convenience, and step-interval
    gating via ``save(checkpoint_number, check_interval)``.
    """

    def __init__(self, checkpoint: Checkpoint, directory: str,
                 max_to_keep: int = 5,
                 keep_checkpoint_every_n_hours: float | None = None,
                 checkpoint_name: str = "ckpt"):
        self.checkpoint = checkpoint
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.keep_every_s = (keep_checkpoint_every_n_hours * 3600
                             if keep_checkpoint_every_n_hours else None)
        self._name = checkpoint_name
        self._kept_pinned: list[str] = []
        # Pin clock starts NOW (≙ the reference's last_preserved_timestamp,
        # checkpoint_management.py:519): the first sweep must NOT pin —
        # a 0.0 epoch origin made `now - last_pin >= keep_every_s` true
        # immediately, permanently pinning the first rotated checkpoint.
        self._last_pin_time = time.time()
        os.makedirs(directory, exist_ok=True)
        self._load_meta()

    @property
    def _prefix(self) -> str:
        return os.path.join(self.directory, self._name)

    # Pin state persists across manager restarts (≙ the reference keeping
    # last_preserved_timestamp in the CheckpointState proto).
    @property
    def _meta_path(self) -> str:
        return os.path.join(self.directory, f"{self._name}.manager.json")

    def _load_meta(self):
        if not os.path.exists(self._meta_path):
            return
        try:
            with open(self._meta_path) as f:
                meta = json.load(f)
            self._last_pin_time = float(meta.get("last_pin_time",
                                                 self._last_pin_time))
            # Pins are persisted as basenames so a manager restarted from
            # a different cwd (or via a different path to the same dir)
            # keeps them out of rotation.
            self._kept_pinned = [
                os.path.join(self.directory, os.path.basename(p))
                for p in meta.get("pinned", [])
                if os.path.isdir(os.path.join(self.directory,
                                              os.path.basename(p)))]
        except (ValueError, OSError):
            pass

    def _save_meta(self):
        if jax.process_index() != 0:
            return
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"last_pin_time": self._last_pin_time,
                       "pinned": [os.path.basename(p)
                                  for p in self._kept_pinned]}, f)
        os.replace(tmp, self._meta_path)

    @staticmethod
    def _is_complete(full: str) -> bool:
        """A checkpoint counts only if its index exists AND every shard
        the index records is present at its recorded size — so a torn
        checkpoint (truncated shard, interrupted commit) is skipped by
        rotation/latest rather than handed to restore. Size-only here
        (stat, not a read); restore does the full crc verification."""
        idx = os.path.join(full, _INDEX_FILE)
        if not os.path.exists(idx):
            return False
        try:
            with open(idx) as f:
                index = json.load(f)
        except (ValueError, OSError):
            return False
        for f_name, meta in index.get("shards", {}).items():
            try:
                if os.path.getsize(os.path.join(full, f_name)) != \
                        meta.get("size"):
                    return False
            except OSError:
                return False
        return True

    def _list_checkpoints(self) -> list[tuple[int, str]]:
        pat = re.compile(re.escape(self._name) + r"-(\d+)$")
        out = []
        for d in os.listdir(self.directory):
            m = pat.match(d)
            full = os.path.join(self.directory, d)
            if m and os.path.isdir(full) and self._is_complete(full):
                out.append((int(m.group(1)), full))
        return sorted(out)

    @property
    def latest_checkpoint(self) -> str | None:
        cks = self._list_checkpoints()
        return cks[-1][1] if cks else None

    @property
    def checkpoints(self) -> list[str]:
        return [p for _, p in self._list_checkpoints()]

    def save(self, checkpoint_number: int | None = None, *,
             async_write: bool = False) -> str:
        if checkpoint_number is not None:
            self.checkpoint._save_counter = checkpoint_number - 1
        path = self.checkpoint.save(self._prefix, async_write=async_write)
        self._sweep()
        return path

    def _sweep(self):
        # Pinned checkpoints are permanently out of rotation: they neither
        # count toward max_to_keep nor get deleted.
        cks = [(n, p) for n, p in self._list_checkpoints()
               if p not in self._kept_pinned]
        now = time.time()
        changed = False
        while len(cks) > self.max_to_keep:
            num, path = cks.pop(0)
            if self.keep_every_s is not None and \
                    now - self._last_pin_time >= self.keep_every_s:
                self._kept_pinned.append(path)
                self._last_pin_time = now
                changed = True
                continue
            if jax.process_index() == 0:
                shutil.rmtree(path, ignore_errors=True)
        if changed:
            self._save_meta()

    def restore_or_initialize(self) -> str | None:
        """≙ CheckpointManager.restore_or_initialize: restore latest if one
        exists, else None (caller keeps fresh init)."""
        latest = self.latest_checkpoint
        if latest is not None:
            self.checkpoint.restore(latest)
            m = re.search(r"-(\d+)$", latest)
            if m:
                self.checkpoint._save_counter = int(m.group(1))
        return latest


def latest_checkpoint(directory: str, name: str = "ckpt") -> str | None:
    """Module-level convenience (≙ tf.train.latest_checkpoint)."""
    mgr = CheckpointManager(Checkpoint(), directory, checkpoint_name=name)
    return mgr.latest_checkpoint
