"""Object-graph checkpointing with sharded, async-capable writes.

TPU-native counterpart of the reference's checkpoint layer
(reference: tensorflow/python/checkpoint/checkpoint.py:2061
``tf.train.Checkpoint``, :1179 ``TrackableSaver``;
checkpoint_management.py:519 ``CheckpointManager`` — SURVEY.md §5.4).

Design: a checkpoint is a directory of per-host ``.npz`` shard files plus a
JSON index. Each host writes exactly the array shards it owns
(``addressable_shards``) — the TPU-native form of the reference's
"chief writes the real checkpoint, non-chiefs write temp dirs" protocol
(multi_worker_util.should_save_checkpoint): with sharded state every host
*must* write, and restore reassembles per-host. Distributed-variable policy
integration (≙ values.py:1159-1294 saveables): mirrored variables save one
copy (process 0 owns the replica), ON_READ variables save their reduced
value, ShardedVariables save as slices of one logical tensor.

Async saves (≙ async_checkpoint_helper.py): device->host transfer happens
synchronously (cheap), file writes on a background thread.

Tiered commits (the recovery ladder's disk half): with
``CheckpointManager(local_dir=...)`` every save commits first to the
node-local fast directory (tier ``local``) and then — pipelined behind
training on the same async machinery — re-commits the identical shards
to the durable directory (tier ``durable``). Each tier commit gets its
own ``checkpoint.commit`` telemetry span carrying a ``tier`` field, and
the index records its tier so ``latest_checkpoint`` can prefer the
freshest *intact* tier. The in-memory tiers (``host``/``peer``) live in
checkpoint/peer_snapshot.py and plug in via ``snapshot_store``.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np

from distributed_tensorflow_tpu import telemetry
from distributed_tensorflow_tpu.parallel.values import DistributedVariable
from distributed_tensorflow_tpu.resilience import faults

_INDEX_FILE = "checkpoint.index.json"
_LATEST_FILE = "checkpoint"  # ≙ the reference's `checkpoint` state file


def checkpoint_span_id(path: str) -> str:
    """Causality id shared by every telemetry span of ONE logical save:
    the ``checkpoint.save`` capture and each tier's
    ``checkpoint.commit`` (local/durable/host). The id derives from the
    checkpoint's tier-invariant basename (``<name>-<number>``), so the
    whole tier ladder links into one flow chain in the merged trace
    (telemetry/trace.py) without threading ids across processes."""
    return f"ckpt/{os.path.basename(path)}"


class CheckpointCorruptError(RuntimeError):
    """A shard file fails its recorded checksum/size — the checkpoint is
    torn (truncated write, partial commit) and must not be restored."""


def _fsync_dir(path: str):
    """fsync a directory so renames inside it are durable — file
    contents being fsynced does not make the *directory entry* crash
    -safe; without this a host crash right after a tmp->final rename can
    lose a checkpoint the index already calls committed."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return                    # platform without dir-open semantics
    try:
        os.fsync(fd)
    except OSError:
        pass                      # e.g. network fs rejecting dir fsync
    finally:
        os.close(fd)


def _crc32_file(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _flatten(tree, prefix=""):
    """Flatten a nested dict/list/variable tree into {path: leaf}."""
    out = {}
    if isinstance(tree, DistributedVariable):
        out[prefix or "var"] = tree
    elif isinstance(tree, Mapping):
        for k in sorted(tree.keys()):
            out.update(_flatten(tree[k], f"{prefix}/{k}" if prefix else str(k)))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}/{i}" if prefix else str(i)))
    elif hasattr(tree, "__dict__") and hasattr(tree, "_checkpoint_children"):
        for k, v in tree._checkpoint_children().items():
            out.update(_flatten(v, f"{prefix}/{k}" if prefix else k))
    else:
        out[prefix or "value"] = tree
    return out


class Checkpoint:
    """Object-style checkpoint of a pytree of arrays/variables.

    ``Checkpoint(state=pytree, ...)`` snapshots leaves on ``save`` and
    restores *in place* for DistributedVariables (values re-placed with
    their original sharding) or returns the restored pytree from
    ``restore``.
    """

    def __init__(self, single_writer: bool = False, **objects):
        #: ``single_writer=True`` declares that THIS process alone owns
        #: the full tracked state and saves/restores it regardless of
        #: how many processes the distributed runtime has — the
        #: disaggregated-cluster case (input/data_service.py: one
        #: trainer + N input workers who never checkpoint). The commit
        #: protocol then skips its cross-process barriers and KV shard
        #: gathering: an SPMD commit barrier over a cluster whose other
        #: members never save would block for the full barrier timeout
        #: on every save. Requires every tracked leaf to be fully
        #: addressable from this process (no cross-process sharding).
        self._single_writer = bool(single_writer)
        self._objects = objects
        self._save_counter = 0
        self._async_thread: threading.Thread | None = None
        self._async_error: BaseException | None = None
        # Paths an in-flight (possibly async) write still commits into —
        # the manager's sweep must never delete these out from under the
        # commit thread.
        self._pending_lock = threading.Lock()
        self._pending_paths: set[str] = set()

    @property
    def save_counter(self) -> int:
        return self._save_counter

    # -- save -------------------------------------------------------------
    def save(self, file_prefix: str, *, async_write: bool = False) -> str:
        """Write ``<file_prefix>-<counter>/``; returns the path.

        Multi-host: every process calls this; each writes only shards it
        owns. Process 0 writes the index.
        """
        self._save_counter += 1
        path = f"{file_prefix}-{self._save_counter}"
        self.write(path, async_write=async_write)
        return path

    def write(self, path: str, *, async_write: bool = False,
              tier: str = "durable", pipeline_to: str | None = None,
              on_captured=None) -> str:
        """Write a checkpoint directory at ``path``.

        ``tier`` labels the index (recorded as ``index["tier"]``);
        ``pipeline_to`` re-commits the same shards to a second directory
        (tier ``durable``) after the first commit — with ``async_write``
        both commits are pipelined behind training. ``on_captured``, if
        given, is called as ``on_captured(host_arrays, index)`` right
        after the device->host capture (before any file IO) — the hook
        the in-memory snapshot tiers ride.
        """
        # span covers the BLOCKING portion (device->host + commit when
        # sync; device->host + thread handoff when async) — the async
        # file IO reports separately via the checkpoint.commit event.
        # span_id is shared by the save span and every tier commit of
        # this save (local/durable/host — the basename is tier-invariant)
        # so the capture->commit ladder renders as one flow chain in the
        # merged trace (telemetry/trace.py).
        span_id = checkpoint_span_id(path)
        with telemetry.span("checkpoint.save", path=path,
                            async_write=async_write, span_id=span_id):
            return self._write_impl(path, async_write=async_write,
                                    tier=tier, pipeline_to=pipeline_to,
                                    on_captured=on_captured,
                                    span_id=span_id)

    def _capture(self) -> tuple[dict[str, np.ndarray], dict]:
        """Device->host snapshot of the tracked pytree: the shard arrays
        this process owns plus the checkpoint index. The cheap,
        synchronous part of every save — and the whole of a host-tier
        snapshot."""
        flat = _flatten(self._objects)

        # Read each leaf ONCE (ON_READ variables reduce on read — a device
        # computation that must not run twice), then start every
        # device->host transfer before blocking on any
        # (≙ async_checkpoint_helper.py's copy-then-write split).
        vals: dict[str, Any] = {}
        for name, leaf in flat.items():
            val = (leaf.read_value() if isinstance(leaf, DistributedVariable)
                   else leaf)
            vals[name] = val
            if isinstance(val, jax.Array):
                for s in val.addressable_shards:
                    s.data.copy_to_host_async()

        index: dict[str, Any] = {"leaves": {}, "format": 1}
        host_arrays: dict[str, np.ndarray] = {}
        for name, leaf in flat.items():
            arr, meta, offset = self._extract(name, leaf, vals[name])
            index["leaves"][name] = meta
            if arr is not None:
                key = self._fname(name)
                host_arrays[key] = arr
                if offset is not None:
                    host_arrays[key + "::off"] = np.asarray([offset],
                                                            dtype=np.int64)
        return host_arrays, index

    def _proc(self) -> int:
        """Shard-owner id: a single-writer checkpoint is always shard 0
        (the saving process owns everything), whatever this process's
        cluster rank is."""
        return 0 if self._single_writer else jax.process_index()

    def _write_impl(self, path: str, *, async_write: bool,
                    tier: str = "durable", pipeline_to: str | None = None,
                    on_captured=None, span_id: str | None = None) -> str:
        proc = self._proc()
        tmp = f"{path}.tmp.{proc}"
        os.makedirs(tmp, exist_ok=True)
        host_arrays, index = self._capture()
        index["tier"] = tier
        if on_captured is not None:
            on_captured(host_arrays, index)

        def mark_pending():
            with self._pending_lock:
                self._pending_paths.add(path)
                if pipeline_to:
                    self._pending_paths.add(pipeline_to)

        def finish():
            try:
                # fsync BEFORE the rename into place: an OS crash after
                # the rename must not leave a shard whose data pages
                # never hit disk (rename is only atomic for the
                # directory entry).
                with telemetry.span("checkpoint.commit", path=path,
                                    tier=tier, span_id=span_id):
                    shard = os.path.join(tmp, f"shard_{proc}.npz")
                    with open(shard, "wb") as f:
                        np.savez(f, **host_arrays)
                        f.flush()
                        os.fsync(f.fileno())
                    self._commit(tmp, path, index)
                if pipeline_to:
                    # second-tier commit: re-commit the just-committed
                    # local shard into the durable directory through the
                    # same hardened protocol (fresh tmp, barriers,
                    # index-last)
                    with telemetry.span("checkpoint.commit",
                                        path=pipeline_to, tier="durable",
                                        span_id=span_id):
                        tmp2 = f"{pipeline_to}.tmp.{proc}"
                        os.makedirs(tmp2, exist_ok=True)
                        shutil.copy2(os.path.join(path,
                                                  f"shard_{proc}.npz"),
                                     os.path.join(tmp2,
                                                  f"shard_{proc}.npz"))
                        index2 = dict(index)
                        index2["tier"] = "durable"
                        index2.pop("shards", None)
                        self._commit(tmp2, pipeline_to, index2)
            finally:
                with self._pending_lock:
                    self._pending_paths.discard(path)
                    if pipeline_to:
                        self._pending_paths.discard(pipeline_to)

        def finish_async():
            try:
                finish()
            except BaseException as e:   # surfaced on next sync/save/restore
                self._async_error = e

        if async_write:
            # device->host already done above (np arrays); file IO async
            self._join_pending()         # may raise a PRIOR write's
            mark_pending()               # error: mark only after it
            self._async_thread = threading.Thread(target=finish_async,
                                                  daemon=True)
            self._async_thread.start()
        else:
            mark_pending()
            finish()                     # sync path: raise right here
        return path

    def pending_write_paths(self) -> set[str]:
        """Checkpoint directories an in-flight write still commits into
        (rotation must skip these)."""
        with self._pending_lock:
            return set(self._pending_paths)

    def _commit(self, tmp: str, path: str, index: dict):
        """Multi-host commit protocol (≙ checkpoint_management's
        chief-writes-last contract, hardened):

        1. every process renames its shard files into ``path``;
        2. cross-process barrier — no host proceeds until ALL shards are
           in place (TSL coordination service; no-op single-process);
        3. process 0 writes the index — which records every shard file's
           size + crc32 (gathered over the KV store) — to a temp name
           and atomically renames it LAST: the index's existence marks
           the checkpoint complete (``_list_checkpoints`` keys on it,
           and verifies the recorded sizes), so a torn checkpoint is
           never observable;
        4. exit barrier so no process returns (and e.g. starts a restore
           or another save into the same path) before the index exists.

        Chaos site ``checkpoint.commit``: ``raise`` fails the commit,
        ``corrupt`` tears this process's shard AFTER the index lands —
        the exact failure the size/crc records exist to catch.
        """
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        decision = faults.fire("checkpoint.commit", tag=path, exc=OSError,
                               msg=f"injected commit failure for {path}")
        # Per-file integrity record for this process's shards, taken
        # while they are still private to us (pre-rename).
        sums = {f: {"crc32": _crc32_file(os.path.join(tmp, f)),
                    "size": os.path.getsize(os.path.join(tmp, f))}
                for f in os.listdir(tmp)}
        os.makedirs(path, exist_ok=True)
        for f in os.listdir(tmp):
            os.replace(os.path.join(tmp, f), os.path.join(path, f))
        os.rmdir(tmp)
        # fsync the directories the renames mutated: the shard files'
        # DATA is already on disk (fsynced pre-rename), but the new
        # directory entries are not until their parent dirs are synced —
        # the last torn-commit window a host crash could still open.
        _fsync_dir(path)
        _fsync_dir(os.path.dirname(os.path.abspath(path)))
        # Token = basename + abspath hash: two saves into different
        # directories that share a basename (e.g. every Model backup dir
        # is ".../backup") must NOT meet at the same barrier.
        import hashlib
        token = (os.path.basename(path) + "."
                 + hashlib.sha1(os.path.abspath(path).encode())
                 .hexdigest()[:12])
        # Save-counter suffix: a re-save into the SAME path must use
        # fresh KV keys (legacy TSL clients cannot safely re-read
        # deleted-then-recreated keys).
        sums_prefix = f"dtx_ckpt_sums/{token}.{self._save_counter}"
        # single-writer: the commit involves exactly one process — no
        # shard barrier to meet, no peer sums to gather, and the index
        # is ours to write whatever our cluster rank is
        distributed = agent.is_distributed and not self._single_writer
        chief = agent.is_chief or self._single_writer
        if distributed:
            try:
                agent.key_value_set(f"{sums_prefix}/p{agent.process_id}",
                                    json.dumps(sums))
            except Exception:
                pass            # degraded: index carries fewer records
            try:
                agent.barrier(f"ckpt_shards/{token}", timeout_s=600.0)
            except Exception as e:
                # Peer death mid-save (preemption best-effort path): a
                # possibly-incomplete checkpoint beats none. Warn loudly.
                import sys
                print(f"[dtx.checkpoint] WARNING: shard barrier failed "
                      f"({e}); committing possibly-incomplete checkpoint "
                      f"{path}", file=sys.stderr)
        if chief:
            all_sums = dict(sums)
            if distributed:
                # enumerated point reads (every process published before
                # the shard barrier; legacy TSL clients hang on remote
                # GetKeyValueDir, and a dead peer just contributes no
                # record — best-effort by design)
                for i in range(agent.num_processes):
                    v = agent.key_value_try_get(f"{sums_prefix}/p{i}")
                    if v is None:
                        continue
                    try:
                        all_sums.update(json.loads(v))
                    except ValueError:
                        pass
            index["shards"] = all_sums
            tmp_index = os.path.join(path, _INDEX_FILE + ".tmp")
            with open(tmp_index, "w") as f:
                json.dump(index, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp_index, os.path.join(path, _INDEX_FILE))
            _fsync_dir(path)      # the index rename IS the commit point
        if distributed:
            try:
                agent.barrier(f"ckpt_index/{token}", timeout_s=600.0)
            except Exception:
                pass            # exit barrier is best-effort by nature
            if agent.is_chief:
                try:
                    agent.key_value_delete(sums_prefix)
                except Exception:
                    pass
        if decision is not None and decision.action == "corrupt":
            # Torn write AFTER the commit protocol finished: the index
            # says the checkpoint is complete, the storage disagrees.
            shard = os.path.join(path, f"shard_{self._proc()}.npz")
            size = os.path.getsize(shard)
            with open(shard, "rb+") as f:
                f.truncate(max(size - max(size // 4, 1), 0))

    def _join_pending(self):
        if self._async_thread is not None and self._async_thread.is_alive():
            self._async_thread.join()
        if self._async_error is not None:
            err, self._async_error = self._async_error, None
            raise RuntimeError("async checkpoint write failed") from err

    def sync(self):
        """Block until any async write completed (≙ AsyncCheckpoint sync)."""
        self._join_pending()

    @staticmethod
    def _fname(name: str) -> str:
        return re.sub(r"[^A-Za-z0-9_.-]", "__", name)

    def _extract(self, name, leaf, val=None):
        """Returns (host_array_or_None, index_meta) for this process.
        ``val`` is the pre-read leaf value (read exactly once by write)."""
        if isinstance(leaf, DistributedVariable):
            if val is None:
                val = leaf.read_value()
            meta = {"kind": "variable", "shape": list(np.shape(val)),
                    "dtype": str(np.asarray(val).dtype) if np.ndim(val) == 0
                    else str(val.dtype)}
            # mirrored/on-read-reduced: single logical tensor, process 0 owns
            if getattr(val, "sharding", None) is not None and \
                    not val.sharding.is_fully_replicated:
                # sharded: save only addressable rows with their offset
                shards = [(s.index, np.asarray(s.data))
                          for s in val.addressable_shards if s.replica_id == 0]
                meta["kind"] = "sharded_variable"
                meta["slices"] = [self._slice_meta(idx) for idx, _ in shards]
                arr, offset = None, None
                if shards:
                    shards = sorted(
                        shards, key=lambda t: (t[0][0].start or 0))
                    for (ia, _), (ib, _) in zip(shards, shards[1:]):
                        if (ia[0].stop or 0) != (ib[0].start or 0):
                            raise NotImplementedError(
                                f"process owns non-contiguous axis-0 slices "
                                f"of {name!r} ({ia[0]} then {ib[0]}); "
                                f"restore would permute rows silently")
                    arr = np.concatenate(
                        [a for _, a in shards], axis=0) \
                        if len(shards) > 1 else shards[0][1]
                    # This process's global axis-0 offset: restore orders
                    # parts by it (file order is NOT slice order).
                    offset = shards[0][0][0].start or 0
                return arr, meta, offset
            if self._proc() == 0:
                return np.asarray(val), meta, None
            return None, meta, None
        arr = np.asarray(leaf)
        meta = {"kind": "array", "shape": list(arr.shape),
                "dtype": str(arr.dtype)}
        return (arr if self._proc() == 0 else None), meta, None

    @staticmethod
    def _slice_meta(index) -> list:
        out = []
        for sl in index:
            out.append([sl.start if sl.start is not None else 0,
                        sl.stop if sl.stop is not None else -1])
        return out

    # -- restore ----------------------------------------------------------
    def restore(self, path: str) -> dict:
        """Restore from ``path``. DistributedVariables are assigned in
        place (re-placed with their sharding); plain leaves are returned in
        the result pytree."""
        with telemetry.span("checkpoint.restore", path=path,
                            span_id=checkpoint_span_id(path)):
            return self._restore_impl(path)

    def _restore_impl(self, path: str) -> dict:
        self._join_pending()
        index_path = os.path.join(path, _INDEX_FILE)
        if not os.path.exists(index_path):
            raise FileNotFoundError(f"No checkpoint index at {path}")
        with open(index_path) as f:
            index = json.load(f)
        # Integrity first (size is cheap, crc reads the file the load
        # below reads anyway): a truncated/corrupt shard must surface as
        # CheckpointCorruptError, not an obscure zipfile traceback.
        # Pre-checksum checkpoints (no "shards" record) skip this.
        for f_name, meta in index.get("shards", {}).items():
            fpath = os.path.join(path, f_name)
            if not os.path.exists(fpath):
                raise CheckpointCorruptError(
                    f"checkpoint {path} is missing shard {f_name}")
            size = os.path.getsize(fpath)
            if size != meta.get("size"):
                raise CheckpointCorruptError(
                    f"shard {f_name} in {path} is {size} bytes, index "
                    f"records {meta.get('size')} (torn write?)")
            if "crc32" in meta and _crc32_file(fpath) != meta["crc32"]:
                raise CheckpointCorruptError(
                    f"shard {f_name} in {path} fails its crc32 "
                    f"(corrupt data)")
        shards = {}
        shard_pat = re.compile(r"shard_(\d+)\.npz$")
        for f_name in sorted(os.listdir(path),
                             key=lambda n: (int(shard_pat.match(n).group(1))
                                            if shard_pat.match(n) else -1)):
            if shard_pat.match(f_name):
                shards[f_name] = np.load(os.path.join(path, f_name))
        return self._apply_shards(shards, index, source=path)

    def _apply_shards(self, shards: Mapping[str, Any], index: dict,
                      source: str) -> dict:
        """Reassemble leaves from shard mappings (npz files or plain
        dicts of arrays) and assign/return them.

        Topology-elastic by construction (reshard-on-load): parts are
        stitched in *slice* order using the recorded axis-0 offsets and
        verified contiguous against the leaf's logical shape, so a
        checkpoint written by N processes restores onto M — each leaf's
        ``assign`` re-places the full tensor under the CURRENT sharding.
        """
        def lookup(name, want_shape=None):
            key = self._fname(name)
            parts = []
            for shard in shards.values():
                if key in shard:
                    off = (int(shard[key + "::off"][0])
                           if key + "::off" in shard else 0)
                    parts.append((off, shard[key]))
            if not parts:
                raise KeyError(f"Leaf {name!r} missing from "
                               f"checkpoint {source}")
            parts.sort(key=lambda t: t[0])   # slice order, not file order
            if want_shape is not None and len(parts) > 1:
                # contiguity check: a missing slice must surface as a
                # corrupt checkpoint, not a silently mis-stitched tensor
                pos = 0
                for off, arr in parts:
                    if off != pos:
                        raise CheckpointCorruptError(
                            f"leaf {name!r} in {source}: slice at axis-0 "
                            f"offset {off} does not abut previous end "
                            f"{pos} (missing shard part?)")
                    pos += np.shape(arr)[0]
                if pos != want_shape[0]:
                    raise CheckpointCorruptError(
                        f"leaf {name!r} in {source}: stitched rows {pos} "
                        f"!= logical rows {want_shape[0]}")
            return [a for _, a in parts]

        flat = _flatten(self._objects)
        restored = {}
        for name, leaf in flat.items():
            if isinstance(leaf, DistributedVariable):
                meta = index["leaves"].get(name, {})
                if meta.get("kind") == "sharded_variable":
                    parts = lookup(name, want_shape=meta.get("shape"))
                    full = np.concatenate(parts, axis=0) if len(parts) > 1 \
                        else parts[0]
                else:
                    full = lookup(name)[0]
                leaf.assign(full.reshape(leaf.shape) if full.shape !=
                            tuple(leaf.shape) else full)
                restored[name] = leaf
            else:
                restored[name] = lookup(name)[0]
        return restored

    def restore_from_parts(self, parts, index: dict) -> dict:
        """Restore from in-memory snapshot parts (the host/peer tiers):
        ``parts`` is an iterable of objects with an ``arrays`` mapping
        (e.g. :class:`~distributed_tensorflow_tpu.checkpoint.
        peer_snapshot.HostSnapshot`) — one per original shard owner.
        Same reassembly (and reshard-on-load) semantics as a disk
        restore, no file IO."""
        self._join_pending()
        with telemetry.span("checkpoint.restore", path="<memory>"):
            shards = {f"mem_{i}": p.arrays for i, p in enumerate(parts)}
            return self._apply_shards(shards, index,
                                      source="<memory snapshot>")

    def read(self, path: str) -> dict:
        return self.restore(path)

    def restore_into(self, path: str) -> dict:
        """Restore from ``path`` AND update the tracked objects in
        place: DistributedVariables are assigned (as in
        :meth:`restore`), and plain-array leaves are replaced inside the
        tracked pytrees, so code holding this ``Checkpoint`` (e.g. a
        SidecarEvaluator's eval_fn) sees the restored state without
        private-attribute surgery. Returns the flat restored mapping."""
        flat_restored = self.restore(path)

        def rebuild(obj, prefix):
            if isinstance(obj, DistributedVariable) or hasattr(obj,
                                                               "assign"):
                return obj                 # assigned in place already
            if isinstance(obj, Mapping):
                return type(obj)(
                    {k: rebuild(obj[k],
                                f"{prefix}/{k}" if prefix else str(k))
                     for k in obj})
            if isinstance(obj, (list, tuple)):
                vals = [rebuild(v, f"{prefix}/{i}" if prefix else str(i))
                        for i, v in enumerate(obj)]
                return type(obj)(vals) if not hasattr(obj, "_fields") \
                    else type(obj)(*vals)
            if (hasattr(obj, "__dict__")
                    and hasattr(obj, "_checkpoint_children")):
                for k, child in obj._checkpoint_children().items():
                    newc = rebuild(child,
                                   f"{prefix}/{k}" if prefix else k)
                    if newc is not child:
                        if k in vars(obj):
                            setattr(obj, k, newc)
                        else:
                            raise ValueError(
                                f"restore_into cannot write restored "
                                f"child {k!r} back into "
                                f"{type(obj).__name__}: "
                                f"_checkpoint_children keys must be "
                                f"attributes (or use .assign leaves)")
                return obj
            return flat_restored.get(prefix or "value", obj)

        for name in list(self._objects):
            self._objects[name] = rebuild(self._objects[name], name)
        return flat_restored

    def get(self, name: str):
        """Public access to a tracked object by constructor kwarg name."""
        return self._objects[name]


class CheckpointManager:
    """Rotation + latest-tracking (≙ checkpoint_management.py:519).

    ``max_to_keep`` oldest-first deletion, ``keep_checkpoint_every_n_hours``
    pinning, ``restore_or_initialize`` convenience, and step-interval
    gating via ``save(checkpoint_number, check_interval)``.

    Fast-recovery tiers (all optional):

    - ``local_dir`` — node-local fast scratch: saves commit here first
      (tier ``local``) and the durable re-commit is pipelined behind
      training; ``latest_checkpoint`` prefers the freshest intact tier.
      Saves default to ``async_write=True`` when a local tier exists.
    - ``snapshot_store`` — a :class:`~distributed_tensorflow_tpu.
      checkpoint.peer_snapshot.SnapshotStore`: every save also captures
      a host-RAM snapshot and ring-replicates it to a peer
      (:meth:`snapshot` takes extra memory-only snapshots between disk
      saves). :meth:`restore_latest` then restores down the ladder
      host > peer > local > durable, emitting a
      ``recovery.restore_tier`` telemetry event.
    """

    def __init__(self, checkpoint: Checkpoint, directory: str,
                 max_to_keep: int = 5,
                 keep_checkpoint_every_n_hours: float | None = None,
                 checkpoint_name: str = "ckpt",
                 local_dir: str | None = None,
                 snapshot_store=None,
                 exchange_timeout_s: float = 30.0):
        self.checkpoint = checkpoint
        self.directory = directory
        self.local_dir = local_dir
        self.snapshot_store = snapshot_store
        self._exchange_timeout_s = exchange_timeout_s
        self.max_to_keep = max_to_keep
        self.keep_every_s = (keep_checkpoint_every_n_hours * 3600
                             if keep_checkpoint_every_n_hours else None)
        self._name = checkpoint_name
        self._kept_pinned: list[str] = []
        # Pin clock starts NOW (≙ the reference's last_preserved_timestamp,
        # checkpoint_management.py:519): the first sweep must NOT pin —
        # a 0.0 epoch origin made `now - last_pin >= keep_every_s` true
        # immediately, permanently pinning the first rotated checkpoint.
        self._last_pin_time = time.time()
        os.makedirs(directory, exist_ok=True)
        if local_dir:
            os.makedirs(local_dir, exist_ok=True)
        self._load_meta()

    @property
    def _prefix(self) -> str:
        return os.path.join(self.directory, self._name)

    @property
    def _local_prefix(self) -> str | None:
        return (os.path.join(self.local_dir, self._name)
                if self.local_dir else None)

    # Pin state persists across manager restarts (≙ the reference keeping
    # last_preserved_timestamp in the CheckpointState proto).
    @property
    def _meta_path(self) -> str:
        return os.path.join(self.directory, f"{self._name}.manager.json")

    def _load_meta(self):
        if not os.path.exists(self._meta_path):
            return
        try:
            with open(self._meta_path) as f:
                meta = json.load(f)
            self._last_pin_time = float(meta.get("last_pin_time",
                                                 self._last_pin_time))
            # Pins are persisted as basenames so a manager restarted from
            # a different cwd (or via a different path to the same dir)
            # keeps them out of rotation.
            self._kept_pinned = [
                os.path.join(self.directory, os.path.basename(p))
                for p in meta.get("pinned", [])
                if os.path.isdir(os.path.join(self.directory,
                                              os.path.basename(p)))]
        except (ValueError, OSError):
            pass

    def _save_meta(self):
        if jax.process_index() != 0:
            return
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"last_pin_time": self._last_pin_time,
                       "pinned": [os.path.basename(p)
                                  for p in self._kept_pinned]}, f)
        os.replace(tmp, self._meta_path)

    @staticmethod
    def _is_complete(full: str) -> bool:
        """A checkpoint counts only if its index exists AND every shard
        the index records is present at its recorded size — so a torn
        checkpoint (truncated shard, interrupted commit) is skipped by
        rotation/latest rather than handed to restore. Size-only here
        (stat, not a read); restore does the full crc verification."""
        idx = os.path.join(full, _INDEX_FILE)
        if not os.path.exists(idx):
            return False
        try:
            with open(idx) as f:
                index = json.load(f)
        except (ValueError, OSError):
            return False
        for f_name, meta in index.get("shards", {}).items():
            try:
                if os.path.getsize(os.path.join(full, f_name)) != \
                        meta.get("size"):
                    return False
            except OSError:
                return False
        return True

    def _list_checkpoints(self, directory: str | None = None
                          ) -> list[tuple[int, str]]:
        directory = directory or self.directory
        pat = re.compile(re.escape(self._name) + r"-(\d+)$")
        out = []
        try:
            entries = os.listdir(directory)
        except OSError:
            return []
        for d in entries:
            m = pat.match(d)
            full = os.path.join(directory, d)
            if m and os.path.isdir(full) and self._is_complete(full):
                out.append((int(m.group(1)), full))
        return sorted(out)

    def _disk_best(self, at_step: int | None = None
                   ) -> "tuple[int, str, str] | None":
        """(step, path, tier) of the freshest intact disk checkpoint
        across tiers; the warmer (local) tier wins step ties. With
        ``at_step`` only that EXACT step qualifies (pin-restore)."""
        cands = []
        for tier, d in (("local", self.local_dir),
                        ("durable", self.directory)):
            if not d:
                continue
            cks = self._list_checkpoints(d)
            if at_step is not None:
                cks = [(n, p) for n, p in cks if n == at_step]
            if cks:
                n, p = cks[-1]
                cands.append((n, 1 if tier == "local" else 0, p, tier))
        if not cands:
            return None
        n, _, p, tier = max(cands)
        return n, p, tier

    @property
    def latest_checkpoint(self) -> str | None:
        best = self._disk_best()
        return best[1] if best else None

    @property
    def checkpoints(self) -> list[str]:
        return [p for _, p in self._list_checkpoints()]

    def save(self, checkpoint_number: int | None = None, *,
             async_write: bool | None = None) -> str:
        """Tier-pipelined save. With a ``local_dir`` the commit lands in
        the local tier first and the durable re-commit is pipelined
        (``async_write`` defaults to True); with a ``snapshot_store``
        the device->host capture is also retained as a host snapshot and
        ring-replicated to a peer before any file IO."""
        if checkpoint_number is not None:
            self.checkpoint._save_counter = checkpoint_number - 1
        if async_write is None:
            async_write = self.local_dir is not None
        self.checkpoint._save_counter += 1
        number = self.checkpoint._save_counter
        on_captured = None
        if self.snapshot_store is not None:
            def on_captured(host_arrays, index):
                self._commit_snapshot(host_arrays, dict(index), number)
        if self.local_dir:
            path = self.checkpoint.write(
                f"{self._local_prefix}-{number}", async_write=async_write,
                tier="local", pipeline_to=f"{self._prefix}-{number}",
                on_captured=on_captured)
        else:
            path = self.checkpoint.write(
                f"{self._prefix}-{number}", async_write=async_write,
                on_captured=on_captured)
        self._sweep()
        return path

    def snapshot(self, step: int):
        """Memory-only host snapshot (+ ring replica exchange): the
        cheap high-frequency tier between disk saves. Collective when
        distributed — every process must snapshot the same steps."""
        if self.snapshot_store is None:
            raise ValueError("CheckpointManager has no snapshot_store")
        host_arrays, index = self.checkpoint._capture()
        return self._commit_snapshot(host_arrays, index, step)

    def _commit_snapshot(self, host_arrays, index, step: int):
        from distributed_tensorflow_tpu.checkpoint import (
            peer_snapshot as _ps)
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        index = dict(index)
        index["tier"] = "host"
        with telemetry.span("checkpoint.commit", tier="host", step=step,
                            span_id=checkpoint_span_id(
                                f"{self._name}-{step}")):
            # copy: the capture aliases live host buffers for plain-np
            # leaves; a retained snapshot must not track future updates
            snap = _ps.HostSnapshot(
                owner=agent.process_id, step=int(step),
                world=agent.num_processes, index=index,
                arrays={k: np.array(v, copy=True)
                        for k, v in host_arrays.items()})
            self.snapshot_store.put(snap)
            _ps.exchange(self.snapshot_store, snap, agent,
                         timeout_s=self._exchange_timeout_s)
        return snap

    def _sweep(self):
        # Never delete a directory an in-flight async write still
        # commits into: the local->durable pipeline copies out of the
        # local tier AFTER it becomes listable, so rotation racing the
        # commit thread would tear the durable re-commit.
        pending = self.checkpoint.pending_write_paths()
        # Pinned checkpoints are permanently out of rotation: they neither
        # count toward max_to_keep nor get deleted.
        cks = [(n, p) for n, p in self._list_checkpoints()
               if p not in self._kept_pinned and p not in pending]
        now = time.time()
        changed = False
        while len(cks) > self.max_to_keep:
            num, path = cks.pop(0)
            if self.keep_every_s is not None and \
                    now - self._last_pin_time >= self.keep_every_s:
                self._kept_pinned.append(path)
                self._last_pin_time = now
                changed = True
                continue
            if jax.process_index() == 0:
                shutil.rmtree(path, ignore_errors=True)
        if changed:
            self._save_meta()
        if self.local_dir:
            locals_ = [(n, p)
                       for n, p in self._list_checkpoints(self.local_dir)
                       if p not in pending]
            while len(locals_) > self.max_to_keep:
                _, path = locals_.pop(0)
                if jax.process_index() == 0:
                    shutil.rmtree(path, ignore_errors=True)

    def restore_or_initialize(self) -> str | None:
        """≙ CheckpointManager.restore_or_initialize: restore latest if one
        exists, else None (caller keeps fresh init)."""
        latest = self.latest_checkpoint
        if latest is not None:
            self.checkpoint.restore(latest)
            m = re.search(r"-(\d+)$", latest)
            if m:
                self.checkpoint._save_counter = int(m.group(1))
        return latest

    #: warmth rank of each restore tier (lower = warmer = faster)
    _TIER_RANK = {"host": 0, "peer": 0, "memory": 0, "local": 1,
                  "durable": 2, "none": 3}

    def _restore_pinned(self, step: int
                        ) -> "tuple[str, int, dict]":
        """Pin-restore the EXACT snapshot ``step`` from disk — the
        rollback primitive. Disk tiers only (memory snapshots hold the
        freshest state, which is precisely what rollback must not
        get), no peer negotiation. Raises loudly rather than silently
        restoring a different version: ``CheckpointCorruptError`` when
        the pinned step's directory exists but is torn,
        ``FileNotFoundError`` when it was pruned / never written."""
        disk = self._disk_best(at_step=step)
        if disk is None:
            seen = []
            for d in (self.local_dir, self.directory):
                if not d:
                    continue
                full = os.path.join(d, f"{self._name}-{step}")
                if os.path.isdir(full):
                    raise CheckpointCorruptError(
                        f"pinned step {step}: {full} exists but is "
                        f"torn/incomplete — refusing to fall back to "
                        f"a different version")
                seen.append(d)
            raise FileNotFoundError(
                f"pinned step {step}: no intact {self._name}-{step} "
                f"under {seen} (pruned by rotation?)")
        got, path, tier = disk
        restored = self.checkpoint.restore(path)
        telemetry.event("recovery.restore_tier", tier=tier, step=got,
                        pinned=True)
        self.checkpoint._save_counter = int(got)
        return tier, int(got), restored

    def restore_latest(self, *, timeout_s: float = 60.0,
                       at_step: int | None = None
                       ) -> "tuple[str, int, dict] | None":
        """Restore down the recovery ladder: own host snapshot > peer
        replica (fetched over the coordination KV) > local disk >
        durable disk. Collective when a ``snapshot_store`` is present
        and the job is distributed: every process must call it exactly
        ONCE per cluster generation (the negotiation keys are
        generation-namespaced and write-once — legacy TSL clients
        cannot safely re-read overwritten keys). Emits a
        ``recovery.restore_tier``
        telemetry event recording the chosen tier, the freshest step
        each tier had, and ``best_available`` — the warmest tier that
        held the freshest state (chaos_sweep gates chosen == best).

        Returns ``(tier, step, flat_restored)`` or ``None`` when there
        is nothing anywhere to restore.

        ``at_step`` PINS the restore to one exact snapshot step (the
        rollback path): disk tiers only, no negotiation, and a torn or
        pruned pinned step raises loudly instead of silently handing
        back a different version. Freshest-intact semantics are
        completely unchanged when ``at_step`` is None.
        """
        if at_step is not None:
            return self._restore_pinned(int(at_step))
        from distributed_tensorflow_tpu.checkpoint import (
            peer_snapshot as _ps)
        from distributed_tensorflow_tpu.cluster import elastic
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        disk = self._disk_best()
        decision = None
        if self.snapshot_store is not None:
            self.snapshot_store.load_surviving()
            try:
                decision = _ps.negotiate(self.snapshot_store, agent, disk,
                                         timeout_s=timeout_s)
            except Exception:
                decision = None          # negotiation failed: disk path
        tier, step, restored, old_world = None, None, None, None
        mem_step = None
        if decision is not None:
            mem_step = (decision.get("step")
                        if decision.get("source") == "memory"
                        else decision.get("mem_step"))
        if decision is not None and decision.get("source") == "memory":
            try:
                remote = _ps.any_fetched_remotely(self.snapshot_store,
                                                  decision)
                parts = _ps.fetch_parts(self.snapshot_store, agent,
                                        decision, timeout_s=timeout_s)
                index = parts[0].index
                restored = self.checkpoint.restore_from_parts(parts, index)
                tier = "peer" if remote else "host"
                step = int(decision["step"])
                old_world = int(decision.get("world", len(parts)))
            except Exception:
                restored = None          # memory tier failed: disk path
        if restored is None:
            if decision is not None and decision.get("source") == "disk":
                step, path, tier = (int(decision["step"]),
                                    decision["path"], decision["tier"])
            elif disk is not None:
                step, path, tier = disk
            else:
                path = None
            if path is not None:
                restored = self.checkpoint.restore(path)
                old_world = len([f for f in os.listdir(path)
                                 if re.match(r"shard_\d+\.npz$", f)])
            else:
                tier, step = None, None
        available = {
            "memory": mem_step,
            "local": (self._list_checkpoints(self.local_dir)[-1][0]
                      if self.local_dir
                      and self._list_checkpoints(self.local_dir)
                      else None),
            "durable": (self._list_checkpoints()[-1][0]
                        if self._list_checkpoints() else None),
        }
        best_step = max((s for s in available.values() if s is not None),
                        default=None)
        best_available = "none" if best_step is None else min(
            (t for t, s in available.items() if s == best_step),
            key=lambda t: self._TIER_RANK[t])
        telemetry.event(
            "recovery.restore_tier",
            tier=tier or "none", step=step,
            generation=elastic.generation(),
            world=agent.num_processes, old_world=old_world,
            resharded=(old_world is not None
                       and old_world != agent.num_processes),
            available=available, best_available=best_available)
        if restored is None:
            return None
        self.checkpoint._save_counter = int(step)
        return tier, int(step), restored


def latest_checkpoint(directory: str, name: str = "ckpt",
                      at_step: int | None = None) -> str | None:
    """Module-level convenience (≙ tf.train.latest_checkpoint). With
    ``at_step`` returns the EXACT pinned step's path — raising
    ``CheckpointCorruptError`` (torn) or ``FileNotFoundError``
    (pruned/absent) instead of silently yielding a different one."""
    mgr = CheckpointManager(Checkpoint(), directory, checkpoint_name=name)
    if at_step is None:
        return mgr.latest_checkpoint
    best = mgr._disk_best(at_step=int(at_step))
    if best is None:
        full = os.path.join(directory, f"{name}-{int(at_step)}")
        if os.path.isdir(full):
            raise CheckpointCorruptError(
                f"pinned step {at_step}: {full} exists but is "
                f"torn/incomplete")
        raise FileNotFoundError(
            f"pinned step {at_step}: no intact {name}-{at_step} under "
            f"{directory} (pruned by rotation?)")
    return best[1]
