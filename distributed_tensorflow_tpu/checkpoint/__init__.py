"""Checkpointing: object save/restore, rotation, preemption safety.

TPU-native counterpart of the reference's checkpoint stack (SURVEY.md §5.4):
tf.train.Checkpoint / CheckpointManager / PreemptionCheckpointHandler.
"""

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpoint,
    CheckpointCorruptError,
    CheckpointManager,
    latest_checkpoint,
)
from distributed_tensorflow_tpu.checkpoint.delta import (
    DeltaChainError,
    DeltaSnapshotStore,
    states_equal,
)
from distributed_tensorflow_tpu.checkpoint.peer_snapshot import (
    HostSnapshot,
    SnapshotStore,
)
from distributed_tensorflow_tpu.checkpoint.failure_handling import (
    EXIT_PREEMPTED,
    PreemptionCheckpointHandler,
    TerminationConfig,
    TrainingPreempted,
)
from distributed_tensorflow_tpu.checkpoint.preemption_watcher import (
    PreemptionWatcher,
)
