"""Preemption-safe coordinated checkpointing.

TPU-native counterpart of tensorflow/python/distribute/failure_handling/
failure_handling.py (SURVEY.md §2.5, §3.5):

- ``TerminationConfig``            ≙ failure_handling.py:75-244 (platform
  matrix: Borg/GCE x CPU/GPU/TPU). Here the platform signal set collapses to
  SIGTERM plus the GCE/TPU-VM maintenance-event file hook.
- ``PreemptionCheckpointHandler``  ≙ failure_handling.py:337: wraps the
  train loop; on a preemption signal every process agrees on a "step to
  save at", checkpoints there, and exits (or counts down a grace period).

The cross-process agreement protocol in the reference rides the
coordination-service KV store plus a step-count gather
(_watch_step_to_save_key, failure_handling.py:1222). Here it rides the
same KV store through cluster/coordination.py: signal key -> background
gather of step counts -> run-to-max -> confirm rounds (see
``_agree_on_preemption``/``_confirm_stop_step``). Single-process
degenerates to a local flag; the multi-host path is exercised by
tests/test_multi_process.py.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Callable

import jax

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpoint,
    CheckpointManager,
)
from distributed_tensorflow_tpu.cluster import elastic
from distributed_tensorflow_tpu.resilience import faults

#: Process exit code meaning "preempted after a clean checkpoint —
#: restart me" (≙ the reference's restart-the-job convention). The
#: recovery supervisor classifies this code as a preemption, not a crash.
EXIT_PREEMPTED = 42


class TrainingPreempted(RuntimeError):
    """Raised (instead of exiting the process from library code) by
    :class:`PreemptionCheckpointHandler` in ``restart`` exit mode, after
    the preemption checkpoint has committed. The owner of the training
    loop — an elastic worker shell or the recovery supervisor's spawned
    task — catches it and tears down for restart, typically exiting
    with :data:`EXIT_PREEMPTED`."""


@dataclasses.dataclass
class TerminationConfig:
    """≙ failure_handling.py:75 ``TerminationConfig``.

    ``exit_mode`` selects what happens once the preemption checkpoint is
    committed and no ``exit_fn`` is injected:

    - ``"exit"`` (default): raise ``SystemExit(EXIT_PREEMPTED)`` so the
      platform restarts the job — the reference's behavior;
    - ``"restart"``: raise :class:`TrainingPreempted` instead, keeping
      process teardown OUT of library code — the mode elastic/supervised
      jobs use (``for_platform`` picks it automatically when a recovery
      supervisor owns this process).
    """

    termination_watcher_fn: Callable[[], bool] | None = None
    exit_fn: Callable[[], None] | None = None
    grace_period: float = 0.0
    save_fn: Callable[[], None] | None = None
    exit_mode: str = "exit"

    def __post_init__(self):
        if self.exit_mode not in ("exit", "restart"):
            raise ValueError(f"exit_mode must be 'exit' or 'restart', "
                             f"got {self.exit_mode!r}")

    @classmethod
    def for_platform(cls) -> "TerminationConfig":
        """Platform sniffing (≙ failure_handling.py:245): on GCE/TPU-VM,
        watch the maintenance-event metadata; default is signal-only.
        Under a recovery supervisor the exit mode is ``restart``."""
        watcher = None
        event_file = os.environ.get("DTX_MAINTENANCE_EVENT_FILE")
        if event_file:
            def watcher() -> bool:  # noqa: F811
                try:
                    with open(event_file) as f:
                        return "TERMINATE" in f.read().upper()
                except OSError:
                    return False
        return cls(termination_watcher_fn=watcher,
                   exit_mode="restart" if elastic.under_supervisor()
                   else "exit")


class PreemptionCheckpointHandler:
    """Wraps a training loop with preemption-triggered checkpointing.

    Usage (≙ failure_handling.py:805 ``run``):

        handler = PreemptionCheckpointHandler(manager)
        for _ in range(steps):
            handler.run(train_step_fn)   # runs fn; checkpoints+exits on
                                         # preemption at a step boundary
    """

    def __init__(self, checkpoint_manager: CheckpointManager,
                 termination_config: TerminationConfig | None = None,
                 watch_interval: float = 1.0):
        self._manager = checkpoint_manager
        self._config = termination_config or TerminationConfig.for_platform()
        self._received = threading.Event()
        self._step = 0
        self._run_count_restored = 0
        self._exited = False
        self._save_at: int | None = None
        self._sync_thread: threading.Thread | None = None
        self._signal_poller: threading.Thread | None = None
        self._poller: threading.Thread | None = None
        # Job-scoped keys: shared by all processes of this job (same
        # checkpoint dir — hashed abspath, so two jobs whose directories
        # share a basename never cross-signal), distinct across jobs.
        import hashlib
        absdir = os.path.abspath(checkpoint_manager.directory)
        job = (os.path.basename(absdir) + "."
               + hashlib.sha1(absdir.encode()).hexdigest()[:12])
        self._SIGNAL_KEY = f"dtx_preemption/{job}/signal"
        self._STEPS_PREFIX = f"dtx_preemption/{job}/steps"
        self._GATHER_BARRIER = f"dtx_preemption/{job}/gather"
        self._CONFIRM_PREFIX = f"dtx_preemption/{job}/confirm"
        self._confirm_round = 0
        self._sync_error: BaseException | None = None
        self._grace_deadline: float | None = None
        self._finalizing = False
        self._sigterm_handler = None
        self._prev_sigterm = None

        # restore first (≙ failure_handling.py:647 restore-on-init)
        latest = self._manager.restore_or_initialize()
        if latest is not None:
            self._run_count_restored = self._manager.checkpoint.save_counter

        self._install_signal_handler()
        if self._config.termination_watcher_fn is not None:
            self._poller = threading.Thread(target=self._poll, daemon=True)
            self._poller.start()
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        if coordination_service().is_distributed:
            self._start_signal_poller()

    # -- signal plumbing ---------------------------------------------------
    def _install_signal_handler(self):
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def handler(signum, frame):
                self._received.set()
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, handler)
            # kept for _restore_signal_handler(): stacked handlers must
            # unwind LIFO without leaking across handler lifetimes (the
            # PreemptionWatcher.stop() discipline)
            self._sigterm_handler = handler
            self._prev_sigterm = prev
        except (ValueError, OSError):
            pass  # non-main thread / restricted env

    def _restore_signal_handler(self):
        """Put back the SIGTERM handler that was installed before this
        handler (only if ours is still the current one — an out-of-order
        teardown must not break a newer handler's chain)."""
        if (self._sigterm_handler is None
                or threading.current_thread()
                is not threading.main_thread()):
            return
        try:
            if signal.getsignal(signal.SIGTERM) is self._sigterm_handler:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
                self._sigterm_handler = None
        except (ValueError, OSError):
            pass

    def _poll(self):
        while not self._received.is_set():
            try:
                if self._config.termination_watcher_fn():
                    self._received.set()
                    return
            except Exception:
                pass
            time.sleep(1.0)

    # -- public API --------------------------------------------------------
    @property
    def total_run_calls(self) -> int:
        """≙ PreemptionCheckpointHandler.total_run_calls: steps run across
        all incarnations (restored + this process)."""
        return self._step

    def watch_preemption(self):
        """Manually mark a preemption notice (tests/fault injection)."""
        self._received.set()

    def finalize(self):
        """Call after the training loop (on every process): if a
        preemption was signalled but the agreed save step was never
        reached (the loop ran out first — e.g. the signal landed on the
        last step), checkpoint NOW so the progress isn't lost. No-op
        otherwise. Either way the SIGTERM handler installed at
        construction is restored (LIFO unwind, the way
        ``PreemptionWatcher.stop()`` already does) — the training loop
        is over, so this handler's watch is too."""
        try:
            self._finalize_impl()
        finally:
            self._restore_signal_handler()

    def _finalize_impl(self):
        if self._exited:
            return
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        # a peer may have signalled after our last in-loop poll
        if (not self._received.is_set() and agent.is_distributed
                and agent.key_value_try_get(self._SIGNAL_KEY) is not None):
            self._received.set()
        if not self._received.is_set():
            return
        # publish our signal/steps + start the sync thread if the signal
        # arrived after the last step's check, then wait it out so its
        # `_save_at = max + 2` cannot overwrite the override below
        self._agree_on_preemption()
        if self._sync_thread is not None and self._sync_thread.is_alive():
            self._sync_thread.join(timeout=600)
        self._save_at = self._step          # save at wherever we stopped
        # Finalize mode: this process CANNOT step further (its loop is
        # over). The confirm protocol must not send it back to "run to
        # the raised target" — it publishes its step as final and loops
        # confirm rounds until peers converge, then saves, so the
        # committed checkpoint always contains this host's shards.
        self._finalizing = True
        self._check_preemption_and_maybe_checkpoint()

    def run(self, distributed_train_fn: Callable, *args, **kwargs):
        """Run one step, then checkpoint-and-exit if preemption was
        signalled (≙ failure_handling.py:805/:1082)."""
        result = distributed_train_fn(*args, **kwargs)
        self._step += 1
        # Chaos site: a scheduled synthetic preemption notice, delivered
        # exactly as a platform SIGTERM would be (the active() guard
        # keeps jax.process_index() off the disabled-path per-step cost).
        if faults.active() and faults.fire(
                "preemption.signal", tag=jax.process_index()) is not None:
            self._received.set()
        self._check_preemption_and_maybe_checkpoint()
        return result

    def _start_signal_poller(self):
        """Multi-process only: a daemon thread that notices a PEER's
        preemption signal via the coordination KV store (≙ the reference's
        _watch_step_to_save_key thread, failure_handling.py:1222) without
        any per-step RPC on the training path."""
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()

        def poll():
            while not self._received.is_set() and not self._exited:
                if agent.key_value_try_get(self._SIGNAL_KEY) is not None:
                    self._received.set()
                    return
                time.sleep(0.1)

        self._signal_poller = threading.Thread(target=poll, daemon=True)
        self._signal_poller.start()

    def _agree_on_preemption(self) -> int | None:
        """Cross-process agreement on the step to save at (≙ the
        reference's gather-run-counts-then-run-to-max protocol,
        failure_handling.py:1222):

        1. the signalled process sets a job-wide SIGNAL key; peers notice
           via their poller threads (no per-step RPC);
        2. every process publishes its current step and joins a barrier
           **on a background thread** — the main loop keeps stepping, so
           in-flight SPMD collectives keep completing and the agreement
           can never deadlock against the data plane;
        3. save_at = max(published steps) + margin; every process runs to
           exactly that step and checkpoints there.

        Returns the agreed step, or None while agreement is pending.
        Single-process degenerates to "save at the current step, now".
        """
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        if not self._received.is_set():
            return self._save_at
        if not agent.is_distributed:
            if self._save_at is None:
                self._save_at = self._step
            return self._save_at
        if self._sync_thread is None:
            try:
                agent.key_value_set(self._SIGNAL_KEY, "1",
                                    allow_overwrite=False)
            except Exception:
                pass                       # a peer signalled first — fine

            def sync():
                try:
                    agent.key_value_set(
                        f"{self._STEPS_PREFIX}/p{agent.process_id}",
                        str(self._step))
                    agent.barrier(self._GATHER_BARRIER, timeout_s=600)
                    # enumerated point reads, not a directory listing:
                    # every process published before the barrier, and
                    # point gets work on every client vintage (legacy
                    # TSL clients hang on remote GetKeyValueDir)
                    steps = [int(agent.key_value_get(
                        f"{self._STEPS_PREFIX}/p{i}", timeout_s=60))
                        for i in range(agent.num_processes)]
                    # margin covers steps taken while the barrier settled
                    self._save_at = max(steps) + 2
                except BaseException as e:
                    # A peer died mid-agreement (the very case preemption
                    # handling exists for): degrade to a best-effort local
                    # save at the next step instead of swallowing the
                    # signal forever.
                    self._sync_error = e
                    self._save_at = self._step + 1

            self._sync_thread = threading.Thread(target=sync, daemon=True)
            self._sync_thread.start()
        return self._save_at

    def _confirm_stop_step(self, save_at: int) -> bool:
        """Phase 2 of the agreement: every process publishes the step it
        actually stopped at and all confirm equality. A process that ran
        past ``save_at`` before noticing (RPC latency beat the +2 margin)
        raises the target to the max, everyone catches up, and the round
        repeats — so the committed checkpoint's shards all come from the
        SAME step. Runs on the main thread; a blocked process has already
        enqueued all its steps, so peers' in-flight collectives complete.

        A process in finalize mode (its loop is over — it cannot step)
        publishes its step with a ``!`` final marker. A round also
        converges when EVERY entry is final-marked: no host can advance,
        so all save now at a common checkpoint number (max of the
        published steps) — every host contributes shards rather than a
        laggard silently dropping out while peers block on the shard
        barrier.

        Returns True when this process should save now.
        """
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        agent = coordination_service()
        if not agent.is_distributed or self._sync_error is not None:
            return True
        del save_at
        while True:
            r = self._confirm_round
            try:
                mark = "!" if self._finalizing else ""
                agent.key_value_set(
                    f"{self._CONFIRM_PREFIX}{r}/p{agent.process_id}",
                    f"{self._step}{mark}")
                agent.barrier(f"{self._CONFIRM_PREFIX}{r}/barrier",
                              timeout_s=600)
                # enumerated point reads (see sync() above)
                entries = [agent.key_value_get(
                    f"{self._CONFIRM_PREFIX}{r}/p{i}",
                    timeout_s=60).decode()
                    for i in range(agent.num_processes)]
                steps = [int(e.rstrip("!")) for e in entries]
                final = max(steps)
                # Convergence when no more catching-up is possible:
                # every process still BELOW the target has declared its
                # loop over. (Processes at the target never need to
                # advance, final-marked or not.)
                blocked = all(e.endswith("!") for e, s in
                              zip(entries, steps) if s < final)
            except Exception as e:
                self._sync_error = e
                return True                # degraded best-effort save
            self._confirm_round += 1       # every process, every round
            # EVERY process adopts the confirmed step — the save path
            # derives the checkpoint number (and thus the commit-barrier
            # token) from _save_at, which must be identical on all hosts.
            self._save_at = final
            if min(steps) == final:
                return True                # all stopped at the same step
            if blocked:
                # No below-target process can advance (their loops are
                # over — the signal landed on someone's last steps):
                # save what we have under a common number so no host's
                # shards are missing from the commit.
                import logging
                logging.getLogger(__name__).warning(
                    "preemption finalize: hosts stopped at unequal steps "
                    "%s; committing best-effort checkpoint at %d",
                    sorted(steps), final)
                return True
            if not self._finalizing and self._step < final:
                # laggard: run to the raised target, then confirm again
                return False
            # already at the target (or final, waiting for peers to
            # reach it / finish their loops): confirm again without
            # stepping — all our steps are enqueued, so peers' in-flight
            # collectives still complete

    def _check_preemption_and_maybe_checkpoint(self):
        if self._exited:
            return
        if self._grace_deadline is not None:
            # already checkpointed; training continues until the platform
            # grace window closes (≙ failure_handling.py:1204 — the
            # reference KEEPS RUNNING during the grace period, banking
            # extra steps, rather than sleeping it away)
            if time.time() >= self._grace_deadline:
                self._exit()
            return
        save_at = self._agree_on_preemption()
        if save_at is None or self._step < save_at:
            return
        if not self._confirm_stop_step(save_at):
            return
        if self._config.save_fn is not None:
            self._config.save_fn()
            # NOTE: no key retirement here — a custom save_fn has no
            # commit barrier, so a peer's sync thread may still be
            # reading the agreement keys.
        else:
            self._manager.save(checkpoint_number=self._save_at +
                               self._run_count_restored
                               if self._save_at is not None
                               else self._step + self._run_count_restored)
            self._manager.checkpoint.sync()
            # Every process has saved (save's commit protocol ends with a
            # cross-process barrier), so the agreement keys can be
            # retired — a later handler on this job must start clean.
            from distributed_tensorflow_tpu.cluster.coordination import (
                coordination_service)
            agent = coordination_service()
            try:
                agent.key_value_delete(self._SIGNAL_KEY)
                agent.key_value_delete(self._STEPS_PREFIX)
            except Exception:
                pass
        if self._config.grace_period:
            # checkpoint secured; bank extra training steps until the
            # platform window closes, then exit at a step boundary
            self._grace_deadline = time.time() + self._config.grace_period
            return
        self._exit()

    def _exit(self):
        """Leave the training loop after the preemption checkpoint
        committed. Injectable (``TerminationConfig.exit_fn``) and
        overridable; with no injection the behavior is mode-selected
        (see :class:`TerminationConfig`) but always *raises* — library
        code never hard-exits the process."""
        self._exited = True
        self._restore_signal_handler()
        from distributed_tensorflow_tpu.telemetry import events as _events
        _events.event("preemption.exit", step=self._step,
                      save_at=self._save_at, mode=self._config.exit_mode)
        if self._config.exit_fn is not None:
            self._config.exit_fn()
        elif self._config.exit_mode == "restart":
            raise TrainingPreempted(
                f"preempted at step {self._step}; checkpoint saved at "
                f"step {self._save_at} — restart to resume")
        else:
            raise SystemExit(EXIT_PREEMPTED)  # platform restarts the job


def _default_exit():
    os._exit(EXIT_PREEMPTED)
