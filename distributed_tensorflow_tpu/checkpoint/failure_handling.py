"""Preemption-safe coordinated checkpointing.

TPU-native counterpart of tensorflow/python/distribute/failure_handling/
failure_handling.py (SURVEY.md §2.5, §3.5):

- ``TerminationConfig``            ≙ failure_handling.py:75-244 (platform
  matrix: Borg/GCE x CPU/GPU/TPU). Here the platform signal set collapses to
  SIGTERM plus the GCE/TPU-VM maintenance-event file hook.
- ``PreemptionCheckpointHandler``  ≙ failure_handling.py:337: wraps the
  train loop; on a preemption signal every process agrees on a "step to
  save at", checkpoints there, and exits (or counts down a grace period).

The cross-process agreement protocol in the reference rides the
coordination-service KV store plus a collective (_watch_step_to_save_key,
failure_handling.py:1222). Here the same two primitives are
``jax.experimental.multihost_utils`` broadcast (coordination-service backed)
— on a single process it degenerates to a local flag, which is what the
tests exercise; the multi-host path reuses the identical code.
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import Callable

import jax
import numpy as np

from distributed_tensorflow_tpu.checkpoint.checkpoint import (
    Checkpoint,
    CheckpointManager,
)


@dataclasses.dataclass
class TerminationConfig:
    """≙ failure_handling.py:75 ``TerminationConfig``."""

    termination_watcher_fn: Callable[[], bool] | None = None
    exit_fn: Callable[[], None] | None = None
    grace_period: float = 0.0
    save_fn: Callable[[], None] | None = None

    @classmethod
    def for_platform(cls) -> "TerminationConfig":
        """Platform sniffing (≙ failure_handling.py:245): on GCE/TPU-VM,
        watch the maintenance-event metadata; default is signal-only."""
        watcher = None
        event_file = os.environ.get("DTX_MAINTENANCE_EVENT_FILE")
        if event_file:
            def watcher() -> bool:  # noqa: F811
                try:
                    with open(event_file) as f:
                        return "TERMINATE" in f.read().upper()
                except OSError:
                    return False
        return cls(termination_watcher_fn=watcher)


class PreemptionCheckpointHandler:
    """Wraps a training loop with preemption-triggered checkpointing.

    Usage (≙ failure_handling.py:805 ``run``):

        handler = PreemptionCheckpointHandler(manager)
        for _ in range(steps):
            handler.run(train_step_fn)   # runs fn; checkpoints+exits on
                                         # preemption at a step boundary
    """

    def __init__(self, checkpoint_manager: CheckpointManager,
                 termination_config: TerminationConfig | None = None,
                 watch_interval: float = 1.0):
        self._manager = checkpoint_manager
        self._config = termination_config or TerminationConfig.for_platform()
        self._received = threading.Event()
        self._step = 0
        self._run_count_restored = 0
        self._exited = False
        self._poller: threading.Thread | None = None

        # restore first (≙ failure_handling.py:647 restore-on-init)
        latest = self._manager.restore_or_initialize()
        if latest is not None:
            self._run_count_restored = self._manager.checkpoint.save_counter

        self._install_signal_handler()
        if self._config.termination_watcher_fn is not None:
            self._poller = threading.Thread(target=self._poll, daemon=True)
            self._poller.start()

    # -- signal plumbing ---------------------------------------------------
    def _install_signal_handler(self):
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            prev = signal.getsignal(signal.SIGTERM)

            def handler(signum, frame):
                self._received.set()
                if callable(prev) and prev not in (signal.SIG_IGN,
                                                   signal.SIG_DFL):
                    prev(signum, frame)

            signal.signal(signal.SIGTERM, handler)
        except (ValueError, OSError):
            pass  # non-main thread / restricted env

    def _poll(self):
        while not self._received.is_set():
            try:
                if self._config.termination_watcher_fn():
                    self._received.set()
                    return
            except Exception:
                pass
            time.sleep(1.0)

    # -- public API --------------------------------------------------------
    @property
    def total_run_calls(self) -> int:
        """≙ PreemptionCheckpointHandler.total_run_calls: steps run across
        all incarnations (restored + this process)."""
        return self._step

    def watch_preemption(self):
        """Manually mark a preemption notice (tests/fault injection)."""
        self._received.set()

    def run(self, distributed_train_fn: Callable, *args, **kwargs):
        """Run one step, then checkpoint-and-exit if preemption was
        signalled (≙ failure_handling.py:805/:1082)."""
        result = distributed_train_fn(*args, **kwargs)
        self._step += 1
        self._check_preemption_and_maybe_checkpoint()
        return result

    def _agree_on_preemption(self) -> bool:
        """All processes must agree before saving (≙ the KV-store
        "step to save at" protocol, failure_handling.py:1222). Any process
        that saw the signal wins."""
        local = self._received.is_set()
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils
            agreed = multihost_utils.process_allgather(
                np.asarray([local], dtype=np.bool_))
            return bool(np.any(agreed))
        return local

    def _check_preemption_and_maybe_checkpoint(self):
        if self._exited or not self._agree_on_preemption():
            return
        deadline = time.time() + (self._config.grace_period or 0.0)
        if self._config.save_fn is not None:
            self._config.save_fn()
        else:
            self._manager.save(checkpoint_number=self._step +
                               self._run_count_restored)
            self._manager.checkpoint.sync()
        # grace-period countdown (≙ failure_handling.py:1204): wait out
        # the full window in small slices so tests can interrupt.
        while True:
            remaining = deadline - time.time()
            if remaining <= 0:
                break
            time.sleep(min(remaining, 0.1))
        self._exited = True
        if self._config.exit_fn is not None:
            self._config.exit_fn()
        else:
            raise SystemExit(42)  # platform restarts the job


def _default_exit():
    os._exit(42)
