"""Goodput/badput ledger: price every hardware-second of a run.

The production question the reference's fleet dashboards answer
(SURVEY §5.5: "what fraction of the pod-hours became training
progress?") — and the one none of the existing layers could: telemetry
(PR 4) exports instruments, the trace timeline (PR 8) attributes *step*
time, but nobody accounts for the seconds BETWEEN steps: compile,
respawn after a SIGKILL, checkpoint stalls, replayed decode work. This
module classifies **every wall-clock second** of every worker into

- **goodput** — productive step time (training compute/collective/host
  work inside ``train.step``; serving decode/prefill inside
  ``serve.step`` minus the replayed share), and
- named **badput** buckets (:data:`BADPUT_BUCKETS`):

  ==================  ==================================================
  ``startup``         process start/restart until its first step
                      (spawn, imports, restore, first compile)
  ``infeed_wait``     step-loop time blocked on the input pipeline
                      (``infeed_wait_s`` on ``train.step``)
  ``ckpt_block``      step-loop time blocked on checkpoint
                      capture/commit (``ckpt_block_s``)
  ``recovery``        death -> respawn gap of a reformed generation
  ``scale_transition``  drain -> reform gap of a generation the
                      autoscaler created on purpose (capacity moved
                      between training and serving — priced separately
                      from failure recovery so decision quality is
                      auditable, resilience/autoscaler.py)
  ``preempt_replay``  serving decode time spent re-generating tokens a
                      preempted/killed sequence had already produced
  ``kv_migrate``      KV-block migration time (``kv.migrate`` spans):
                      disaggregated prefill→decode handoff, drain-by-
                      migration, rescue — the honest price of NOT
                      replaying (serving/migrate.py); a drain that
                      migrates moves seconds from ``preempt_replay``
                      into this much smaller bucket
  ``rollout``         model-version transition time (``serve.swap``
                      events): weight hot-swap restore+flip, canary
                      promote/rollback pin-restores — the price of
                      keeping serving fresh without restarts
                      (serving/engine.install_version,
                      resilience/rollout.py)
  ``idle``            everything unattributed (gaps between steps,
                      drain after the last step)
  ==================  ==================================================

with the **hard identity** ``wall == goodput + Σ badput`` enforced by
construction in both implementations:

- :func:`ledger_from_events` — post-hoc/near-live: partitions each
  worker's ``[first_wall, last_wall]`` span by walking its event file in
  append order with overlap clipping (a span can never claim time an
  earlier span already claimed), so the identity is exact no matter how
  spans overlap, how many generations appended to the file, or whether
  a SIGKILL tore the tail. The recovery supervisor recomputes this on
  its export tick — the fleet's LIVE goodput surface.
- :class:`GoodputLedger` — in-process live ledger a trainer / serving
  replica feeds per step; attribution is clamped to elapsed wall so the
  registry gauges it exports (``goodput/*``, picked up by fleet rollups
  and the Prometheus exporter) always satisfy the identity too.

``tools/health_report.py`` renders either surface and gates CI on a
``--goodput-floor``.
"""

from __future__ import annotations

import threading
import time

from distributed_tensorflow_tpu.telemetry import registry as _registry

#: Badput bucket names, in render order. ``idle`` is the residual that
#: makes the identity exact.
BADPUT_BUCKETS = ("startup", "infeed_wait", "ckpt_block", "recovery",
                  "scale_transition", "preempt_replay",
                  "reroute_replay", "kv_migrate", "rollout", "idle")

#: Step events whose duration is (mostly) goodput.
_STEP_EVENTS = frozenset({"train.step", "serve.step"})


# ---------------------------------------------------------------------------
# Post-hoc / supervisor-live: classify a run's event files
# ---------------------------------------------------------------------------

def _empty() -> dict:
    return {"wall_s": 0.0, "goodput_s": 0.0,
            "badput_s": {b: 0.0 for b in BADPUT_BUCKETS}}


def _worker_ledger(events: "list[dict]",
                   scale_generations: "frozenset | set" = frozenset()
                   ) -> dict:
    """Partition one worker's observed wall span.

    Walks events in FILE ORDER (append order — chronological across
    generations even though the monotonic ``t`` resets per incarnation).
    Only three things advance the classification *cursor*: **step
    events** (their clipped ``[wall - dur, wall]`` interval is goodput
    minus the blocked shares), **generation boundaries** (the gap is
    recovery/respawn time), and ``run.start``. Every other event —
    per-request lifecycle breadcrumbs nested inside a serve step,
    async checkpoint commits pipelined BEHIND training (deliberately
    not badput: that pipelining is the point of the tiered
    checkpointer), dispatch retries — contributes metadata only, so
    nested spans can never eat their enclosing step's interval. Every
    step attribution is clipped to ``[cursor, wall]``, so overlapping
    or lying durations cannot double-count: the identity is exact by
    construction.
    """
    out = _empty()
    bad = out["badput_s"]
    cursor = None          # wall time classified so far
    cur_gen = 0
    in_startup = True      # from (re)start until the first step
    first_wall = last_wall = None
    serve_s = 0.0          # serve.step seconds (split by replay below)
    fresh_tokens = 0
    replayed_tokens = 0
    rerouted_tokens = 0    # tokens served under a router re-route

    for ev in events:
        wall = ev.get("wall")
        if not isinstance(wall, (int, float)):
            continue
        name = ev.get("ev")
        dur = ev.get("dur_s")
        dur = float(dur) if isinstance(dur, (int, float)) and dur > 0 \
            else 0.0
        if cursor is None:
            # open the observed span at the first event's START (a span
            # event's duration precedes its completion wall), so a file
            # that begins mid-run still prices its first step
            first_wall = cursor = wall - dur
        wall = max(wall, cursor)        # clamp: never travel backwards
        last_wall = max(last_wall or wall, wall)
        gen = ev.get("gen", 0)
        if isinstance(gen, int) and gen != cur_gen:
            # generation boundary inside one appended file: the gap
            # from the old incarnation's last step to the new
            # incarnation's first event is death -> respawn -> rejoin —
            # priced ``recovery`` for a failure reform and
            # ``scale_transition`` for a generation the autoscaler
            # created deliberately (same interval, different bucket:
            # the identity is untouched, the attribution is honest)
            bad["scale_transition" if gen in scale_generations
                else "recovery"] += wall - cursor
            cursor = wall
            cur_gen = gen
            in_startup = True
        if name in _STEP_EVENTS:
            start = max(cursor, wall - dur)
            bad["startup" if in_startup else "idle"] += start - cursor
            in_startup = False
            span = wall - start
            if name == "train.step":
                infeed = ev.get("infeed_wait_s")
                infeed = min(float(infeed), span) if isinstance(
                    infeed, (int, float)) and infeed > 0 else 0.0
                ckpt = ev.get("ckpt_block_s")
                ckpt = min(float(ckpt), span - infeed) if isinstance(
                    ckpt, (int, float)) and ckpt > 0 else 0.0
                bad["infeed_wait"] += infeed
                bad["ckpt_block"] += ckpt
                out["goodput_s"] += span - infeed - ckpt
            else:                        # serve.step
                serve_s += span
            cursor = wall
        elif name == "kv.migrate":
            # KV handoff (export or adopt) is honest badput: the chip
            # moved cache rows instead of computing tokens. The event
            # also ADVANCES the cursor, so a migration nested inside a
            # serve.step span is clipped out of that step's serve share
            # by the standard overlap rule — never double-counted.
            start = max(cursor, wall - dur)
            bad["startup" if in_startup else "idle"] += start - cursor
            bad["kv_migrate"] += wall - start
            cursor = wall
        elif name == "serve.swap":
            # a version transition (hot-swap flip + restore share that
            # landed on this worker's wall, or a restart adoption) is
            # ``rollout`` badput; the cursor advance clips it out of
            # any enclosing/overlapping serve.step exactly like
            # kv.migrate — identity intact by the same overlap rule
            start = max(cursor, wall - dur)
            bad["startup" if in_startup else "idle"] += start - cursor
            bad["rollout"] += wall - start
            cursor = wall
        elif name == "serve.request":
            rt = ev.get("replayed_tokens")
            nt = ev.get("new_tokens")
            if isinstance(rt, (int, float)):
                replayed_tokens += int(rt)
                if isinstance(nt, (int, float)):
                    fresh_tokens += max(0, int(nt) - int(rt))
        elif name == "serve.rerouted":
            # the router re-dispatched this request after its first
            # replica died mid-flight: this replica's serve share of it
            # is duplicate/recovery work, priced reroute_replay below
            nt = ev.get("new_tokens")
            if isinstance(nt, (int, float)):
                rerouted_tokens += int(nt)
        elif name == "run.start":
            bad["startup" if in_startup else "idle"] += wall - cursor
            cursor = wall
            in_startup = True

    # the tail after the last step (drain, shutdown, or simply events
    # still being written) closes the partition
    if cursor is not None and last_wall > cursor:
        bad["startup" if in_startup else "idle"] += last_wall - cursor

    # serving: the replayed share of decode/prefill work re-generated
    # tokens a preemption (or replica death) already produced once —
    # badput, not goodput. Tokens served under a router RE-ROUTE are
    # priced separately (``reroute_replay``): the whole re-served
    # request is conservatively treated as recovery work (an upper
    # bound — the dead replica may not have finished it), so the
    # measured re-route cost can never be understated.
    total_tokens = fresh_tokens + replayed_tokens
    replay_frac = (replayed_tokens / total_tokens) if total_tokens else 0.0
    reroute_frac = (min(rerouted_tokens, fresh_tokens) / total_tokens) \
        if total_tokens else 0.0
    bad["preempt_replay"] += serve_s * replay_frac
    bad["reroute_replay"] += serve_s * reroute_frac
    out["goodput_s"] += serve_s * (1.0 - replay_frac - reroute_frac)
    out["replayed_tokens"] = replayed_tokens
    out["rerouted_tokens"] = rerouted_tokens

    if first_wall is not None:
        out["wall_s"] = last_wall - first_wall
    return out


def ledger_from_events(events_by_pid: "dict") -> dict:
    """Fleet goodput/badput ledger from per-process event lists
    (:func:`telemetry.read_run` output).

    Only numeric pids count as hardware (the supervisor watches, it
    does not burn accelerator time). Returns::

        {"wall_s": hw_seconds, "goodput_s": s, "goodput_frac": f,
         "badput_s": {bucket: s}, "identity_error_s": ~0.0,
         "per_worker": {pid: {...}}}

    ``identity_error_s`` is recomputed from the summed parts (not
    assumed): ``wall - (goodput + Σ badput)``. It is ~0 by construction
    and asserted ≤1% of wall by the chaos-sweep gate.
    """
    # generations the autoscaler created on purpose (``scale.applied``
    # is emitted by the supervisor whose log shares this run dir): their
    # reform gaps price into ``scale_transition``, failure reforms into
    # ``recovery``. Scanned across EVERY pid — the supervisor's own
    # (non-numeric) log is where the markers live.
    scale_gens = frozenset(
        ev.get("generation") for events in events_by_pid.values()
        for ev in events
        if ev.get("ev") == "scale.applied"
        and isinstance(ev.get("generation"), int))
    per_worker: dict = {}
    total = _empty()
    for pid, events in sorted(events_by_pid.items(),
                              key=lambda kv: str(kv[0])):
        if not isinstance(pid, int):
            continue
        lw = _worker_ledger(events, scale_gens)
        per_worker[pid] = lw
        total["wall_s"] += lw["wall_s"]
        total["goodput_s"] += lw["goodput_s"]
        for b in BADPUT_BUCKETS:
            total["badput_s"][b] += lw["badput_s"][b]
    wall = total["wall_s"]
    attributed = total["goodput_s"] + sum(total["badput_s"].values())
    total["goodput_frac"] = (total["goodput_s"] / wall) if wall > 0 \
        else None
    total["identity_error_s"] = wall - attributed
    total["per_worker"] = per_worker
    return total


def ledger_from_run(run_dir: str) -> dict:
    """:func:`ledger_from_events` over a telemetry run directory
    (torn-tail tolerant — safe against files still being written)."""
    from distributed_tensorflow_tpu.telemetry import events as _events
    return ledger_from_events(_events.read_run(run_dir))


def prometheus_lines(ledger: dict, *, prefix: str = "dtx_") -> list:
    """Render a ledger as Prometheus exposition lines (the recovery
    supervisor's export tick appends these to its scrape)."""
    lines = [f"# TYPE {prefix}goodput_seconds gauge",
             f'{prefix}goodput_seconds {ledger["goodput_s"]:.6f}',
             f"# TYPE {prefix}wall_seconds gauge",
             f'{prefix}wall_seconds {ledger["wall_s"]:.6f}',
             f"# TYPE {prefix}badput_seconds gauge"]
    for b in BADPUT_BUCKETS:
        lines.append(f'{prefix}badput_seconds{{bucket="{b}"}} '
                     f'{ledger["badput_s"][b]:.6f}')
    frac = ledger.get("goodput_frac")
    if frac is not None:
        lines += [f"# TYPE {prefix}goodput_frac gauge",
                  f"{prefix}goodput_frac {frac:.6f}"]
    return lines


# ---------------------------------------------------------------------------
# In-process live ledger
# ---------------------------------------------------------------------------

class GoodputLedger:
    """Live per-process ledger a step loop feeds.

    ::

        ledger = GoodputLedger()          # registers goodput/* gauges
        goodput.activate(ledger)
        ...
        ledger.step_completed(dur_s, infeed_s=w, ckpt_s=c)   # trainer
        ledger.serve_step(dur_s); ledger.tokens(fresh, replayed)

    Attribution is clamped so the total never exceeds elapsed wall;
    :meth:`snapshot` returns the identity-exact breakdown with ``idle``
    as the residual. The snapshot is exported through a registry
    collector (``goodput/<field>`` gauges) so fleet rollups and the
    Prometheus exporter carry it with zero extra wiring.

    ``enter(bucket)`` names the bucket the CURRENT gap is accruing to —
    the stall detector stamps it on ``stall.suspected`` so a stall names
    both the blocked lane and the badput class it is becoming.
    """

    def __init__(self, reg=None, clock=time.monotonic, register=True):
        self._clock = clock
        self._t0 = clock()
        self._lock = threading.Lock()
        self._named = {b: 0.0 for b in BADPUT_BUCKETS if b != "idle"}
        self._good_train = 0.0
        self._serve_s = 0.0
        self._fresh = 0
        self._replayed = 0
        self._rerouted = 0
        self._attributed = 0.0
        self._bucket = "startup"       # current accruing bucket
        self._reg = reg or _registry.get_registry()
        if register:
            self._reg.register_collector("goodput", self._collect)

    # -- feeding -----------------------------------------------------------
    def _claim(self, seconds: float) -> float:
        """Clamp an attribution to the wall not yet attributed."""
        avail = (self._clock() - self._t0) - self._attributed
        add = max(0.0, min(float(seconds), avail))
        self._attributed += add
        return add

    def step_completed(self, dur_s: float, *, infeed_s: float = 0.0,
                       ckpt_s: float = 0.0):
        """One training step: ``dur_s`` minus the blocked shares is
        goodput; the first step also retires the ``startup`` bucket
        (everything before it was startup/compile)."""
        with self._lock:
            self._retire_startup(reserve=dur_s)
            span = self._claim(dur_s)
            infeed = min(max(0.0, infeed_s), span)
            ckpt = min(max(0.0, ckpt_s), span - infeed)
            self._named["infeed_wait"] += infeed
            self._named["ckpt_block"] += ckpt
            self._good_train += span - infeed - ckpt
            self._bucket = "idle"

    def serve_step(self, dur_s: float):
        """One serving engine iteration (split goodput/replay at
        snapshot time by the token ratio from :meth:`tokens`)."""
        with self._lock:
            self._retire_startup(reserve=dur_s)
            self._serve_s += self._claim(dur_s)
            self._bucket = "idle"

    def tokens(self, fresh: int, replayed: int = 0,
               rerouted: int = 0):
        """``rerouted`` marks fresh tokens that re-served a request a
        dead replica already had in flight (router re-route) — their
        serve share prices ``reroute_replay`` at snapshot time."""
        with self._lock:
            self._fresh += max(0, int(fresh))
            self._replayed += max(0, int(replayed))
            self._rerouted += max(0, int(rerouted))

    def record(self, bucket: str, seconds: float):
        """Explicit badput (e.g. the supervisor pricing a recovery)."""
        if bucket not in self._named:
            raise ValueError(f"unknown badput bucket {bucket!r}; "
                             f"expected one of {BADPUT_BUCKETS}")
        with self._lock:
            self._named[bucket] += self._claim(seconds)

    def _retire_startup(self, reserve: float = 0.0):
        """First step of an incarnation: everything before it (minus
        the step itself, ``reserve``) was startup/compile."""
        if self._bucket == "startup":
            avail = ((self._clock() - self._t0) - self._attributed
                     - max(0.0, reserve))
            if avail > 0:
                self._named["startup"] += avail
                self._attributed += avail

    def enter(self, bucket: str):
        """Name the bucket un-attributed time is CURRENTLY accruing to
        (``idle`` default after the first step; ``startup`` before)."""
        if bucket != "idle" and bucket not in self._named:
            raise ValueError(f"unknown badput bucket {bucket!r}")
        self._bucket = bucket

    @property
    def current_bucket(self) -> str:
        return self._bucket

    # -- export ------------------------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            wall = self._clock() - self._t0
            total_tok = self._fresh + self._replayed
            rf = (self._replayed / total_tok) if total_tok else 0.0
            xf = (min(self._rerouted, self._fresh) / total_tok) \
                if total_tok else 0.0
            bad = {b: self._named.get(b, 0.0) for b in BADPUT_BUCKETS
                   if b != "idle"}
            bad["preempt_replay"] += self._serve_s * rf
            bad["reroute_replay"] += self._serve_s * xf
            good = self._good_train + self._serve_s * (1.0 - rf - xf)
            bad["idle"] = max(0.0, wall - good
                              - sum(bad.values()))
        return {"wall_s": wall, "goodput_s": good,
                "goodput_frac": (good / wall) if wall > 0 else None,
                "badput_s": bad}

    def _collect(self) -> dict:
        snap = self.snapshot()
        out = {"wall_s": round(snap["wall_s"], 6),
               "goodput_s": round(snap["goodput_s"], 6)}
        if snap["goodput_frac"] is not None:
            out["goodput_frac"] = round(snap["goodput_frac"], 6)
        for b, v in snap["badput_s"].items():
            out[f"badput/{b}_s"] = round(v, 6)
        return out

    def close(self):
        self._reg.unregister_collector("goodput")


# ---------------------------------------------------------------------------
# Process-wide active ledger (the events._LOG activation pattern)
# ---------------------------------------------------------------------------

_ACTIVE: "GoodputLedger | None" = None


def activate(ledger: "GoodputLedger | None") -> "GoodputLedger | None":
    """Install (or, with None, clear) the process-wide live ledger that
    StepTelemetry / the serving engine / the stall detector feed and
    read. Returns the previous ledger."""
    global _ACTIVE
    prev, _ACTIVE = _ACTIVE, ledger
    return prev


def active_ledger() -> "GoodputLedger | None":
    return _ACTIVE


def accruing_bucket() -> str:
    """The badput bucket un-attributed time is accruing to right now —
    ``idle`` when no live ledger is active (unattributed is the honest
    default)."""
    led = _ACTIVE
    return led.current_bucket if led is not None else "idle"
