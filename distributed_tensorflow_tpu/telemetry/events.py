"""Structured run events: append-only JSONL spans/events.

The run-level "what happened" record: every process of a job writes an
append-only JSONL file of structured events with monotonic timestamps —
step completions, dispatch retries, checkpoint spans, fault firings.
``tools/obs_report.py`` renders a finished run's files into a
human-readable report; the stall detector and cross-host aggregation
consume the same stream live.

Record format (one JSON object per line)::

    {"ev": "train.step", "t": 12.034561, "wall": 1755312000.2,
     "pid": 0, "dur_s": 0.0312, "step": 7, "loss": 2.31}

- ``ev``    event name, dotted namespace (``train.step``,
  ``dispatch.retry``, ``checkpoint.save``, ``stall.suspected``)
- ``t``     monotonic seconds since this process's log was opened —
  strictly ordered within a file regardless of wall-clock steps
- ``wall``  wall time (cross-host correlation, human display)
- ``pid``   the process id in the cluster (jax.process_index vintage)
- ``dur_s`` present for span-end events: the span's duration

API::

    telemetry.configure(logdir)          # or env DTX_TELEMETRY_DIR
    telemetry.event("dispatch.retry", worker=3)
    with telemetry.span("checkpoint.save", path=p):
        ...                              # emits dur_s on exit

With no log configured — the production default — ``event``/``span``
are a single module-global None check: zero overhead, no allocation
(same contract as resilience/faults.fire).

Reading back: :func:`read_events` parses a file, tolerating a torn
final line (a crashed process mid-write) but refusing mid-file
corruption — the distinction ``obs_report --check`` enforces.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import threading
import time


class EventLogCorruptError(ValueError):
    """A JSONL event file is corrupt before its final line (torn tails
    are expected from crashed writers; mid-file damage is not)."""


#: Env var: rotate a process's events.jsonl once it exceeds this many
#: bytes (``events.jsonl`` -> ``events.jsonl.1``, older segments shift
#: up). Unset/0 = never rotate (the pre-rotation behavior).
ENV_ROTATE_BYTES = "DTX_TELEMETRY_ROTATE_BYTES"


class EventLog:
    """Append-only JSONL event writer for one process.

    One file handle per process, all writes serialized under a lock and
    written as complete lines (a reader can never observe a half
    record except the final line of a crashed writer).

    **Rotation:** with ``max_bytes`` set (arg, or the
    ``DTX_TELEMETRY_ROTATE_BYTES`` env var spawned children inherit),
    the file rotates to ``<path>.1`` when a write pushes it past the
    cap (``.1`` -> ``.2`` and so on shift up first), so a long-lived
    serving replica's log stays size-capped per segment.
    :func:`read_events` transparently chains the rotated segments back
    in chronological order — trace/obs reports are unchanged. Rotation
    happens at a line boundary, so rotated segments are always whole.
    """

    def __init__(self, path: str, process_id: "int | str | None" = None,
                 run_id: str | None = None,
                 max_bytes: "int | None" = None):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path
        self.process_id = process_id if process_id is not None else 0
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(ENV_ROTATE_BYTES, "0"))
            except ValueError:
                max_bytes = 0
        self.max_bytes = max_bytes or 0
        try:
            self._size = os.path.getsize(path)
        except OSError:
            self._size = 0
        self._lock = threading.Lock()
        # line-buffered: every complete event line reaches the OS as it
        # is written, so a process that dies hard (SIGKILL, os._exit —
        # exactly the processes whose last events matter most) loses at
        # most the line being written, never a buffer of whole events
        self._f: io.TextIOBase | None = open(path, "a", buffering=1,
                                             encoding="utf-8")
        self._t0 = time.monotonic()
        self._last_t = 0.0
        # Elastic-cluster generation stamp: a reformed cluster's restarted
        # process appends to the SAME events-<pid>.jsonl, so the trace
        # assembler needs each record to say which incarnation wrote it.
        # Generation 0 (non-elastic default) stays unstamped — byte-
        # identical records to before.
        try:
            from distributed_tensorflow_tpu.cluster import elastic
            self._gen = elastic.generation()
        except Exception:
            self._gen = 0
        if run_id:
            self.event("run.start", run_id=run_id)

    # -- write ------------------------------------------------------------
    def event(self, name: str, **fields):
        """Append one structured event; returns the record written."""
        rec = {"ev": name}
        with self._lock:
            if self._f is None:
                return None
            # monotonic within the file even if time.monotonic were to
            # be adjusted (it can't go backwards, but clamp anyway so
            # the file-level invariant is unconditional)
            t = time.monotonic() - self._t0
            if t < self._last_t:
                t = self._last_t
            self._last_t = t
            rec["t"] = round(t, 6)
            rec["wall"] = round(time.time(), 6)
            rec["pid"] = self.process_id
            if self._gen:
                rec["gen"] = self._gen
            rec.update(fields)
            line = json.dumps(rec) + "\n"
            self._f.write(line)
            self._size += len(line)
            if self.max_bytes and self._size > self.max_bytes:
                self._rotate_locked()
        return rec

    def _rotate_locked(self):
        """Shift rotated segments up and start a fresh file (caller
        holds the lock; the write that crossed the cap is complete, so
        every segment ends at a line boundary)."""
        self._f.flush()
        self._f.close()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for i in range(n, 1, -1):
            os.replace(f"{self.path}.{i - 1}", f"{self.path}.{i}")
        os.replace(self.path, f"{self.path}.1")
        self._f = open(self.path, "a", buffering=1, encoding="utf-8")
        self._size = 0

    @contextlib.contextmanager
    def span(self, name: str, **fields):
        """Scoped span: emits ``<name>`` at exit with ``dur_s`` (and
        ``error`` when the body raised). Yields a dict the body may add
        result fields to (e.g. ``sp["bytes"] = n``)."""
        extra: dict = {}
        t0 = time.perf_counter()
        try:
            yield extra
        except BaseException as e:
            extra["error"] = f"{type(e).__name__}: {e}"
            raise
        finally:
            merged = {"dur_s": round(time.perf_counter() - t0, 6)}
            merged.update(fields)
            merged.update(extra)        # body-added fields win; never a
            self.event(name, **merged)  # duplicate-kwarg TypeError here

    def flush(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()

    def close(self):
        with self._lock:
            if self._f is not None:
                self._f.flush()
                self._f.close()
                self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


# ---------------------------------------------------------------------------
# Process-wide log (the faults.py activation pattern: a single global,
# None = disabled = zero overhead).
# ---------------------------------------------------------------------------

_LOG: EventLog | None = None
_LOG_LOCK = threading.Lock()

#: Env var children of multi_process_runner inherit: a directory to
#: write per-process event logs into (file name carries the process id).
ENV_TELEMETRY_DIR = "DTX_TELEMETRY_DIR"


def _default_process_id() -> int:
    # jax.process_index() without forcing backend init in processes that
    # never initialize jax.distributed (single-host tools, tests).
    try:
        import jax
        if jax._src.distributed.global_state.client is not None:
            return jax.process_index()
    except Exception:
        pass
    # multi_process_runner children: the task index is injected before
    # jax.distributed comes up, so env-activated logs in a freshly
    # spawned cluster task land in per-task files instead of all
    # colliding on events-0.jsonl
    for var in ("DTX_TASK_ID", "DTX_MPR_TASK_INDEX"):
        try:
            return int(os.environ[var])
        except (KeyError, ValueError):
            continue
    return 0


def event_log_path(logdir: str, process_id: int) -> str:
    return os.path.join(logdir, f"events-{process_id}.jsonl")


def configure(logdir: str, process_id: int | None = None,
              run_id: str | None = None) -> EventLog:
    """Open (or replace) the process-wide event log under ``logdir``.
    Each process writes its own ``events-<pid>.jsonl``."""
    global _LOG
    pid = process_id if process_id is not None else _default_process_id()
    with _LOG_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = EventLog(event_log_path(logdir, pid), process_id=pid,
                        run_id=run_id)
        return _LOG


def shutdown():
    """Close and detach the process-wide log (back to zero-overhead)."""
    global _LOG
    with _LOG_LOCK:
        if _LOG is not None:
            _LOG.close()
        _LOG = None


def get_event_log() -> EventLog | None:
    return _LOG


def enabled() -> bool:
    """True when a process-wide event log is configured. Call sites with
    non-trivial field construction guard on this; plain sites just call
    :func:`event` (a no-op without a log)."""
    return _LOG is not None


def event(name: str, **fields):
    """Module-level event against the process-wide log; no-op (one
    None check) when telemetry is off."""
    log = _LOG
    if log is None:
        return None
    return log.event(name, **fields)


@contextlib.contextmanager
def span(name: str, **fields):
    """Module-level span; a plain passthrough when telemetry is off."""
    log = _LOG
    if log is None:
        yield {}
        return
    with log.span(name, **fields) as extra:
        yield extra


# Env activation (≙ faults.DTX_FAULT_SCHEDULE): spawned multi-process
# children inherit the telemetry directory for free.
_env = os.environ.get(ENV_TELEMETRY_DIR)
if _env:
    configure(_env)
del _env


# ---------------------------------------------------------------------------
# Reading back
# ---------------------------------------------------------------------------

def rotated_segments(path: str) -> list[str]:
    """Rotated siblings of an event file in CHRONOLOGICAL order
    (``path.N`` is older than ``path.N-1``; the live ``path`` itself is
    newest and not included)."""
    import glob
    import re
    segs = []
    for p in glob.glob(glob.escape(path) + ".*"):
        m = re.match(re.escape(path) + r"\.(\d+)$", p)
        if m:
            segs.append((int(m.group(1)), p))
    return [p for _, p in sorted(segs, reverse=True)]


def _read_one(path: str, *, tolerate_torn_tail: bool) -> list[dict]:
    out: list[dict] = []
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        lines = f.read().split("\n")
    if lines and lines[-1] == "":
        lines.pop()                     # trailing newline artifact
    for i, line in enumerate(lines):
        try:
            rec = json.loads(line)
            if not isinstance(rec, dict):
                raise ValueError("event record is not an object")
        except ValueError as e:
            if i == len(lines) - 1 and tolerate_torn_tail:
                break                   # torn tail: crashed mid-write
            raise EventLogCorruptError(
                f"{path}:{i + 1}: malformed event line: {e}") from e
        out.append(rec)
    return out


def read_events(path: str, *, tolerate_torn_tail: bool = True,
                include_rotated: bool = True) -> list[dict]:
    """Parse one JSONL event file (chaining any rotated segments).

    A torn FINAL line (crashed writer) is dropped when
    ``tolerate_torn_tail`` (the default); malformed content anywhere
    before the final line raises :class:`EventLogCorruptError` —
    mid-file corruption means the file cannot be trusted at all.

    When the writer rotated (``<path>.N`` siblings exist), the rotated
    segments are read first in chronological order — transparently, so
    every consumer of the base file sees the full history. Rotation
    happens at line boundaries, so only the LIVE file may have a torn
    tail; a malformed line inside a rotated segment is corruption.
    """
    out: list[dict] = []
    if include_rotated:
        for seg in rotated_segments(path):
            out.extend(_read_one(seg, tolerate_torn_tail=False))
    out.extend(_read_one(path, tolerate_torn_tail=tolerate_torn_tail))
    return out


def read_run(logdir: str, *, tolerate_torn_tail: bool = True) -> dict:
    """All per-process event files under ``logdir``:
    ``{process_id: [events...]}`` keyed by the id in the file name
    (numeric ids as ints; a recovery supervisor's file keys as the
    string ``"supervisor"``)."""
    import glob
    import re
    out: dict = {}
    for path in sorted(glob.glob(os.path.join(logdir, "events-*.jsonl"))):
        m = re.search(r"events-([A-Za-z0-9_]+)\.jsonl$", path)
        suffix = m.group(1) if m else str(len(out))
        pid = int(suffix) if suffix.isdigit() else suffix
        out[pid] = read_events(path, tolerate_torn_tail=tolerate_torn_tail)
    return out
