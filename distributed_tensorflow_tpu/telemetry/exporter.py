"""Streaming metrics export: ring-buffer history, Prometheus scrape,
file fallback, fleet merge.

PR 4's registry answers "what are the numbers *now*" to in-process
callers only; everything else (obs_report, trend tooling) reads files
after the run exits. This module is the live path out:

- :class:`SeriesHistory` — per-instrument bounded ring-buffer
  time-series built from periodic snapshot DELTAS, so counter rates
  (``rate()``) come from history, not from a second instrument set.
- :func:`render_prometheus` — a registry snapshot as Prometheus text
  exposition (counters/gauges; histograms+timers as summaries with
  ``quantile`` labels, ``_count``/``_sum``).
- :func:`render_rollup` — the fleet merge path: one coordination-KV
  rollup (telemetry/aggregate.py) rendered with ``worker="<pid>"``
  labels, so ONE scrape of the coordinator/supervisor sees every
  worker.
- :class:`MetricsExporter` — the periodic tick: snapshot → history →
  render → serve. Serving is opt-in twice over: an HTTP ``/metrics``
  endpoint (stdlib ``http.server``) when ``DTX_METRICS_PORT`` (or the
  ``port=`` arg) is set, and a ``metrics-live.prom`` file (atomic
  rename) whenever a directory is given — the portless fallback test
  environments and the chaos sweeps scrape.

Metric names sanitize ``training/step_time`` → ``dtx_training_step_time``
(Prometheus charset); every value is a float sample on one line, no
client library required.
"""

from __future__ import annotations

import collections
import http.server
import os
import re
import threading
import time

from distributed_tensorflow_tpu.telemetry import registry as _registry

#: Env var enabling the HTTP endpoint in any process that starts a
#: MetricsExporter (0/absent = file-only). Port 0 binds an ephemeral
#: port (exposed as ``exporter.port``).
ENV_METRICS_PORT = "DTX_METRICS_PORT"

#: File name of the scrape fallback written into the export directory.
LIVE_METRICS_FILE = "metrics-live.prom"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str, prefix: str = "dtx_") -> str:
    return prefix + _NAME_RE.sub("_", str(name)).strip("_")


def _num(v):
    return (float(v) if isinstance(v, (int, float))
            and not isinstance(v, bool) else None)


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

def render_prometheus(snapshot: dict, *, prefix: str = "dtx_",
                      labels: "dict | None" = None) -> "list[str]":
    """Registry snapshot (``MetricsRegistry.snapshot()``) → exposition
    lines. Histograms/timers render as summaries (quantile labels)."""
    lab = ""
    if labels:
        lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    lines: list[str] = []
    for name, entry in sorted(snapshot.items()):
        pname = _prom_name(name, prefix)
        kind = entry.get("type")
        if kind == "counter":
            v = _num(entry.get("value"))
            if v is not None:
                lines.append(f"# TYPE {pname} counter")
                lines.append(f"{pname}{{{lab}}} {v:g}" if lab
                             else f"{pname} {v:g}")
        elif kind in ("histogram", "timer"):
            lines.append(f"# TYPE {pname} summary")
            for q in ("p50", "p95", "p99"):
                v = _num(entry.get(q))
                if v is not None:
                    ql = f'quantile="0.{q[1:]}"'
                    both = f"{ql},{lab}" if lab else ql
                    lines.append(f"{pname}{{{both}}} {v:g}")
            c, s = _num(entry.get("count")), _num(entry.get("sum"))
            if c is not None:
                lines.append(f"{pname}_count{{{lab}}} {c:g}" if lab
                             else f"{pname}_count {c:g}")
            if s is not None:
                lines.append(f"{pname}_sum{{{lab}}} {s:g}" if lab
                             else f"{pname}_sum {s:g}")
        else:                            # gauge (and collector output)
            v = _num(entry.get("value"))
            if v is not None:
                lines.append(f"# TYPE {pname} gauge")
                lines.append(f"{pname}{{{lab}}} {v:g}" if lab
                             else f"{pname} {v:g}")
    return lines


def render_rollup(rollup: dict, *, prefix: str = "dtx_fleet_",
                  stale_after_s: "float | None" = None,
                  now: "float | None" = None,
                  retired: "dict | None" = None) -> "list[str]":
    """Fleet rollup (``aggregate.merge_rollup``) → per-worker labelled
    samples plus the merged stats — the one-scrape-sees-all-workers
    path.

    ``stale_after_s`` drops the ``worker="<pid>"`` label series of
    workers whose last snapshot (``rollup["workers"][pid]["wall"]``)
    is older than that many seconds before ``now`` (default: the
    NEWEST snapshot wall in the rollup, so the filter needs no clock
    agreement with the workers). A worker that died before a reform
    leaves its final snapshot in the KV forever; without the filter a
    post-recovery scrape keeps reporting that ghost as a live series.
    The merged ``stat=`` samples are untouched — they answer "what did
    the fleet do", the per-worker labels answer "who is alive doing
    it".

    ``retired`` extends the same dedup to ROLE CHANGES: a worker the
    autoscaler repurposed (training↔serving) keeps heartbeating, so the
    age filter never fires, yet its pre-reassignment snapshot must not
    linger as a ghost series of the OLD role. It maps ``pid -> wall of
    reassignment``: that worker's label series are dropped until it
    publishes a snapshot NEWER than its reassignment (i.e. from the new
    role's registry — or from the old role again, if it was handed
    back)."""
    stale: set = set()
    workers = rollup.get("workers") or {}
    if stale_after_s is not None and workers:
        walls = {pid: w.get("wall") for pid, w in workers.items()
                 if isinstance(w, dict)
                 and isinstance(w.get("wall"), (int, float))}
        if walls:
            ref = now if now is not None else max(walls.values())
            stale = {pid for pid, wall in walls.items()
                     if ref - wall > stale_after_s}
            # snapshot payloads key workers by int, JSON round-trips
            # may key them by str: treat both spellings as the pid
            stale |= {str(p) for p in stale}
    if retired:
        for pid, rwall in retired.items():
            w = workers.get(pid)
            if w is None:
                w = workers.get(str(pid)) or (
                    workers.get(int(pid)) if str(pid).isdigit() else None)
            wall = w.get("wall") if isinstance(w, dict) else None
            if not isinstance(wall, (int, float)) or wall <= rwall:
                stale.add(pid)
                stale.add(str(pid))
    lines: list[str] = []
    for name, entry in sorted((rollup.get("metrics") or {}).items()):
        pname = _prom_name(name, prefix)
        lines.append(f"# TYPE {pname} gauge")
        for stat in ("sum", "max", "mean", "p50", "p95", "count"):
            v = _num(entry.get(stat))
            if v is not None:
                lines.append(f'{pname}{{stat="{stat}"}} {v:g}')
        per_worker = entry.get("per_worker") \
            or entry.get("per_worker_count") or {}
        for pid, v in sorted(per_worker.items(), key=lambda kv:
                             str(kv[0])):
            if pid in stale or str(pid) in stale:
                continue
            v = _num(v)
            if v is not None:
                lines.append(f'{pname}{{worker="{pid}"}} {v:g}')
    return lines


# ---------------------------------------------------------------------------
# Ring-buffer history + rates
# ---------------------------------------------------------------------------

class SeriesHistory:
    """Bounded time-series per instrument, fed by snapshot deltas.

    Each :meth:`record` appends ``(wall, value)`` for every numeric
    scalar the snapshot carries (counter/gauge values; histogram/timer
    count+sum) — but ONLY for entries that changed since the previous
    snapshot (the ``delta`` discipline of aggregate.py: repeated ticks
    of an idle process cost nothing). ``rate()`` differentiates the
    ring buffer, which is what turns monotonic counters into the
    steps/s / tokens/s the health surface shows.
    """

    def __init__(self, points: int = 512):
        self._points = points
        self._series: "dict[str, collections.deque]" = {}
        self._prev: dict = {}
        self._lock = threading.Lock()

    @staticmethod
    def _scalars(name: str, entry: dict):
        kind = entry.get("type")
        if kind in ("histogram", "timer"):
            for stat in ("count", "sum"):
                v = _num(entry.get(stat))
                if v is not None:
                    yield f"{name}/{stat}", v
        else:
            v = _num(entry.get("value"))
            if v is not None:
                yield name, v

    def record(self, snapshot: dict, wall: "float | None" = None):
        wall = wall if wall is not None else time.time()
        with self._lock:
            for name, entry in snapshot.items():
                if self._prev.get(name) == entry:
                    continue            # unchanged: no new point
                for key, v in self._scalars(name, entry):
                    ring = self._series.get(key)
                    if ring is None:
                        ring = self._series[key] = collections.deque(
                            maxlen=self._points)
                    ring.append((wall, v))
            self._prev = dict(snapshot)

    def names(self) -> "list[str]":
        with self._lock:
            return sorted(self._series)

    def series(self, name: str) -> "list[tuple[float, float]]":
        with self._lock:
            return list(self._series.get(name, ()))

    def rate(self, name: str, window_s: float = 60.0,
             now: "float | None" = None) -> "float | None":
        """Per-second rate of a monotonic series over the trailing
        window (None with <2 in-window points)."""
        pts = self.series(name)
        now = now if now is not None else (pts[-1][0] if pts else 0.0)
        pts = [(t, v) for t, v in pts if t >= now - window_s]
        if len(pts) < 2 or pts[-1][0] <= pts[0][0]:
            return None
        return (pts[-1][1] - pts[0][1]) / (pts[-1][0] - pts[0][0])


# ---------------------------------------------------------------------------
# The exporter
# ---------------------------------------------------------------------------

class _ScrapeHandler(http.server.BaseHTTPRequestHandler):
    exporter: "MetricsExporter" = None     # bound per server below

    def do_GET(self):                      # noqa: N802 (stdlib API)
        if self.path.rstrip("/") not in ("", "/metrics"):
            self.send_error(404)
            return
        body = self.server.exporter.scrape().encode()
        self.send_response(200)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4; charset=utf-8")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):             # quiet: scrapes are periodic
        pass


class MetricsExporter:
    """Periodic snapshot → history → render → serve loop.

    ::

        exporter = MetricsExporter(dir=run_dir)       # file fallback
        exporter = MetricsExporter(port=0)            # HTTP, any port
        ...
        exporter.stop()                               # final tick

    ``rollup_fn`` (→ a fleet rollup dict) merges every worker into the
    scrape; ``extra_fn`` (→ list of pre-rendered exposition lines)
    appends e.g. the goodput ledger / SLO burn lines. Both are called
    on the tick thread and guarded — a failing provider degrades the
    scrape, never kills it.
    """

    def __init__(self, reg=None, *, interval_s: float = 2.0,
                 dir: "str | None" = None, port: "int | None" = None,
                 rollup_fn=None, extra_fn=None, history_points: int = 512,
                 labels: "dict | None" = None,
                 stale_workers_after_s: "float | None" = 30.0):
        self.reg = reg or _registry.get_registry()
        self.interval_s = interval_s
        self.dir = dir
        self.history = SeriesHistory(history_points)
        self._rollup_fn = rollup_fn
        self._extra_fn = extra_fn
        #: drop ghost ``worker=`` series whose snapshot is this much
        #: older than the fleet's newest (None keeps every label —
        #: see render_rollup)
        self.stale_workers_after_s = stale_workers_after_s
        #: pid -> wall of the worker's last role reassignment (the
        #: autoscaler repurposing it training↔serving): its label
        #: series are suppressed until a snapshot newer than that wall
        #: arrives (see render_rollup's ``retired``)
        self._retired: dict = {}
        self._labels = labels
        self._text = "# dtx exporter: no tick yet\n"
        self._text_lock = threading.Lock()
        self._server = None
        self.port = None
        if port is None:
            env = os.environ.get(ENV_METRICS_PORT)
            port = int(env) if env and env.isdigit() else None
        if port is not None:
            self._server = http.server.ThreadingHTTPServer(
                ("127.0.0.1", port), _ScrapeHandler)
            self._server.exporter = self
            self.port = self._server.server_address[1]
            threading.Thread(target=self._server.serve_forever,
                             daemon=True,
                             name="dtx-metrics-http").start()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtx-metrics-export")
        self._thread.start()

    # -- the tick ---------------------------------------------------------
    def tick(self) -> str:
        wall = time.time()
        snap = self.reg.snapshot()
        self.history.record(snap, wall)
        lines = [f"# dtx metrics  wall={wall:.3f}"]
        lines += render_prometheus(snap, labels=self._labels)
        if self._rollup_fn is not None:
            try:
                rollup = self._rollup_fn()
                if rollup:
                    lines += render_rollup(
                        rollup,
                        stale_after_s=self.stale_workers_after_s,
                        retired=self._retired or None)
            except Exception:
                lines.append("# rollup_fn failed")
        if self._extra_fn is not None:
            try:
                lines += list(self._extra_fn() or [])
            except Exception:
                lines.append("# extra_fn failed")
        text = "\n".join(lines) + "\n"
        with self._text_lock:
            self._text = text
        if self.dir:
            try:
                os.makedirs(self.dir, exist_ok=True)
                path = os.path.join(self.dir, LIVE_METRICS_FILE)
                tmp = path + ".tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    f.write(text)
                os.replace(tmp, path)    # scrapers never see a torn file
            except OSError:
                pass
        return text

    def retire_worker(self, pid, wall: "float | None" = None):
        """Mark a worker as reassigned (role change / slot removed by a
        scale action) at ``wall`` (default now): its ``worker=`` label
        series vanish from the scrape until it publishes a snapshot
        newer than that instant."""
        self._retired[pid] = wall if wall is not None else time.time()

    def unretire_worker(self, pid):
        self._retired.pop(pid, None)

    def scrape(self) -> str:
        """Latest rendered exposition text (what ``/metrics`` serves)."""
        with self._text_lock:
            return self._text

    def _run(self):
        # first tick immediately: a short run must still leave a scrape
        try:
            self.tick()
        except Exception:
            pass
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                pass                    # registry torn down mid-run

    def stop(self):
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5.0)
            try:
                self.tick()             # final flush
            except Exception:
                pass
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
