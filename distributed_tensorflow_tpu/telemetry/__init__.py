"""Unified telemetry: metrics registry, structured events, fleet rollups.

The observability backbone (≙ the reference's tf.monitoring gauges +
coordinator monitored_timer metrics + tf.summary event files, SURVEY.md
§2.5/§5.5), in four pieces:

- :mod:`registry`  — MetricsRegistry: namespaced Counter / Gauge /
  Histogram / Timer instruments with snapshot/delta export. Every
  existing instrument set (coordinator/metric_utils.py, utils/summary.py
  gauges, resilience/health.py, input pipeline stage stats,
  resilience/faults.py firings) registers through it.
- :mod:`events`    — structured run events: ``span``/``event`` API
  writing append-only JSONL with monotonic timestamps; rendered by
  ``tools/obs_report.py``.
- :mod:`aggregate` — cross-host aggregation: workers publish snapshots
  through the coordination KV store; the coordinator merges fleet
  rollups (sum/max/p50/p95) and emits them to TensorBoard.
- :mod:`stall`     — StallDetector layered on coordinator/watchdog.py:
  no step within ``factor`` x trailing median -> ``stall.suspected``
  naming the slowest worker, non-fatal.
- :mod:`trace`     — cross-host trace assembly: every process's JSONL
  merged into ONE Chrome-trace/Perfetto JSON with per-host clock
  offsets estimated from barrier/heartbeat sync points, span causality
  via ``span_id`` flow arrows, and a bottleneck classifier (input- /
  comm- / compute- / checkpoint- / recovery-bound) with explicit
  thresholds; rendered by ``tools/trace_report.py``.
- :mod:`exporter`  — LIVE export: bounded ring-buffer time-series per
  instrument, Prometheus text endpoint (``DTX_METRICS_PORT``) with a
  ``metrics-live.prom`` file fallback, fleet merge over KV rollups.
- :mod:`goodput`   — goodput/badput ledger pricing every wall-clock
  second into productive step time vs named waste buckets (startup,
  infeed wait, checkpoint block, recovery, preempt replay, idle) with
  ``wall == goodput + Σ badput`` enforced; rendered/gated by
  ``tools/health_report.py``.
- :mod:`slo`       — declarative serving SLOs (p99 latency, TTFT,
  availability) evaluated over multi-window burn rates, live and as CI
  gates.

Quick start::

    from distributed_tensorflow_tpu import telemetry

    telemetry.configure("/tmp/run1/telemetry")     # per-process JSONL
    step_t = telemetry.timer("training/step_time")
    with telemetry.span("train.step", step=i), step_t.time():
        state, metrics = step_fn(state, batch)

Telemetry is OFF by default: with no event log configured and no
publisher started, instrumented call sites cost one None check.
"""

from distributed_tensorflow_tpu.telemetry.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    Timer,
    counter,
    gauge,
    get_registry,
    histogram,
    timer,
)
from distributed_tensorflow_tpu.telemetry.events import (
    ENV_TELEMETRY_DIR,
    EventLog,
    EventLogCorruptError,
    configure,
    enabled,
    event,
    event_log_path,
    get_event_log,
    read_events,
    read_run,
    shutdown,
    span,
)
from distributed_tensorflow_tpu.telemetry.aggregate import (
    FleetAggregator,
    MetricsPublisher,
    RollupTopology,
    collect_rollup,
    collect_rollup_tree,
    merge_rollup,
    publish_snapshot,
    read_snapshots,
    rollup_scalars,
    run_duties,
)
from distributed_tensorflow_tpu.telemetry.stall import (
    StallDetector,
    suspect_worker,
)
from distributed_tensorflow_tpu.telemetry.trace import (
    BOTTLENECK_THRESHOLDS,
    assemble_run,
    assemble_trace,
    classify_run,
    estimate_clock_offsets,
    overlap_efficiency,
    trace_completeness,
    write_trace,
)
from distributed_tensorflow_tpu.telemetry.exporter import (
    ENV_METRICS_PORT,
    LIVE_METRICS_FILE,
    MetricsExporter,
    SeriesHistory,
    render_prometheus,
    render_rollup,
)
from distributed_tensorflow_tpu.telemetry.goodput import (
    BADPUT_BUCKETS,
    GoodputLedger,
    ledger_from_events,
    ledger_from_run,
)
from distributed_tensorflow_tpu.telemetry.slo import (
    DEFAULT_BURN_WINDOWS,
    SLO,
    SLOMonitor,
    default_serving_slos,
    evaluate_records,
    records_from_events,
    windows_for_span,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "Timer",
    "counter", "gauge", "get_registry", "histogram", "timer",
    "ENV_TELEMETRY_DIR", "EventLog", "EventLogCorruptError", "configure",
    "enabled", "event", "event_log_path", "get_event_log", "read_events",
    "read_run", "shutdown", "span",
    "FleetAggregator", "MetricsPublisher", "RollupTopology",
    "collect_rollup", "collect_rollup_tree", "merge_rollup",
    "publish_snapshot", "read_snapshots", "rollup_scalars",
    "run_duties",
    "StallDetector", "suspect_worker",
    "BOTTLENECK_THRESHOLDS", "assemble_run", "assemble_trace",
    "classify_run", "estimate_clock_offsets", "overlap_efficiency",
    "trace_completeness", "write_trace",
    "ENV_METRICS_PORT", "LIVE_METRICS_FILE", "MetricsExporter",
    "SeriesHistory", "render_prometheus", "render_rollup",
    "BADPUT_BUCKETS", "GoodputLedger", "ledger_from_events",
    "ledger_from_run",
    "DEFAULT_BURN_WINDOWS", "SLO", "SLOMonitor", "default_serving_slos",
    "evaluate_records", "records_from_events", "windows_for_span",
]
