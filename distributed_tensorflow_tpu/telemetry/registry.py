"""MetricsRegistry: one namespaced home for every instrument.

The framework grew four disconnected instrument sets — coordinator
counters/timers (coordinator/metric_utils.py), tf.monitoring-style
gauges (utils/summary.py), worker-health bookkeeping
(resilience/health.py), and input-pipeline stage stats
(utils/profiler.py via input/dataset.py). This registry unifies them
under one namespaced API (``"coordinator/closure_execution"``,
``"input/prefetch/elements"``) with four typed instruments:

- :class:`Counter`    — monotonically increasing int
- :class:`Gauge`      — latest value (any JSON-serializable type)
- :class:`Histogram`  — streaming value distribution with bounded
  reservoir percentiles (p50/p95/p99) plus exact count/sum/min/max
- :class:`Timer`      — accumulating duration timer whose samples also
  feed a histogram (so rollups can report duration percentiles)

Export is via :meth:`MetricsRegistry.snapshot` (a plain JSON-ready
dict) and :meth:`MetricsRegistry.delta` (what changed since a previous
snapshot — the unit workers publish cross-host, keeping repeated
publishes O(changed), not O(all)).

External instrument sets that keep their own storage (pipeline stage
stats, health trackers) join through **collectors**: a callable
returning ``{name: gauge-like value}`` merged into every snapshot
(:meth:`register_collector`). This keeps the hot paths of those
subsystems untouched — the registry reads them only at export time.

Everything is thread-safe; instrument handles are cheap to hold and
get-or-create is idempotent (same name + same type returns the same
instrument; same name + different type raises).
"""

from __future__ import annotations

import contextlib
import threading
import time


class Counter:
    """Monotonic counter."""

    kind = "counter"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = 0
        self._lock = threading.Lock()

    def increment(self, n: int = 1):
        with self._lock:
            self._value += n

    inc = increment

    @property
    def value(self) -> int:
        with self._lock:
            return self._value

    def export(self) -> dict:
        return {"type": "counter", "value": self.value}


class Gauge:
    """Latest-value cell (numbers, strings — anything JSON-ready)."""

    kind = "gauge"

    def __init__(self, name: str, description: str = ""):
        self.name = name
        self.description = description
        self._value = None
        self._lock = threading.Lock()

    def set(self, value):
        with self._lock:
            self._value = value

    @property
    def value(self):
        with self._lock:
            return self._value

    def export(self) -> dict:
        return {"type": "gauge", "value": self.value}


class Histogram:
    """Streaming distribution: exact count/sum/min/max + reservoir
    percentiles.

    The reservoir keeps the most recent ``window`` samples (a trailing
    window, not uniform sampling): telemetry questions are about what a
    run is doing NOW — trailing p50/p95 step time is the stall
    detector's reference signal — so recency beats whole-run uniformity.
    """

    kind = "histogram"

    def __init__(self, name: str, description: str = "",
                 window: int = 512):
        self.name = name
        self.description = description
        self._window = window
        self._samples: list[float] = []
        self._next = 0                   # ring-buffer write cursor
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def record(self, value: float):
        value = float(value)
        with self._lock:
            self._count += 1
            self._sum += value
            if self._min is None or value < self._min:
                self._min = value
            if self._max is None or value > self._max:
                self._max = value
            if len(self._samples) < self._window:
                self._samples.append(value)
            else:
                self._samples[self._next] = value
                self._next = (self._next + 1) % self._window

    observe = record

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    def percentile(self, q: float) -> float | None:
        """Trailing-window percentile, q in [0, 100]."""
        with self._lock:
            if not self._samples:
                return None
            s = sorted(self._samples)
        idx = min(len(s) - 1, max(0, int(round(q / 100 * (len(s) - 1)))))
        return s[idx]

    def export(self) -> dict:
        with self._lock:
            s = sorted(self._samples)
            out = {"type": "histogram", "count": self._count,
                   "sum": round(self._sum, 9), "min": self._min,
                   "max": self._max}
        if s:
            def pct(q):
                return s[min(len(s) - 1,
                             max(0, int(round(q / 100 * (len(s) - 1)))))]
            out.update(p50=pct(50), p95=pct(95), p99=pct(99))
        return out


class Timer:
    """Accumulating duration timer; samples feed an internal histogram
    so exports carry duration percentiles (≙ monitored_timer)."""

    kind = "timer"

    def __init__(self, name: str, description: str = "",
                 window: int = 512):
        self.name = name
        self.description = description
        self._hist = Histogram(name, window=window)

    @contextlib.contextmanager
    def time(self):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._hist.record(time.perf_counter() - start)

    def record(self, seconds: float):
        self._hist.record(seconds)

    @property
    def count(self) -> int:
        return self._hist.count

    @property
    def total_seconds(self) -> float:
        with self._hist._lock:
            return self._hist._sum

    @property
    def average_seconds(self) -> float:
        with self._hist._lock:
            return self._hist._sum / self._hist._count \
                if self._hist._count else 0.0

    def export(self) -> dict:
        out = self._hist.export()
        out["type"] = "timer"
        return out


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram,
          "timer": Timer}


class MetricsRegistry:
    """Named, typed instrument store with snapshot/delta export."""

    def __init__(self):
        self._instruments: dict[str, object] = {}
        self._collectors: dict[str, object] = {}
        self._lock = threading.Lock()

    # -- get-or-create ----------------------------------------------------
    def _instrument(self, cls, name: str, description: str = "", **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{inst.kind}, requested {cls.kind}")
                return inst
            inst = cls(name, description, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, description: str = "") -> Counter:
        return self._instrument(Counter, name, description)

    def gauge(self, name: str, description: str = "") -> Gauge:
        return self._instrument(Gauge, name, description)

    def histogram(self, name: str, description: str = "",
                  window: int = 512) -> Histogram:
        return self._instrument(Histogram, name, description,
                                window=window)

    def timer(self, name: str, description: str = "",
              window: int = 512) -> Timer:
        return self._instrument(Timer, name, description, window=window)

    def register(self, instrument, name: str | None = None):
        """Adopt an externally constructed instrument (back-compat shims
        in coordinator/metric_utils.py construct instruments directly).
        Re-registering a name replaces the previous instrument — the
        newest instance is the live one a snapshot reads (per-object
        lifecycles, e.g. one closure queue per Cluster, stay intact).
        """
        with self._lock:
            self._instruments[name or instrument.name] = instrument
        return instrument

    def unregister(self, name: str):
        with self._lock:
            self._instruments.pop(name, None)

    def get(self, name: str):
        with self._lock:
            return self._instruments.get(name)

    def names(self) -> list[str]:
        with self._lock:
            return sorted(self._instruments)

    # -- collectors -------------------------------------------------------
    def register_collector(self, prefix: str, fn):
        """``fn() -> {name: value}``; merged into every snapshot under
        ``<prefix>/<name>`` as gauge entries. For instrument sets that
        keep their own storage (pipeline stage stats, health trackers).
        """
        with self._lock:
            self._collectors[prefix] = fn

    def unregister_collector(self, prefix: str):
        with self._lock:
            self._collectors.pop(prefix, None)

    # -- export -----------------------------------------------------------
    def snapshot(self) -> dict:
        """All instruments as one JSON-ready dict {name: export-dict}."""
        with self._lock:
            instruments = dict(self._instruments)
            collectors = dict(self._collectors)
        out = {name: inst.export() for name, inst in instruments.items()}
        for prefix, fn in collectors.items():
            try:
                collected = fn()
            except Exception:          # a broken collector must not
                continue               # take down metric export
            for name, value in collected.items():
                out[f"{prefix}/{name}"] = {"type": "gauge", "value": value}
        return out

    def delta(self, previous: dict | None) -> dict:
        """Entries that changed since ``previous`` (a prior snapshot).
        Workers publish deltas on their periodic schedule so repeat
        publishes cost O(changed). Returns the full snapshot when
        ``previous`` is None."""
        snap = self.snapshot()
        if not previous:
            return snap
        return {k: v for k, v in snap.items() if previous.get(k) != v}

    def reset(self):
        """Drop every instrument and collector (test isolation)."""
        with self._lock:
            self._instruments.clear()
            self._collectors.clear()


_default = MetricsRegistry()
_default_lock = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry every subsystem registers in."""
    return _default


# module-level conveniences against the default registry
def counter(name: str, description: str = "") -> Counter:
    return _default.counter(name, description)


def gauge(name: str, description: str = "") -> Gauge:
    return _default.gauge(name, description)


def histogram(name: str, description: str = "",
              window: int = 512) -> Histogram:
    return _default.histogram(name, description, window=window)


def timer(name: str, description: str = "", window: int = 512) -> Timer:
    return _default.timer(name, description, window=window)
