"""Cross-host trace timeline: merge per-process event logs into one
Chrome-trace/Perfetto JSON, attribute step time to phases, name the
bottleneck.

The reference answers "why is my pod slow?" with the tf.profiler /
TensorBoard-profile toolchain (SURVEY §5.1 — trace viewer + input
pipeline analyzer over XPlane). ``utils/profiler.py`` keeps that capture
surface, but its output is opaque to this framework's own tooling and
the ``telemetry/`` JSONL span logs stay trapped in per-host files. This
module is the missing layer between the two:

- **Trace assembly** (:func:`assemble_trace` / :func:`assemble_run`):
  every worker's ``events-<pid>.jsonl`` (plus the recovery supervisor's)
  merges into ONE Chrome-trace JSON — open it in Perfetto
  (https://ui.perfetto.dev) or ``chrome://tracing``. Torn-tail tolerant
  like :func:`events.read_events`; spans carrying a ``span_id`` (remote
  dispatch closures, tiered checkpoint commits) become Perfetto *flow
  arrows*, so dispatch→execute→result and capture→local→durable render
  as causally linked tracks.
- **Clock alignment** (:func:`estimate_clock_offsets`): per-host wall
  clocks are aligned from sync points the run already produces —
  ``clock.sync`` events emitted when a coordination-service barrier
  releases (every participant exits within the release latency, so their
  recorded walls read the same instant), and the supervisor's
  ``clock.hb`` observations pairing a worker heartbeat's self-reported
  wall with the file's mtime (both stamped within the write latency).
  Accuracy is bounded by those latencies: sub-ms in-process, ~RTT
  across a real fabric.
- **Bottleneck classification** (:func:`classify_run`): from per-step
  phase attribution (compute / collective / infeed wait / host callback
  / checkpoint blocking — see ``training/loops.StepTelemetry``) plus the
  recovery timeline, a run is named input-bound / comm-bound /
  compute-bound / checkpoint-bound / recovery-bound against the explicit
  thresholds in :data:`BOTTLENECK_THRESHOLDS`. ``tools/obs_report.py``
  renders the table and ``--check`` gates on the class in CI.
- **Overlap accounting** (:func:`overlap_efficiency`): the fraction of
  collective time hidden behind the remaining backward pass — the direct
  measure of the bucketed-collective win (see
  ``parallel/collectives.simulate_overlap`` for the schedule model).
"""

from __future__ import annotations

import collections
import json
import os
import statistics
import zlib

from distributed_tensorflow_tpu.telemetry import events as _events

#: Event emitted by CoordinationServiceAgent.barrier at barrier release:
#: every participant records its wall clock for the same shared instant.
CLOCK_SYNC_EVENT = "clock.sync"

#: Event emitted by the recovery supervisor when it observes a fresh
#: worker heartbeat: pairs the worker's self-reported wall with the
#: heartbeat file's mtime (supervisor/filesystem clock domain).
CLOCK_HB_EVENT = "clock.hb"

#: The synthetic Chrome-trace pid block non-numeric process ids (the
#: recovery supervisor) are mapped into.
_SYNTHETIC_PID_BASE = 100000


# ---------------------------------------------------------------------------
# Clock-offset estimation
# ---------------------------------------------------------------------------

def _pairwise_offsets(events_by_pid: dict) -> dict:
    """Collect pairwise clock-offset observations.

    Returns ``{(a, b): [delta, ...]}`` where each ``delta`` observes
    ``offset_a - offset_b`` (with ``offset_p`` = how far pid p's wall
    clock runs AHEAD of true time): for a shared instant read as
    ``w_a`` by a and ``w_b`` by b, ``w_a - w_b = offset_a - offset_b``.
    """
    obs: dict = collections.defaultdict(list)

    # clock.sync: group by (gen, barrier name, per-process occurrence
    # index) — the i-th crossing of barrier NAME in generation G is the
    # same shared instant on every participant.
    sync_walls: dict = collections.defaultdict(dict)
    for pid, events in events_by_pid.items():
        counts: dict = collections.Counter()
        for ev in events:
            if ev.get("ev") != CLOCK_SYNC_EVENT:
                continue
            name = ev.get("barrier", "?")
            key = (ev.get("gen", 0), name, counts[name])
            counts[name] += 1
            wall = ev.get("wall")
            if isinstance(wall, (int, float)):
                sync_walls[key][pid] = wall
    for walls in sync_walls.values():
        pids = sorted(walls, key=str)
        for i, a in enumerate(pids):
            for b in pids[i + 1:]:
                obs[(a, b)].append(walls[a] - walls[b])

    # clock.hb: the OBSERVING process (usually "supervisor") pairs a
    # worker's self-reported wall with the heartbeat file's mtime in its
    # own clock domain: offset_worker - offset_observer ≈ wall - mtime.
    for pid, events in events_by_pid.items():
        for ev in events:
            if ev.get("ev") != CLOCK_HB_EVENT:
                continue
            worker = ev.get("worker")
            w_wall, mtime = ev.get("worker_wall"), ev.get("mtime")
            if (worker is None or worker == pid
                    or not isinstance(w_wall, (int, float))
                    or not isinstance(mtime, (int, float))):
                continue
            obs[(worker, pid)].append(w_wall - mtime)
    return dict(obs)


def estimate_clock_offsets(events_by_pid: dict,
                           reference=None) -> dict:
    """Per-process clock offsets (seconds) relative to ``reference``.

    ``aligned_wall = wall - offset[pid]`` puts every process on the
    reference clock. Offsets come from the run's own sync points (see
    :func:`_pairwise_offsets`); per edge the MEDIAN observation is used
    (robust to one slow barrier release). Processes unreachable from the
    reference through any sync edge get offset 0.0 (flagged by
    :func:`assemble_trace` metadata).

    ``reference`` defaults to pid 0 when present, else the smallest
    numeric pid, else the first key.
    """
    pids = list(events_by_pid)
    if not pids:
        return {}
    if reference is None:
        numeric = sorted(p for p in pids if isinstance(p, int))
        reference = (0 if 0 in pids else
                     numeric[0] if numeric else pids[0])
    edges: dict = collections.defaultdict(dict)
    for (a, b), deltas in _pairwise_offsets(events_by_pid).items():
        d = statistics.median(deltas)
        edges[a][b] = d          # offset_a - offset_b = d
        edges[b][a] = -d
    offsets = {p: 0.0 for p in pids}
    seen = {reference}
    frontier = [reference]
    while frontier:
        a = frontier.pop()
        for b, d in edges.get(a, {}).items():
            if b in seen or b not in offsets:
                continue
            # d = offset_a - offset_b  ->  offset_b = offset_a - d
            offsets[b] = offsets[a] - d
            seen.add(b)
            frontier.append(b)
    offsets["__unaligned__"] = [p for p in pids if p not in seen]
    return offsets


# ---------------------------------------------------------------------------
# Chrome-trace assembly
# ---------------------------------------------------------------------------

#: Dotted-namespace prefix -> named track (Chrome-trace tid). Everything
#: else lands on a track named after its first namespace component.
_TRACK_ORDER = ["train", "serve", "checkpoint", "recovery", "dispatch",
                "worker", "pipeline", "input", "fault", "stall",
                "scaling", "profiler", "clock", "run"]

_SKIP_ARG_FIELDS = frozenset({"ev", "t", "wall", "pid", "dur_s"})


def _track(name: str) -> str:
    return name.split(".", 1)[0] if isinstance(name, str) else "other"


def _numeric_pid(pid, synthetic: dict) -> int:
    if isinstance(pid, int):
        return pid
    if pid not in synthetic:
        synthetic[pid] = _SYNTHETIC_PID_BASE + len(synthetic)
    return synthetic[pid]


def _flow_id(span_id: str) -> int:
    return zlib.crc32(str(span_id).encode()) & 0x7FFFFFFF


def assemble_trace(events_by_pid: dict, *, offsets: dict | None = None,
                   run_id: str | None = None) -> dict:
    """Merge per-process event lists into one Chrome-trace JSON dict.

    - every process becomes a trace *process* (the supervisor gets a
      synthetic numeric pid, named in metadata);
    - events within a process land on *threads* named by event namespace
      (``train``, ``checkpoint``, ``recovery`` ...);
    - events carrying ``dur_s`` become complete slices (``ph: X``; the
      JSONL record is written at span END, so the slice starts at
      ``wall - dur_s``), the rest instant events (``ph: i``);
    - events sharing a ``span_id`` are joined by flow arrows in record
      order — the dispatch→execute→result and capture→commit chains;
    - timestamps are wall clocks aligned by ``offsets`` (defaults to
      :func:`estimate_clock_offsets`), rebased so the earliest event is
      t=0.

    The result round-trips through ``json.dumps`` and loads in Perfetto
    / ``chrome://tracing`` as-is.
    """
    if offsets is None:
        offsets = estimate_clock_offsets(events_by_pid)
    unaligned = offsets.get("__unaligned__", [])
    synthetic: dict = {}
    trace_events: list[dict] = []

    # first pass: aligned start times (for rebasing + flow ordering)
    aligned: dict = {}
    t0 = None
    for pid, events in events_by_pid.items():
        off = offsets.get(pid, 0.0)
        for i, ev in enumerate(events):
            wall = ev.get("wall")
            if not isinstance(wall, (int, float)):
                continue
            dur = ev.get("dur_s")
            dur = dur if isinstance(dur, (int, float)) and dur >= 0 else 0.0
            start = wall - off - dur
            aligned[(pid, i)] = (start, dur)
            t0 = start if t0 is None else min(t0, start)
    t0 = t0 or 0.0

    flows: dict = collections.defaultdict(list)
    for pid, events in sorted(events_by_pid.items(), key=lambda kv:
                              str(kv[0])):
        cpid = _numeric_pid(pid, synthetic)
        label = (f"worker {pid}" if isinstance(pid, int) else str(pid))
        trace_events.append({"ph": "M", "pid": cpid, "tid": 0,
                             "name": "process_name",
                             "args": {"name": label + (
                                 " (clock unaligned)"
                                 if pid in unaligned else "")}})
        tracks: dict = {}
        for i, ev in enumerate(events):
            if (pid, i) not in aligned:
                continue
            start, dur = aligned[(pid, i)]
            name = ev.get("ev", "?")
            track = _track(name)
            if track not in tracks:
                tid = len(tracks) + 1
                tracks[track] = tid
                trace_events.append({
                    "ph": "M", "pid": cpid, "tid": tid,
                    "name": "thread_name", "args": {"name": track}})
            tid = tracks[track]
            ts = round((start - t0) * 1e6, 3)
            args = {k: v for k, v in ev.items()
                    if k not in _SKIP_ARG_FIELDS}
            rec = {"name": name, "cat": track, "pid": cpid, "tid": tid,
                   "ts": ts, "args": args}
            if dur > 0:
                rec.update(ph="X", dur=round(dur * 1e6, 3))
            else:
                rec.update(ph="i", s="t")
            trace_events.append(rec)
            span_id = ev.get("span_id")
            if span_id is not None:
                flows[str(span_id)].append(
                    (start, {"pid": cpid, "tid": tid, "ts": ts}))

    # flow arrows: s -> t ... t -> f in aligned time order
    n_links = 0
    for span_id, points in flows.items():
        if len(points) < 2:
            continue
        points.sort(key=lambda p: p[0])
        fid = _flow_id(span_id)
        for j, (_, where) in enumerate(points):
            ph = ("s" if j == 0 else
                  "f" if j == len(points) - 1 else "t")
            rec = {"ph": ph, "id": fid, "name": span_id, "cat": "flow"}
            rec.update(where)
            if ph == "f":
                rec["bp"] = "e"
            trace_events.append(rec)
        n_links += len(points) - 1

    meta = {
        "run_id": run_id,
        "clock_offsets_s": {str(p): round(v, 6)
                            for p, v in offsets.items()
                            if p != "__unaligned__"},
        "clock_unaligned": [str(p) for p in unaligned],
        "flow_links": n_links,
        "processes": sorted(str(p) for p in events_by_pid),
    }
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": meta}


def assemble_run(run_dir: str, *, reference=None) -> dict:
    """Read every ``events-*.jsonl`` under ``run_dir`` (torn tails
    tolerated) and assemble the merged trace."""
    events_by_pid = _events.read_run(run_dir)
    offsets = estimate_clock_offsets(events_by_pid, reference=reference)
    return assemble_trace(events_by_pid, offsets=offsets,
                          run_id=os.path.basename(
                              os.path.normpath(run_dir)))


def write_trace(run_dir: str, out_path: str | None = None) -> str:
    """Assemble ``run_dir`` and write the Chrome-trace JSON (default:
    ``<run_dir>/trace.json``). Returns the output path."""
    out_path = out_path or os.path.join(run_dir, "trace.json")
    trace = assemble_run(run_dir)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
        f.write("\n")
    return out_path


# ---------------------------------------------------------------------------
# Completeness: is every generation's telemetry present and mergeable?
# ---------------------------------------------------------------------------

def trace_completeness(events_by_pid: dict) -> dict:
    """Verify the merged timeline covers every cluster generation.

    A generation counts as covered when at least one WORKER event
    carries it (records are stamped ``gen`` by EventLog for generation
    > 0; generation-0 events are unstamped). Generations are enumerated
    from the supervisor's ``recovery.generation_start`` timeline when
    present, else from the stamps themselves. A SIGKILL'd worker's torn
    tail must not break this — callers read with the default
    torn-tail-tolerant reader.

    Returns ``{"generations": {gen: {"worker_events": n, "pids":
    [...]}}, "missing": [gen, ...], "complete": bool}``.
    """
    expected: set[int] = set()
    for events in events_by_pid.values():
        for ev in events:
            if ev.get("ev") == "recovery.generation_start":
                g = ev.get("generation")
                if isinstance(g, int):
                    expected.add(g)
    per_gen: dict = collections.defaultdict(
        lambda: {"worker_events": 0, "pids": set()})
    for pid, events in events_by_pid.items():
        if not isinstance(pid, int):
            continue                     # supervisor: not a worker
        for ev in events:
            g = ev.get("gen", 0)
            if not isinstance(g, int):
                continue
            per_gen[g]["worker_events"] += 1
            per_gen[g]["pids"].add(pid)
    if not expected:
        expected = set(per_gen) or {0}
    missing = sorted(g for g in expected
                     if per_gen.get(g, {}).get("worker_events", 0) == 0)
    return {
        "generations": {g: {"worker_events": d["worker_events"],
                            "pids": sorted(d["pids"])}
                        for g, d in sorted(per_gen.items())},
        "expected_generations": sorted(expected),
        "missing": missing,
        "complete": not missing,
    }


# ---------------------------------------------------------------------------
# Overlap efficiency (the bucketed-collective win, measured)
# ---------------------------------------------------------------------------

def overlap_efficiency(total_collective_s: float,
                       exposed_collective_s: float) -> float | None:
    """Fraction of collective time hidden behind compute.

    ``total_collective_s`` is what the collectives cost run back-to-back
    (serial); ``exposed_collective_s`` is how much of that actually
    extended the step's critical path. 1.0 = fully overlapped, 0.0 = the
    schedule hid nothing. None when there was no collective at all.
    """
    if total_collective_s <= 0:
        return None
    eff = 1.0 - exposed_collective_s / total_collective_s
    return max(0.0, min(1.0, eff))


# ---------------------------------------------------------------------------
# Bottleneck classification
# ---------------------------------------------------------------------------

#: Explicit thresholds, in the priority order of the table below: a run
#: triggers a class when the measured fraction meets the threshold; when
#: several trigger, the LARGEST ratio (measured / threshold) wins.
#:
#: - ``recovery`` — recovery downtime (sum of death→restored MTTRs) as a
#:   fraction of the run's wall span
#: - ``infeed``   — step-loop time blocked on the input pipeline, as a
#:   fraction of total step time (InfeedLoop.wait_fraction's signal)
#: - ``checkpoint`` — step-loop time blocked capturing/committing
#:   checkpoints, as a fraction of total step time
#: - ``collective`` — EXPOSED collective time (not hidden behind the
#:   backward pass), as a fraction of total step time
BOTTLENECK_THRESHOLDS = {
    "recovery": 0.20,
    "infeed": 0.15,
    "checkpoint": 0.10,
    "collective": 0.25,
}

_CLASS_NAMES = {
    "recovery": "recovery-bound",
    "infeed": "input-bound",
    "checkpoint": "checkpoint-bound",
    "collective": "comm-bound",
}


def classify_run(fractions: dict) -> dict:
    """Name a run's bottleneck from measured phase fractions.

    ``fractions`` maps the :data:`BOTTLENECK_THRESHOLDS` keys to
    measured fractions (missing/None = 0). Returns ``{"class": name,
    "trigger": key | None, "measured": {...}, "thresholds": {...},
    "reasons": [...]}`` where ``class`` is one of input-bound /
    comm-bound / compute-bound / checkpoint-bound / recovery-bound.
    A run that trips no threshold is compute-bound — the healthy state.
    """
    measured = {k: float(fractions.get(k) or 0.0)
                for k in BOTTLENECK_THRESHOLDS}
    reasons = []
    best_key, best_ratio = None, 0.0
    for key, thresh in BOTTLENECK_THRESHOLDS.items():
        frac = measured[key]
        if frac >= thresh:
            reasons.append(f"{key} fraction {frac:.1%} >= "
                           f"threshold {thresh:.0%}")
            ratio = frac / thresh
            if ratio > best_ratio:
                best_key, best_ratio = key, ratio
    return {
        "class": _CLASS_NAMES.get(best_key, "compute-bound"),
        "trigger": best_key,
        "measured": {k: round(v, 4) for k, v in measured.items()},
        "thresholds": dict(BOTTLENECK_THRESHOLDS),
        "reasons": reasons,
    }
