"""Cause-itemized production-day audit: score a day from its logs alone.

The observability capstone over everything the repo already emits
(ROADMAP item 4): given ONE telemetry run directory — per-worker step
events, the recovery supervisor's transition log, the serving fleet's
completion records, the day driver's phase markers — answer, with no
access to any in-process state, the two questions a production
retrospective starts with:

1. **Where did the hardware-seconds go?** The fleet goodput identity
   (``wall == goodput + Σ badput`` across every worker and generation,
   :mod:`~distributed_tensorflow_tpu.telemetry.goodput`) is recomputed
   and its residual gated to ±1%; per-phase goodput breaks the same
   seconds down along the day's diurnal curve.
2. **Where did the SLO budget go?** Each SLO's budget spend
   (:mod:`~distributed_tensorflow_tpu.telemetry.slo`) is itemized by
   *attributed cause*: every violating completion record is matched
   against cause windows derived purely from logged control-plane
   transitions — recovery reforms, deliberate scale transitions,
   rollout swaps, KV migrations, preemption replay, flash-spike
   overload — with an explicit ``unattributed`` remainder the CI gate
   caps (an unexplained burn is an observability bug: some subsystem
   degraded service without logging why).

Cause attribution is deliberately coarse-but-honest: a record is
attributed when its service interval ``[wall - latency, wall]``
overlaps a cause window, record-level evidence (``replayed_tokens``)
wins over windows, and window causes apply in severity order
(``recovery`` > ``scale_transition`` > ``rollout`` > ``kv_migrate`` >
``spike_overload``), so a request that is late because a rack died
*during* a spike is priced to the rack, not the spike. Per-cause
spends partition the total: they sum exactly to each SLO's
``budget_consumed``.

Consumed by ``tools/day_report.py`` (render + ``--check`` gates),
``tools/obs_report.py`` / ``tools/health_report.py`` (per-cause budget
table, day-phase breakdown), ``chaos_sweep.py --day`` and
``bench.py --day``.
"""

from __future__ import annotations

#: Attribution causes, in priority order (highest first). ``recovery``
#: outranks everything: a failure reform degrades service no matter
#: what else is happening; ``spike_overload`` is last — pure load with
#: no control-plane event to blame.
CAUSES = ("recovery", "scale_transition", "rollout", "kv_migrate",
          "preempt_replay", "spike_overload")

#: Restore-tier rank, warmest first (the recovery ladder). The day
#: gate requires a rack loss to recover at ``peer`` or warmer.
TIER_RANK = {"host": 0, "memory": 0, "peer": 1, "local": 2,
             "durable": 3, "none": 4}

_WARM_TIERS = frozenset(t for t, r in TIER_RANK.items() if r <= 1)


def _walls(events_by_pid, name: str):
    """(wall, event) pairs of every ``name`` event, wall-sorted."""
    out = []
    for events in events_by_pid.values():
        for ev in events:
            if ev.get("ev") == name and \
                    isinstance(ev.get("wall"), (int, float)):
                out.append((ev["wall"], ev))
    out.sort(key=lambda p: p[0])
    return out


def day_records(events_by_pid) -> "list[dict]":
    """Completion records from ``serve.request`` events — the
    :func:`telemetry.slo.records_from_events` mapping plus the
    attribution evidence those drop (``replayed_tokens``, the emitting
    pid, the driver-stamped request class)."""
    records = []
    for pid, events in events_by_pid.items():
        for ev in events:
            if ev.get("ev") != "serve.request":
                continue
            records.append({
                "wall": ev.get("wall"),
                "latency_s": ev.get("dur_s"),
                "ttft_s": ev.get("ttft_s"),
                "model_version": ev.get("model_version"),
                "ok": not ev.get("error"),
                "pid": pid,
                "kind": ev.get("kind"),
                "replayed_tokens": ev.get("replayed_tokens"),
            })
    records.sort(key=lambda r: r.get("wall") or 0.0)
    return records


def phase_spans(events_by_pid) -> "list[dict]":
    """The day's phase timeline from the driver's ``day.phase``
    markers: each marker opens a phase, the next one (or ``day.end``)
    closes it."""
    marks = _walls(events_by_pid, "day.phase")
    ends = _walls(events_by_pid, "day.end")
    out = []
    for i, (wall, ev) in enumerate(marks):
        if i + 1 < len(marks):
            end = marks[i + 1][0]
        elif ends:
            end = ends[-1][0]
        else:
            end = wall
        out.append({"phase": ev.get("phase", f"phase{i}"),
                    "start": wall, "end": end,
                    "dur_s": round(max(0.0, end - wall), 6),
                    "rate_rps": ev.get("rate_rps")})
    return out


def cause_windows(events_by_pid, *,
                  recovery_backdate_s: float = 0.25,
                  recovery_drain_s: float = 1.0,
                  scale_lead_s: float = 0.5,
                  scale_drain_s: float = 1.0,
                  span_margin_s: float = 0.25,
                  spike_drain_s: float = 2.0) -> "dict[str, list]":
    """{cause: [(lo, hi), ...]} attribution windows, derived purely
    from logged control-plane transitions.

    - ``recovery``: each failure onset (a ``recovery.worker_death``,
      or the day driver's ``day.rack_kill`` which precedes detection)
      until the NEXT ``recovery.generation_start`` plus a drain margin
      (the respawned fleet still owes the backlog that queued while it
      was down).
    - ``scale_transition``: around each ``scale.applied`` (the event is
      emitted at reform end, so the lead covers the drain/terminate
      that preceded it).
    - ``rollout`` / ``kv_migrate``: the logged span of each
      ``serve.swap`` / ``kv.migrate`` event plus a margin.
    - ``spike_overload``: every ``day.phase`` marker whose phase name
      contains ``spike`` (or carries ``overload`` truthy), extended by
      a drain margin — queueing theory's revenge outlives the spike.
    """
    out: "dict[str, list]" = {c: [] for c in CAUSES}
    gen_starts = [w for w, _ in
                  _walls(events_by_pid, "recovery.generation_start")]

    def _until_gen_start(wall: float) -> float:
        later = [g for g in gen_starts if g > wall]
        return (later[0] if later else wall) + recovery_drain_s

    onsets = ([w for w, _ in _walls(events_by_pid, "day.rack_kill")]
              + [w for w, _ in
                 _walls(events_by_pid, "recovery.worker_death")])
    for wall in onsets:
        out["recovery"].append((wall - recovery_backdate_s,
                                _until_gen_start(wall)))
    for wall, _ in _walls(events_by_pid, "scale.applied"):
        out["scale_transition"].append((wall - scale_lead_s,
                                        wall + scale_drain_s))
    for name, cause in (("serve.swap", "rollout"),
                        ("kv.migrate", "kv_migrate")):
        for wall, ev in _walls(events_by_pid, name):
            dur = ev.get("dur_s")
            dur = float(dur) if isinstance(dur, (int, float)) else 0.0
            out[cause].append((wall - dur - span_margin_s,
                               wall + span_margin_s))
    for ph in phase_spans(events_by_pid):
        name = str(ph.get("phase", ""))
        if "spike" in name or ph.get("overload"):
            out["spike_overload"].append(
                (ph["start"], ph["end"] + spike_drain_s))
    return {c: _merge(ws) for c, ws in out.items()}


def _merge(windows: "list[tuple]") -> "list[tuple]":
    merged: "list[list]" = []
    for lo, hi in sorted(windows):
        if merged and lo <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], hi)
        else:
            merged.append([lo, hi])
    return [tuple(w) for w in merged]


def attribute(record: dict, windows: "dict[str, list]") -> "str | None":
    """The cause of one violating record, or None (unattributed).
    Record-level evidence first (a replayed request indicts the
    preemption no matter when it completed), then window causes in
    :data:`CAUSES` priority order over the record's service interval.
    """
    rt = record.get("replayed_tokens")
    if isinstance(rt, (int, float)) and rt > 0:
        return "preempt_replay"
    wall = record.get("wall")
    if not isinstance(wall, (int, float)):
        return None
    lat = record.get("latency_s")
    start = wall - (float(lat) if isinstance(lat, (int, float)) else 0.0)
    for cause in CAUSES:
        for lo, hi in windows.get(cause, ()):
            if start <= hi and wall >= lo:
                return cause
    return None


def _phase_goodput(events_by_pid, phases: "list[dict]") -> None:
    """Annotate each phase span with the hardware-seconds and goodput
    (step-event seconds) that fell inside it, clipped per worker.

    This is the LEDGER'S goodput re-cut along the day's phase
    boundaries as a breakdown aid: the serving replay share and the
    named badput buckets stay fleet-level (the ledger is the
    authority); a phase's ``wall_s`` sums each worker's observed-span
    overlap with the phase, so mid-phase deaths shrink it honestly.
    """
    for ph in phases:
        ph["wall_s"] = 0.0
        ph["goodput_s"] = 0.0
    for pid, events in events_by_pid.items():
        if not isinstance(pid, int):
            continue
        walls = [ev["wall"] for ev in events
                 if isinstance(ev.get("wall"), (int, float))]
        if not walls:
            continue
        first, last = min(walls), max(walls)
        for ph in phases:
            ph["wall_s"] += max(0.0, min(last, ph["end"])
                                - max(first, ph["start"]))
        for ev in events:
            if ev.get("ev") not in ("train.step", "serve.step"):
                continue
            wall, dur = ev.get("wall"), ev.get("dur_s")
            if not isinstance(wall, (int, float)):
                continue
            dur = float(dur) if isinstance(dur, (int, float)) \
                and dur > 0 else 0.0
            for ph in phases:
                ph["goodput_s"] += max(
                    0.0, min(wall, ph["end"]) - max(wall - dur,
                                                    ph["start"]))
    for ph in phases:
        ph["wall_s"] = round(ph["wall_s"], 6)
        ph["goodput_s"] = round(min(ph["goodput_s"], ph["wall_s"]), 6)
        ph["goodput_frac"] = (round(ph["goodput_s"] / ph["wall_s"], 6)
                              if ph["wall_s"] > 0 else None)


def _rack_loss(events_by_pid) -> "dict | None":
    """The day's correlated-failure scorecard: kill → next generation
    (MTTR) and the restore tiers the reformed trainers reported."""
    kills = _walls(events_by_pid, "day.rack_kill")
    if not kills:
        return None
    wall, ev = kills[0]
    gen_starts = [w for w, _ in
                  _walls(events_by_pid, "recovery.generation_start")
                  if w > wall]
    restores = [(w, e) for w, e in
                _walls(events_by_pid, "recovery.restore_tier")
                if w > wall]
    tiers = sorted({str(e.get("tier")) for _, e in restores},
                   key=lambda t: TIER_RANK.get(t, 99))
    worst = max((TIER_RANK.get(str(e.get("tier")), 99)
                 for _, e in restores), default=None)
    deaths = [e for w, e in
              _walls(events_by_pid, "recovery.worker_death") if w >= wall]
    return {
        "domain": ev.get("domain"),
        "victims": ev.get("victims"),
        "kill_wall": wall,
        "deaths_observed": len(deaths),
        "mttr_s": (round(gen_starts[0] - wall, 6) if gen_starts
                   else None),
        "restore_tiers": tiers,
        "worst_tier_rank": worst,
        "warm": (worst is not None and worst <= TIER_RANK["peer"]),
    }


def itemize_slos(records, slos, evaluated, windows) -> float:
    """Itemize each SLO's budget spend by attributed cause: annotates
    every ``evaluated[slo.name]`` with ``by_cause`` (spends partition
    ``budget_consumed`` exactly) and ``unattributed``, and returns the
    worst unattributed share of bad records across the SLOs. Shared by
    :func:`audit_day` and ``tools/health_report.py``."""
    max_unattr = 0.0
    for slo in slos:
        res = evaluated[slo.name]
        n = max(res["requests"], 1)
        by_cause = {c: 0 for c in CAUSES}
        unattr = 0
        for r in records:
            if not slo.is_bad(r):
                continue
            cause = attribute(r, windows)
            if cause is None:
                unattr += 1
            else:
                by_cause[cause] += 1
        res["by_cause"] = {
            c: {"bad": k,
                "budget_consumed": round((k / n) / slo.error_budget, 6)}
            for c, k in by_cause.items()}
        frac = (unattr / res["bad"]) if res["bad"] else 0.0
        res["unattributed"] = {
            "bad": unattr,
            "budget_consumed": round((unattr / n) / slo.error_budget, 6),
            "frac_of_bad": round(frac, 6)}
        max_unattr = max(max_unattr, frac)
    return max_unattr


def audit_day(events_by_pid, *, slos=None,
              window_opts: "dict | None" = None) -> dict:
    """The full day audit from one run's event files
    (:func:`telemetry.events.read_run` output). Pure function of the
    logs — no in-process state, no clock reads."""
    from distributed_tensorflow_tpu.telemetry import goodput as _goodput
    from distributed_tensorflow_tpu.telemetry import slo as _slo

    ledger = _goodput.ledger_from_events(events_by_pid)
    records = day_records(events_by_pid)
    windows = cause_windows(events_by_pid, **(window_opts or {}))
    phases = phase_spans(events_by_pid)
    _phase_goodput(events_by_pid, phases)

    if slos is None:
        walls = [r["wall"] for r in records
                 if isinstance(r.get("wall"), (int, float))]
        span = (max(walls) - min(walls)) if len(walls) > 1 else 1.0
        slos = _slo.default_serving_slos(
            windows=_slo.windows_for_span(max(span, 1e-3)))
    evaluated = _slo.evaluate_records(records, slos)
    max_unattr = itemize_slos(records, slos, evaluated, windows)

    generated = max((int(e.get("generated", 0)) for _, e in
                     _walls(events_by_pid, "day.load")), default=None)
    completed = len(records)
    wall = ledger["wall_s"]
    return {
        "ledger": {
            "wall_s": round(wall, 6),
            "goodput_s": round(ledger["goodput_s"], 6),
            "goodput_frac": ledger["goodput_frac"],
            "badput_s": {b: round(v, 6)
                         for b, v in ledger["badput_s"].items()},
            "identity_error_s": round(ledger["identity_error_s"], 6),
            "identity_error_frac": (
                round(abs(ledger["identity_error_s"]) / wall, 6)
                if wall > 0 else 0.0),
            "workers": len(ledger["per_worker"]),
        },
        "slos": evaluated,
        "max_unattributed_frac": round(max_unattr, 6),
        "phases": phases,
        "rack_loss": _rack_loss(events_by_pid),
        "requests": {
            "generated": generated,
            "completed": completed,
            "dropped": (max(0, generated - completed)
                        if generated is not None else None)},
        "cause_windows": {c: [(round(lo, 6), round(hi, 6))
                              for lo, hi in ws]
                          for c, ws in windows.items()},
    }


def check_audit(audit: dict, *, identity_tol: float = 0.01,
                max_unattributed: float = 0.05,
                goodput_floor: "float | None" = None,
                require_warm_restore: bool = False,
                max_rack_mttr_s: "float | None" = None,
                require_no_drops: bool = True) -> "list[str]":
    """The day's CI gates over an :func:`audit_day` result; returns
    human-readable failures (empty = pass)."""
    fails: "list[str]" = []
    led = audit["ledger"]
    if led["identity_error_frac"] > identity_tol:
        fails.append(
            f"goodput identity broken: |wall - (goodput + badput)| = "
            f"{led['identity_error_s']:.3f}s is "
            f"{led['identity_error_frac']:.1%} of {led['wall_s']:.3f}s "
            f"hardware-seconds (tolerance {identity_tol:.0%})")
    if goodput_floor is not None and (
            led["goodput_frac"] is None
            or led["goodput_frac"] < goodput_floor):
        fails.append(f"day goodput_frac {led['goodput_frac']} below "
                     f"floor {goodput_floor}")
    for name, res in audit["slos"].items():
        frac = res.get("unattributed", {}).get("frac_of_bad", 0.0)
        if frac > max_unattributed:
            fails.append(
                f"SLO {name}: {frac:.1%} of budget spend unattributed "
                f"({res['unattributed']['bad']}/{res['bad']} bad "
                f"records match no cause window; cap "
                f"{max_unattributed:.0%})")
    rack = audit.get("rack_loss")
    if require_warm_restore:
        if rack is None:
            fails.append("no rack loss in the run (day scenario "
                         "requires one)")
        elif not rack["restore_tiers"]:
            fails.append("rack loss but no recovery.restore_tier "
                         "events — restore path unobserved")
        elif not rack["warm"]:
            fails.append(
                f"rack loss fell through the warm tiers: restored "
                f"from {rack['restore_tiers']} (placement must keep "
                f"host/peer recoverable)")
    if rack is not None and max_rack_mttr_s is not None:
        if rack["mttr_s"] is None or rack["mttr_s"] > max_rack_mttr_s:
            fails.append(f"rack-loss MTTR {rack['mttr_s']}s over "
                         f"budget {max_rack_mttr_s}s")
    req = audit["requests"]
    if require_no_drops and req["dropped"]:
        fails.append(f"{req['dropped']} requests dropped "
                     f"({req['generated']} generated, "
                     f"{req['completed']} completed)")
    return fails
