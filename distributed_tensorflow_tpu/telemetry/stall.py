"""Stall detection: no step completed within k x trailing median.

Layered on :class:`coordinator.watchdog.WatchDog`: the training driver
reports each completed step (:meth:`StallDetector.step_completed`); the
detector keeps a trailing window of step intervals and arms the
watchdog with ``factor`` x the median interval. If no step completes
within that budget the watchdog triggers and the detector emits a
structured ``stall.suspected`` event naming the suspect worker —
non-fatal (escalation rides the WatchDog ``on_triggered`` contract: a
raising callback never kills the watch loop, and training continues).

Suspect attribution, in order of evidence quality:

1. a dispatch lane currently blocked in ``RemoteLane.wait`` (the
   ``coordinator/dispatch/waiting/<wid>`` gauges set by
   remote_dispatch) — the worker the coordinator is literally waiting
   on right now;
2. from the last fleet rollup (aggregate.FleetAggregator): the worker
   with the fewest completed steps, else the stalest publisher.
"""

from __future__ import annotations

import collections
import statistics
import sys
import threading
import time

from distributed_tensorflow_tpu.telemetry import events as _events
from distributed_tensorflow_tpu.telemetry import registry as _registry

#: Gauge-name prefix remote_dispatch sets while a lane blocks on a
#: worker's result (value = monotonic time the wait started).
WAITING_GAUGE_PREFIX = "coordinator/dispatch/waiting/"


def suspect_worker(rollup: dict | None = None,
                   reg=None,
                   step_metric: str = "training/steps_completed"):
    """Best-evidence suspect: ``(worker_id, reason)`` or (None, "")."""
    reg = reg or _registry.get_registry()
    # 1. lanes blocked in dispatch right now: oldest wait wins
    oldest: tuple[float, str] | None = None
    for name in reg.names():
        if not name.startswith(WAITING_GAUGE_PREFIX):
            continue
        g = reg.get(name)
        since = g.value if g is not None else None
        if isinstance(since, (int, float)):
            wid = name[len(WAITING_GAUGE_PREFIX):]
            if oldest is None or since < oldest[0]:
                oldest = (since, wid)
    if oldest is not None:
        age = time.monotonic() - oldest[0]
        return oldest[1], (f"dispatch lane blocked on worker "
                           f"{oldest[1]} for {age:.1f}s")
    # 2. fleet rollup: fewest completed steps, else stalest publisher
    if rollup:
        steps = (rollup.get("metrics", {}).get(step_metric, {})
                 .get("per_worker") or {})
        numeric = {p: v for p, v in steps.items()
                   if isinstance(v, (int, float))}
        if len(numeric) > 1 and len(set(numeric.values())) > 1:
            wid = min(numeric, key=numeric.get)
            return wid, (f"worker {wid} at step {numeric[wid]} "
                         f"(fleet max {max(numeric.values())})")
        workers = rollup.get("workers") or {}
        walls = {p: w.get("wall") for p, w in workers.items()
                 if isinstance(w.get("wall"), (int, float))}
        if walls:
            wid = min(walls, key=walls.get)
            return wid, (f"worker {wid} last published "
                         f"{time.time() - walls[wid]:.1f}s ago")
    return None, ""


class StallDetector:
    """Adaptive no-progress detector for a step loop.

    ::

        detector = StallDetector(factor=4.0, rollup_fn=lambda:
                                 aggregator.last_rollup)
        for step in range(n):
            state = train_step(state)
            detector.step_completed(step)
        detector.stop()

    Until ``min_steps`` intervals are observed the watchdog is armed
    with ``warmup_timeout_s`` (generous: compile time, first-batch
    staging); after that the budget tracks ``factor`` x trailing median
    step time, clamped to [``min_timeout_s``, ``warmup_timeout_s``].
    Triggers emit ``stall.suspected`` (telemetry event log), increment
    ``coordinator/stalls_suspected``, and call ``on_stall(info)``.
    """

    def __init__(self, factor: float = 4.0, window: int = 32,
                 min_steps: int = 5, min_timeout_s: float = 1.0,
                 warmup_timeout_s: float = 300.0,
                 rollup_fn=None, on_stall=None, reg=None,
                 output=sys.stderr):
        self.factor = factor
        self.min_steps = min_steps
        self.min_timeout_s = min_timeout_s
        self.warmup_timeout_s = warmup_timeout_s
        self.rollup_fn = rollup_fn
        self.on_stall = on_stall
        self.reg = reg or _registry.get_registry()
        self._intervals: collections.deque = collections.deque(
            maxlen=window)
        self._last_step_t: float | None = None
        self._last_step = None
        self._lock = threading.Lock()
        self._stall_counter = self.reg.counter(
            "coordinator/stalls_suspected",
            "stall.suspected events emitted")
        # deferred import: the coordinator package imports telemetry, so
        # binding WatchDog at module-import time would be a cycle
        from distributed_tensorflow_tpu.coordinator.watchdog import WatchDog
        self._watchdog = WatchDog(timeout=warmup_timeout_s,
                                  on_triggered=self._triggered,
                                  output=output)

    @property
    def triggered_count(self) -> int:
        return self._watchdog.triggered_count

    def median_step_s(self) -> float | None:
        with self._lock:
            if len(self._intervals) < self.min_steps:
                return None
            return statistics.median(self._intervals)

    def step_completed(self, step=None, dur_s: float | None = None):
        """Report one completed step; re-arms the watchdog budget."""
        now = time.monotonic()
        with self._lock:
            if self._last_step_t is not None:
                self._intervals.append(
                    dur_s if dur_s is not None else now - self._last_step_t)
            elif dur_s is not None:
                self._intervals.append(dur_s)
            self._last_step_t = now
            self._last_step = step
            enough = len(self._intervals) >= self.min_steps
            median = (statistics.median(self._intervals)
                      if enough else None)
        if median is not None:
            budget = min(self.warmup_timeout_s,
                         max(self.min_timeout_s, self.factor * median))
            self._watchdog.set_timeout(budget)
        self._watchdog.report_activity()

    def _triggered(self):
        median = self.median_step_s()
        with self._lock:
            last_t, last_step = self._last_step_t, self._last_step
        stalled_s = (time.monotonic() - last_t) if last_t else None
        rollup = None
        if self.rollup_fn is not None:
            try:
                rollup = self.rollup_fn()
            except Exception:
                rollup = None
        wid, reason = suspect_worker(rollup, self.reg)
        # the badput class the blocked time is accruing to (the live
        # goodput ledger's current bucket; "idle" when no ledger is
        # active) — a stall names both the blocked lane AND the bucket
        # it is pricing into
        from distributed_tensorflow_tpu.telemetry import goodput
        info = {"last_step": last_step,
                "stalled_s": round(stalled_s, 3) if stalled_s else None,
                "median_step_s": (round(median, 6)
                                  if median is not None else None),
                "factor": self.factor,
                "suspect_worker": wid, "suspect_reason": reason,
                "badput_bucket": goodput.accruing_bucket()}
        self._stall_counter.increment()
        _events.event("stall.suspected", **info)
        if self.on_stall is not None:
            self.on_stall(info)         # WatchDog guards raises

    def stop(self):
        self._watchdog.stop()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
