"""Cross-host metric aggregation over the coordination KV store.

Workers publish periodic registry snapshots; the coordinator merges
them into fleet rollups and emits them to TensorBoard:

    worker p                         coordinator (process 0)
    --------                         -----------------------
    MetricsPublisher thread          FleetAggregator thread
    snap = registry.snapshot()       for p in worker_ids:
    kv[telemetry/snap/p] = json        read kv[telemetry/snap/p]
      (every interval_s)             rollup = merge(snapshots)
                                     SummaryWriter <- fleet/<name>/<stat>

Legacy-jaxlib discipline (see cluster/coordination.py and the memory
notes): snapshots are JSON **strings** (the string KV API is the only
one safe in every read direction on jaxlib<=0.4.36), the coordinator
reads them with enumerated per-process point reads (``try_get`` per
worker id — NEVER a directory read, which hangs off-host on that
vintage), and keys are overwritten in place, never deleted-and-recreated.

Rollup semantics per instrument type:

- counter    -> ``sum`` across processes, ``max``, per-worker values
- gauge      -> per-worker values (+ ``max``/``mean`` when numeric)
- histogram/timer -> ``count``/``sum`` summed; ``max`` of maxes;
  ``p50`` = count-weighted median of per-worker p50s (approximate —
  workers export percentiles, not samples); ``p95`` = max of per-worker
  p95s (conservative: fleet tail latency is at least the worst worker's)
"""

from __future__ import annotations

import json
import threading
import time

from distributed_tensorflow_tpu.telemetry import registry as _registry

_SNAP_PREFIX = "dtx_telemetry/snap"


def _snap_key(process_id: int) -> str:
    return f"{_SNAP_PREFIX}/{process_id}"


def publish_snapshot(agent=None, reg=None,
                     process_id: int | None = None, seq: int = 0) -> dict:
    """Publish this process's registry snapshot to the coordination KV.
    Returns the published payload."""
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    agent = agent or coordination_service()
    reg = reg or _registry.get_registry()
    pid = process_id if process_id is not None else agent.process_id
    payload = {"pid": pid, "seq": seq, "wall": time.time(),
               "metrics": reg.snapshot()}
    agent.key_value_set(_snap_key(pid), json.dumps(payload))
    return payload


def read_snapshots(agent=None, worker_ids=None) -> dict:
    """Enumerated point reads of every process's latest snapshot:
    ``{pid: payload}`` (absent processes omitted)."""
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    agent = agent or coordination_service()
    if worker_ids is None:
        worker_ids = range(agent.num_processes)
    out: dict[int, dict] = {}
    for pid in worker_ids:
        raw = agent.key_value_try_get(_snap_key(pid))
        if raw is None:
            continue
        try:
            out[pid] = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue                    # torn publish: take the next one
    return out


def _weighted_median(pairs: "list[tuple[float, float]]") -> float | None:
    """(value, weight) pairs -> weighted median."""
    pairs = sorted(p for p in pairs if p[0] is not None)
    if not pairs:
        return None
    total = sum(w for _, w in pairs) or len(pairs)
    acc = 0.0
    for v, w in pairs:
        acc += w if w else 1.0
        if acc * 2 >= total:
            return v
    return pairs[-1][0]


def merge_rollup(snapshots: "dict[int, dict]") -> dict:
    """Merge per-process snapshot payloads into one fleet rollup:
    ``{"workers": {...}, "metrics": {name: {stat: value}}}``."""
    per_metric: dict[str, dict[int, dict]] = {}
    workers: dict[int, dict] = {}
    for pid, payload in snapshots.items():
        workers[pid] = {"seq": payload.get("seq"),
                        "wall": payload.get("wall")}
        for name, entry in (payload.get("metrics") or {}).items():
            per_metric.setdefault(name, {})[pid] = entry

    metrics: dict[str, dict] = {}
    for name, by_pid in sorted(per_metric.items()):
        kinds = {e.get("type") for e in by_pid.values()}
        kind = kinds.pop() if len(kinds) == 1 else "gauge"
        out: dict = {"type": kind}
        if kind == "counter":
            vals = {p: e.get("value", 0) for p, e in by_pid.items()}
            out["sum"] = sum(vals.values())
            out["max"] = max(vals.values())
            out["per_worker"] = vals
        elif kind in ("histogram", "timer"):
            counts = {p: e.get("count", 0) for p, e in by_pid.items()}
            out["count"] = sum(counts.values())
            out["sum"] = round(sum(e.get("sum") or 0.0
                                   for e in by_pid.values()), 9)
            maxes = [e.get("max") for e in by_pid.values()
                     if e.get("max") is not None]
            if maxes:
                out["max"] = max(maxes)
            p50 = _weighted_median(
                [(e.get("p50"), counts[p]) for p, e in by_pid.items()
                 if e.get("p50") is not None])
            if p50 is not None:
                out["p50"] = p50
            p95s = [e.get("p95") for e in by_pid.values()
                    if e.get("p95") is not None]
            if p95s:
                out["p95"] = max(p95s)
            out["per_worker_count"] = counts
        else:                            # gauge
            vals = {p: e.get("value") for p, e in by_pid.items()}
            out["per_worker"] = vals
            nums = [v for v in vals.values()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)]
            if nums:
                out["max"] = max(nums)
                out["mean"] = sum(nums) / len(nums)
        metrics[name] = out
    return {"workers": workers, "metrics": metrics}


def collect_rollup(agent=None, worker_ids=None) -> dict:
    """One-shot: read every process's snapshot and merge."""
    return merge_rollup(read_snapshots(agent, worker_ids))


def phase_summary(rollup: dict) -> dict:
    """Fleet-wide step-phase view of a rollup: the per-step phase
    fractions StepTelemetry publishes (``training/phase/<name>_frac``
    histograms) as count-weighted p50s, plus the worst worker's p95 and
    the mean/min overlap efficiency across workers. The fleet answer to
    "is anyone input/comm/checkpoint-bound?" without reading any
    worker's event file."""
    metrics = rollup.get("metrics", {})
    phases: dict = {}
    for name, entry in metrics.items():
        if not name.startswith("training/phase/") \
                or not name.endswith("_frac"):
            continue
        phase = name[len("training/phase/"):-len("_frac")]
        phases[phase] = {k: entry[k] for k in ("p50", "p95", "count")
                        if k in entry}
    overlap = metrics.get("training/overlap_eff", {})
    vals = [v for v in (overlap.get("per_worker") or {}).values()
            if isinstance(v, (int, float))]
    return {"phases": phases,
            "overlap_eff": {"mean": sum(vals) / len(vals),
                            "min": min(vals)} if vals else None}


def rollup_scalars(rollup: dict) -> dict:
    """Flatten a rollup into TensorBoard scalar tags:
    ``fleet/<metric>/<stat> -> float``."""
    out: dict[str, float] = {}
    for name, entry in rollup.get("metrics", {}).items():
        for stat in ("sum", "max", "mean", "p50", "p95", "count"):
            v = entry.get(stat)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"fleet/{name}/{stat}"] = float(v)
    return out


class MetricsPublisher:
    """Worker-side background thread publishing registry snapshots on a
    period. ``stop()`` publishes one final snapshot so short runs are
    never invisible to the coordinator."""

    def __init__(self, agent=None, reg=None,
                 interval_s: float = 2.0, process_id: int | None = None):
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        self.agent = agent or coordination_service()
        self.reg = reg or _registry.get_registry()
        self.interval_s = interval_s
        self.process_id = (process_id if process_id is not None
                           else self.agent.process_id)
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtx-telemetry-publish")
        self._thread.start()

    def _publish(self):
        self._seq += 1
        try:
            publish_snapshot(self.agent, self.reg,
                             process_id=self.process_id, seq=self._seq)
        except Exception:
            pass                        # service going down mid-run

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self._publish()

    def stop(self):
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._publish()             # final flush

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class FleetAggregator:
    """Coordinator-side background thread: collect per-process
    snapshots, merge into a fleet rollup, emit scalars to TensorBoard
    (utils/summary.SummaryWriter). ``last_rollup`` is the stall
    detector's source for naming the slowest worker."""

    def __init__(self, worker_ids, agent=None, interval_s: float = 2.0,
                 summary_writer=None, step_metric: str =
                 "training/steps_completed"):
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        self.agent = agent or coordination_service()
        self.worker_ids = list(worker_ids)
        self.interval_s = interval_s
        self.writer = summary_writer
        self.step_metric = step_metric
        self._rollup_lock = threading.Lock()
        self._last_rollup: dict | None = None
        self._n = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtx-telemetry-aggregate")
        self._thread.start()

    @property
    def last_rollup(self) -> dict | None:
        with self._rollup_lock:
            return self._last_rollup

    def export_live(self, **exporter_kwargs):
        """Start a :class:`telemetry.exporter.MetricsExporter` whose
        scrape merges this aggregator's latest fleet rollup (per-worker
        ``worker="<pid>"`` labels) — ONE scrape of the coordinator sees
        every worker. Caller owns ``.stop()``."""
        from distributed_tensorflow_tpu.telemetry import exporter
        return exporter.MetricsExporter(
            rollup_fn=lambda: self.last_rollup, **exporter_kwargs)

    def collect_once(self) -> dict:
        rollup = collect_rollup(self.agent, self.worker_ids)
        with self._rollup_lock:
            self._last_rollup = rollup
            self._n += 1
            n = self._n
        if self.writer is not None and rollup.get("metrics"):
            # global step for the scalar series: the fleet-max completed
            # step when published, else the rollup ordinal
            step_entry = rollup["metrics"].get(self.step_metric, {})
            step = int(step_entry.get("max", n) or n)
            try:
                self.writer.scalars(rollup_scalars(rollup), step=step)
                self.writer.flush()
            except Exception:
                pass
        return rollup

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.collect_once()
            except Exception:
                pass                    # service teardown mid-run

    def stop(self):
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5.0)
            try:
                self.collect_once()     # final rollup
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
