"""Cross-host metric aggregation over the coordination KV store.

Workers publish periodic registry snapshots; the coordinator merges
them into fleet rollups and emits them to TensorBoard:

    worker p                         coordinator (process 0)
    --------                         -----------------------
    MetricsPublisher thread          FleetAggregator thread
    snap = registry.snapshot()       for p in worker_ids:
    kv[telemetry/snap/p] = json        read kv[telemetry/snap/p]
      (every interval_s)             rollup = merge(snapshots)
                                     SummaryWriter <- fleet/<name>/<stat>

Legacy-jaxlib discipline (see cluster/coordination.py and the memory
notes): snapshots are JSON **strings** (the string KV API is the only
one safe in every read direction on jaxlib<=0.4.36), the coordinator
reads them with enumerated per-process point reads (``try_get`` per
worker id — NEVER a directory read, which hangs off-host on that
vintage), and keys are overwritten in place, never deleted-and-recreated.

Rollup semantics per instrument type:

- counter    -> ``sum`` across processes, ``max``, per-worker values
- gauge      -> per-worker values (+ ``max``/``mean`` when numeric)
- histogram/timer -> ``count``/``sum`` summed; ``max`` of maxes;
  ``p50`` = count-weighted median of per-worker p50s (approximate —
  workers export percentiles, not samples); ``p95`` = max of per-worker
  p95s (conservative: fleet tail latency is at least the worst worker's)
"""

from __future__ import annotations

import json
import threading
import time

from distributed_tensorflow_tpu.telemetry import registry as _registry

_SNAP_PREFIX = "dtx_telemetry/snap"
_TREE_PREFIX = "dtx_telemetry/tree"


def _snap_key(process_id: int) -> str:
    return f"{_SNAP_PREFIX}/{process_id}"


def _tree_key(level: int, node: int) -> str:
    return f"{_TREE_PREFIX}/{level}/{node}"


def publish_snapshot(agent=None, reg=None,
                     process_id: int | None = None, seq: int = 0) -> dict:
    """Publish this process's registry snapshot to the coordination KV.
    Returns the published payload."""
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    agent = agent or coordination_service()
    reg = reg or _registry.get_registry()
    pid = process_id if process_id is not None else agent.process_id
    payload = {"pid": pid, "seq": seq, "wall": time.time(),
               "metrics": reg.snapshot()}
    agent.key_value_set(_snap_key(pid), json.dumps(payload))
    return payload


def read_snapshots(agent=None, worker_ids=None) -> dict:
    """Enumerated point reads of every process's latest snapshot:
    ``{pid: payload}`` (absent processes omitted)."""
    from distributed_tensorflow_tpu.cluster.coordination import (
        coordination_service)
    agent = agent or coordination_service()
    if worker_ids is None:
        worker_ids = range(agent.num_processes)
    out: dict[int, dict] = {}
    for pid in worker_ids:
        raw = agent.key_value_try_get(_snap_key(pid))
        if raw is None:
            continue
        try:
            out[pid] = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue                    # torn publish: take the next one
    return out


def _weighted_median(pairs: "list[tuple[float, float]]") -> float | None:
    """(value, weight) pairs -> weighted median."""
    pairs = sorted(p for p in pairs if p[0] is not None)
    if not pairs:
        return None
    total = sum(w for _, w in pairs) or len(pairs)
    acc = 0.0
    for v, w in pairs:
        acc += w if w else 1.0
        if acc * 2 >= total:
            return v
    return pairs[-1][0]


def merge_rollup(snapshots: "dict[int, dict]") -> dict:
    """Merge per-process snapshot payloads into one fleet rollup:
    ``{"workers": {...}, "metrics": {name: {stat: value}}}``."""
    per_metric: dict[str, dict[int, dict]] = {}
    workers: dict[int, dict] = {}
    for pid, payload in snapshots.items():
        workers[pid] = {"seq": payload.get("seq"),
                        "wall": payload.get("wall")}
        for name, entry in (payload.get("metrics") or {}).items():
            per_metric.setdefault(name, {})[pid] = entry

    metrics: dict[str, dict] = {}
    for name, by_pid in sorted(per_metric.items()):
        kinds = {e.get("type") for e in by_pid.values()}
        kind = kinds.pop() if len(kinds) == 1 else "gauge"
        out: dict = {"type": kind}
        if kind == "counter":
            vals = {p: e.get("value", 0) for p, e in by_pid.items()}
            out["sum"] = sum(vals.values())
            out["max"] = max(vals.values())
            out["per_worker"] = vals
        elif kind in ("histogram", "timer"):
            counts = {p: e.get("count", 0) for p, e in by_pid.items()}
            out["count"] = sum(counts.values())
            out["sum"] = round(sum(e.get("sum") or 0.0
                                   for e in by_pid.values()), 9)
            maxes = [e.get("max") for e in by_pid.values()
                     if e.get("max") is not None]
            if maxes:
                out["max"] = max(maxes)
            p50 = _weighted_median(
                [(e.get("p50"), counts[p]) for p, e in by_pid.items()
                 if e.get("p50") is not None])
            if p50 is not None:
                out["p50"] = p50
            p95s = [e.get("p95") for e in by_pid.values()
                    if e.get("p95") is not None]
            if p95s:
                out["p95"] = max(p95s)
            out["per_worker_count"] = counts
        else:                            # gauge
            vals = {p: e.get("value") for p, e in by_pid.items()}
            out["per_worker"] = vals
            nums = [v for v in vals.values()
                    if isinstance(v, (int, float))
                    and not isinstance(v, bool)]
            if nums:
                out["max"] = max(nums)
                out["mean"] = sum(nums) / len(nums)
        metrics[name] = out
    return {"workers": workers, "metrics": metrics}


def collect_rollup(agent=None, worker_ids=None) -> dict:
    """One-shot: read every process's snapshot and merge."""
    return merge_rollup(read_snapshots(agent, worker_ids))


# ---------------------------------------------------------------------------
# Tree-structured rollups (fleet scale)
# ---------------------------------------------------------------------------
# The flat scheme above has the coordinator point-read every worker's
# snapshot key — O(N) KV ops on ONE node per rollup tick, the
# control-plane bottleneck the fleet harness (testing/fleet_sim.py)
# exposes first. The tree scheme spreads that fan-in over reducer
# workers: leaves keep publishing their own snapshot key exactly as
# before, but designated reducers (the lowest pid of each fanout-sized
# group) union their group's snapshots into one *partial* key per tree
# node, level by level, and the coordinator reads only the ROOT key.
# No single node ever touches more than ``fanout`` keys per tick
# (the root reducer pays fanout ops per level: O(fanout·log_F N)), and
# the merged output is BIT-IDENTICAL to the flat path at every depth —
# partials carry the union of leaf payloads, so the final merge is the
# same ``merge_rollup`` over the same per-worker entries, just routed
# through fewer reads at the top. (The trade is payload size, not op
# count: a root partial aggregates every worker's snapshot. KV ops —
# RPC count — are what bound the control plane at small-snapshot
# sizes; see README "Fleet scale".)
#
# Freshness: a value reaches the root after every level between has
# republished — rollup latency is O(depth × publish interval), which
# bench.py --fleet measures as snapshot age at collect time.
#
# Legacy discipline unchanged: partials are JSON strings, written in
# place, read with enumerated point reads; a dead reducer's partial
# simply goes stale (its subtree's freshness degrades until the
# supervisor reforms the cluster — the same failure surface sharded
# heartbeats have, see resilience/heartbeats.py).


class RollupTopology:
    """The fanout-F reduction tree over worker ids.

    Level 0 groups ``fanout`` consecutive leaves per node; each higher
    level groups ``fanout`` nodes of the level below, up to a single
    root. The reducer of a node is the lowest pid under it — so pid 0
    is the root reducer, and a reducer's duties nest (it reduces its
    group at every level it anchors).
    """

    def __init__(self, num_workers: int, fanout: int = 16):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        if fanout < 2:
            raise ValueError("fanout must be >= 2")
        self.num_workers = num_workers
        self.fanout = fanout
        #: nodes per level, leaves upward: levels[0] = ceil(N/F), ...
        self.level_sizes: list[int] = []
        n = num_workers
        while True:
            n = -(-n // fanout)           # ceil division
            self.level_sizes.append(n)
            if n == 1:
                break

    @property
    def depth(self) -> int:
        return len(self.level_sizes)

    @property
    def root(self) -> "tuple[int, int]":
        return (self.depth - 1, 0)

    def leaf_children(self, node: int) -> range:
        """Worker pids under level-0 node ``node``."""
        lo = node * self.fanout
        return range(lo, min(lo + self.fanout, self.num_workers))

    def node_children(self, level: int, node: int) -> range:
        """Child node indices (at ``level - 1``) of a level>=1 node."""
        lo = node * self.fanout
        return range(lo, min(lo + self.fanout,
                             self.level_sizes[level - 1]))

    def reducer_of(self, level: int, node: int) -> int:
        """The pid responsible for publishing this node's partial."""
        return node * self.fanout ** (level + 1)

    def duties(self, pid: int) -> "list[tuple[int, int]]":
        """The (level, node) partials ``pid`` publishes, leaves upward
        (ascending level — a reducer folds its own lower partial into
        the next level's on the same tick)."""
        out = []
        for level, size in enumerate(self.level_sizes):
            step = self.fanout ** (level + 1)
            if pid % step != 0:
                break                     # not a reducer above this level
            node = pid // step
            if node < size:
                out.append((level, node))
        return out


def publish_tree_partial(agent, level: int, node: int,
                         snapshots: "dict[int, dict]"):
    """Publish the union-of-leaf-snapshots partial for one tree node."""
    agent.key_value_set(
        _tree_key(level, node),
        json.dumps({"wall": time.time(),
                    "snapshots": {str(p): s
                                  for p, s in snapshots.items()}}))


def read_tree_partial(agent, level: int, node: int) -> "dict[int, dict]":
    """The leaf snapshots accumulated under one tree node ({} when the
    partial is absent or torn)."""
    raw = agent.key_value_try_get(_tree_key(level, node))
    if raw is None:
        return {}
    try:
        payload = json.loads(raw.decode())
        return {int(p): s
                for p, s in (payload.get("snapshots") or {}).items()}
    except (ValueError, UnicodeDecodeError):
        return {}                         # torn publish: next tick heals


def run_duties(agent, topology: RollupTopology, pid: int):
    """Execute ``pid``'s reducer duties for one tick: for each anchored
    node (leaves upward), union the children's payloads and republish
    the partial. Missing children (dead or not-yet-published workers)
    are skipped — their last partial simply stays stale."""
    for level, node in topology.duties(pid):
        if level == 0:
            snaps = read_snapshots(agent, topology.leaf_children(node))
        else:
            snaps = {}
            for child in topology.node_children(level, node):
                snaps.update(read_tree_partial(agent, level - 1, child))
        if snaps:
            publish_tree_partial(agent, level, node, snaps)


def collect_rollup_tree(agent, topology: RollupTopology) -> dict:
    """Coordinator-side collect: ONE root read instead of N leaf reads;
    the merge itself is the exact flat-path ``merge_rollup`` over the
    union the tree accumulated (bit-identical output at any depth)."""
    level, node = topology.root
    return merge_rollup(read_tree_partial(agent, level, node))


def phase_summary(rollup: dict) -> dict:
    """Fleet-wide step-phase view of a rollup: the per-step phase
    fractions StepTelemetry publishes (``training/phase/<name>_frac``
    histograms) as count-weighted p50s, plus the worst worker's p95 and
    the mean/min overlap efficiency across workers. The fleet answer to
    "is anyone input/comm/checkpoint-bound?" without reading any
    worker's event file."""
    metrics = rollup.get("metrics", {})
    phases: dict = {}
    for name, entry in metrics.items():
        if not name.startswith("training/phase/") \
                or not name.endswith("_frac"):
            continue
        phase = name[len("training/phase/"):-len("_frac")]
        phases[phase] = {k: entry[k] for k in ("p50", "p95", "count")
                        if k in entry}
    overlap = metrics.get("training/overlap_eff", {})
    vals = [v for v in (overlap.get("per_worker") or {}).values()
            if isinstance(v, (int, float))]
    return {"phases": phases,
            "overlap_eff": {"mean": sum(vals) / len(vals),
                            "min": min(vals)} if vals else None}


def rollup_scalars(rollup: dict) -> dict:
    """Flatten a rollup into TensorBoard scalar tags:
    ``fleet/<metric>/<stat> -> float``."""
    out: dict[str, float] = {}
    for name, entry in rollup.get("metrics", {}).items():
        for stat in ("sum", "max", "mean", "p50", "p95", "count"):
            v = entry.get(stat)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                out[f"fleet/{name}/{stat}"] = float(v)
    return out


class MetricsPublisher:
    """Worker-side background thread publishing registry snapshots on a
    period. ``stop()`` publishes one final snapshot so short runs are
    never invisible to the coordinator.

    With ``tree`` set (a :class:`RollupTopology`), the publisher also
    executes this process's reducer duties each tick — the worker-side
    half of the tree-structured rollup path."""

    def __init__(self, agent=None, reg=None,
                 interval_s: float = 2.0, process_id: int | None = None,
                 tree: "RollupTopology | None" = None):
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        self.agent = agent or coordination_service()
        self.reg = reg or _registry.get_registry()
        self.interval_s = interval_s
        self.process_id = (process_id if process_id is not None
                           else self.agent.process_id)
        self.tree = tree
        self._seq = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtx-telemetry-publish")
        self._thread.start()

    def _publish(self):
        self._seq += 1
        try:
            publish_snapshot(self.agent, self.reg,
                             process_id=self.process_id, seq=self._seq)
            if self.tree is not None:
                run_duties(self.agent, self.tree, self.process_id)
        except Exception:
            pass                        # service going down mid-run

    def _run(self):
        while not self._stop.wait(self.interval_s):
            self._publish()

    def stop(self):
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5.0)
            self._publish()             # final flush

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class FleetAggregator:
    """Coordinator-side background thread: collect per-process
    snapshots, merge into a fleet rollup, emit scalars to TensorBoard
    (utils/summary.SummaryWriter). ``last_rollup`` is the stall
    detector's source for naming the slowest worker."""

    def __init__(self, worker_ids, agent=None, interval_s: float = 2.0,
                 summary_writer=None, step_metric: str =
                 "training/steps_completed",
                 tree: "RollupTopology | None" = None):
        from distributed_tensorflow_tpu.cluster.coordination import (
            coordination_service)
        self.agent = agent or coordination_service()
        self.worker_ids = list(worker_ids)
        self.tree = tree
        self.interval_s = interval_s
        self.writer = summary_writer
        self.step_metric = step_metric
        self._rollup_lock = threading.Lock()
        self._last_rollup: dict | None = None
        self._n = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dtx-telemetry-aggregate")
        self._thread.start()

    @property
    def last_rollup(self) -> dict | None:
        with self._rollup_lock:
            return self._last_rollup

    def export_live(self, **exporter_kwargs):
        """Start a :class:`telemetry.exporter.MetricsExporter` whose
        scrape merges this aggregator's latest fleet rollup (per-worker
        ``worker="<pid>"`` labels) — ONE scrape of the coordinator sees
        every worker. Caller owns ``.stop()``."""
        from distributed_tensorflow_tpu.telemetry import exporter
        return exporter.MetricsExporter(
            rollup_fn=lambda: self.last_rollup, **exporter_kwargs)

    def collect_once(self) -> dict:
        rollup = (collect_rollup_tree(self.agent, self.tree)
                  if self.tree is not None
                  else collect_rollup(self.agent, self.worker_ids))
        with self._rollup_lock:
            self._last_rollup = rollup
            self._n += 1
            n = self._n
        if self.writer is not None and rollup.get("metrics"):
            # global step for the scalar series: the fleet-max completed
            # step when published, else the rollup ordinal
            step_entry = rollup["metrics"].get(self.step_metric, {})
            step = int(step_entry.get("max", n) or n)
            try:
                self.writer.scalars(rollup_scalars(rollup), step=step)
                self.writer.flush()
            except Exception:
                pass
        return rollup

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.collect_once()
            except Exception:
                pass                    # service teardown mid-run

    def stop(self):
        if not self._stop.is_set():
            self._stop.set()
            self._thread.join(timeout=5.0)
            try:
                self.collect_once()     # final rollup
            except Exception:
                pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
